"""tpulint rules: trace-safety, sync-schedule, and state-contract checks.

Rule catalog (codes are stable API — tests, suppressions, and the CI gate
key off them):

====== ======================= ==========================================================
code   name                    what it rejects
====== ======================= ==========================================================
TPL101 host-transfer           ``.item()``/``.tolist()``/``float()``/``int()``/``bool()``/
                               ``len()``/``np.asarray``/``jax.device_get`` applied to a
                               traced value in ``update()``-reachable code
TPL102 traced-branch           ``if``/``while``/``assert``/ternary/bool-op/``range`` on a
                               traced value in ``update()``-reachable code
TPL104 host-telemetry          a ``telemetry.spans``/``telemetry.instruments`` call (span
                               opened, counter bumped) in ``update()``-reachable code —
                               host-side effects that run at trace time only under jit
                               (and re-run on every retrace); instrument the runtime
                               seams instead
TPL105 host-health-read        a host-SYNCING ``telemetry.health`` read (``summarize``/
                               ``publish_health``/``release_health``) in ``update()``-
                               reachable code — it ``device_get``\\ s the probe counters,
                               forcing a device sync per step; the trace-safe probe
                               (``probe_tree``/``probe_packed``) belongs in the step
                               program, the READ belongs on the compute()/stats() seam
TPL106 serving-layer           (a) a ``telemetry.serve``/``telemetry.slo`` entry point
                               (admin server start, SLO engine) reachable from
                               ``update()`` — the serving plane lives beside the stream,
                               never inside a step; (b) a BLOCKING device read
                               (``jax.device_get``/``block_until_ready``/``.item()``/
                               ``health.summarize``) reachable from an admin HTTP
                               handler (``do_GET``-family methods of a
                               ``BaseHTTPRequestHandler``) or an SLO sampler loop — a
                               scrape must never synchronize with an in-flight dispatch
TPL107 backbone-in-update      backbone construction or weight placement (``lpips_backbone``/
                               ``load_inception_params``/``inception_feature_extractor``/
                               ``backbones.get_backbone``, or a ``jax.device_put`` of a
                               param/weight tree) in ``update()``-reachable code — resident
                               weights are acquired ONCE per process through the backbone
                               registry at metric construction; in a step they re-place
                               per call (or per retrace under jit).  Acquire in
                               ``__init__``, dispatch the handle in ``update()``
TPL108 stale-residency-read    a local caching a tenant's device residency
                               (``<tenant>.state``/``<tenant>.device_health``) used after a
                               hibernation point (``hibernate``/``sweep_lifecycle``/
                               ``enforce_budget``/``ensure_resident``/``revive``/
                               ``maybe_hibernate``) without re-reading — the lifecycle
                               manager may have spilled the tenant and dropped those
                               device buffers between bind and use.  Hold the manager's
                               ``residency_lock`` across read *and* use, or re-read after
                               the point
TPL109 stale-routing-read      a local caching a tenant's rank placement (a routing
                               ``.owner(...)``/``.natural_owner(...)`` read or an
                               ``owner_rank`` attribute) used after a migration seam
                               (``migrate``/``migrate_tenant``/``commit_migration``/
                               ``rebalance``/``resize``/``recover_handoffs``/
                               ``reassign``) without re-reading — the seam re-pins the
                               ring and bumps the routing epoch, so the cached rank may
                               name a service the tenant has already left.  Hold the
                               controller's ``routing_lock`` across read *and* use, or
                               re-read after the seam
TPL120 lock-order-inversion    a pair of locks acquired in opposite nesting orders on
                               two code paths (or a non-reentrant lock re-acquired
                               while already held) — a concurrent pair of threads can
                               deadlock.  The declared hierarchy (service lock ≡
                               residency lock → ledger → instruments) is allowlisted
TPL121 unguarded-guarded-attr  an attribute consistently written under one lock
                               elsewhere in the class, read or written bare in
                               thread-reachable code — the torn-read/lost-update race
TPL122 signal-handler-safety   lock acquisition, ``Thread``/``.start()``, blocking
                               I/O, or a ledger write reachable from an installed
                               signal handler — a handler preempts the very thread
                               holding the lock it would need (``Event.set()`` + a
                               pre-spawned parked runner is the sanctioned idiom)
TPL123 blocking-under-lock     ``jax.device_get``/``block_until_ready``/``.item()``/
                               file I/O/HTTP/``sleep`` while a declared lock is held —
                               every reader/writer of that lock inherits the stall
                               (bounded acquisition + cached snapshot is the fix)
TPL201 divergent-collective    a collective (``sync``/``all_reduce``/``all_gather``/
                               ``flush``/…) reachable on only one branch of a rank- or
                               data-dependent conditional — the static complement of the
                               runtime ``LockstepViolation``
TPL301 bad-state-default       ``add_state`` default inconsistent with ``dist_reduce_fx``
                               (non-zero for ``sum``, non-``+inf`` for ``min``,
                               non-``-inf`` for ``max``, non-empty for ``cat``; for a
                               callable merge — the sketch state kind — a provably
                               non-identity default, e.g. a pre-seeded sketch)
TPL302 state-mutation          in-place mutation of an array state (subscript store,
                               discarded ``.at[...]`` result, ``.fill()``/``.sort()``)
                               instead of reassignment
TPL303 unshardable-state       array state declared with ``dist_reduce_fx=None`` — has no
                               world-size-independent meaning, so ``parallel/merge.py``
                               refuses to fold or elastically reshard it
TPL304 stale-partition-rule    a literal ``StatePartitionRules`` regex that matches no
                               state declared anywhere in the package (or does not
                               compile) — the state it meant to shard is silently
                               replicated
TPL305 dynamic-window          a windowed-aggregator construction whose ``window``/
                               ``slots`` argument is provably not a static int (a call,
                               subscript, or non-int literal) — window length is state
                               SHAPE, so a data-dependent window retraces every step
TPL401 shadow-state            ``self.<attr>`` assigned in ``update()``-reachable code but
                               never declared via ``add_state`` — invisible to ``reset()``,
                               snapshots, and elastic fold/reshard
TPL900 syntax-error            file could not be parsed (never suppressible)
TPL901 unjustified-suppression ``tpulint: disable`` comment without a ``-- why`` text
                               (never suppressible)
TPL902 unused-suppression      a ``tpulint: disable`` comment that silences nothing —
                               stale directives mute the next edit on that line
                               (never suppressible)
====== ======================= ==========================================================

Traced-value inference is a forward taint pass per function: parameters with
``Array``-ish annotations (and unannotated ``update()`` parameters — arrays
by contract), ``self.<state>`` loads of declared states, and ``jnp.*`` /
``jax.lax.*``-family call results are traced; ``.shape``/``.dtype``/``.ndim``
stay host-side; list states and literal containers of traced values are
tracked separately (``len()``/emptiness checks on them are fine, transfers
are not).  The recognized **eager-guard idiom** — any conditional whose test
mentions ``jax.core.Tracer``/``isinstance(..., Tracer)`` or a name matching
``is_traced``/``in_trace``/``is_concrete`` — marks its subtree as
deliberately eager, and host-sync rules stay quiet inside it (the runtime
check is authoritative there).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from tpumetrics.analysis.core import ClassInfo, Finding, FuncInfo, ModuleInfo, PackageIndex

CATALOG: Dict[str, Tuple[str, str]] = {
    "TPL101": ("host-transfer", "host transfer of a traced value reachable from update()"),
    "TPL102": ("traced-branch", "Python control flow on a traced value reachable from update()"),
    "TPL104": ("host-telemetry", "span/instrument call in update()-reachable metric code"),
    "TPL105": ("host-health-read", "host-syncing health read in update()-reachable metric code"),
    "TPL106": (
        "serving-layer",
        "admin/SLO entry point in update()-reachable code, or a blocking device "
        "read in an admin-handler/SLO-sampler path",
    ),
    "TPL107": (
        "backbone-in-update",
        "backbone construction or pretrained-weight placement in update()-reachable code",
    ),
    "TPL108": (
        "stale-residency-read",
        "tenant device-state read cached across a hibernation point outside the "
        "residency lock",
    ),
    "TPL109": (
        "stale-routing-read",
        "tenant->rank routing read cached across a migration seam outside the "
        "routing lock",
    ),
    "TPL110": (
        "bare-durability-write",
        "direct write/rename in a durability seam module bypassing the storage "
        "shim's retry/quarantine/fault-injection path",
    ),
    "TPL120": (
        "lock-order-inversion",
        "locks acquired in opposite nesting orders on two paths (or a "
        "non-reentrant lock re-acquired while held) — potential deadlock",
    ),
    "TPL121": (
        "unguarded-guarded-attr",
        "attribute consistently lock-guarded elsewhere read/written bare in "
        "thread-reachable code",
    ),
    "TPL122": (
        "signal-handler-safety",
        "lock acquisition, thread start, blocking I/O, or ledger write "
        "reachable from an installed signal handler",
    ),
    "TPL123": (
        "blocking-under-lock",
        "blocking call (device sync, file I/O, HTTP, sleep) while a declared "
        "lock is held",
    ),
    "TPL201": (
        "divergent-collective",
        "collective reachable on only one branch of a rank- or data-dependent conditional",
    ),
    "TPL301": ("bad-state-default", "add_state default inconsistent with dist_reduce_fx"),
    "TPL302": ("state-mutation", "in-place mutation of an array state instead of reassignment"),
    "TPL303": ("unshardable-state", "array state with dist_reduce_fx=None cannot be folded/resharded"),
    "TPL304": ("stale-partition-rule", "partition rule regex matches no declared state"),
    "TPL305": ("dynamic-window", "windowed metric whose window length is not a static int"),
    "TPL401": ("shadow-state", "attribute assigned in update()-reachable code but not declared via add_state"),
    "TPL900": ("syntax-error", "file could not be parsed"),
    "TPL901": ("unjustified-suppression", "tpulint disable comment without a justification"),
    "TPL902": ("unused-suppression", "tpulint disable comment that silences nothing"),
}

# ----------------------------------------------------------- value lattice
TRACED = "traced"  # a (potentially) traced jax array
CONTAINER = "container"  # python container holding traced values (list state, tuple of arrays)
HOST = "host"  # definitely host-side (shape tuples, python scalars, strings)
UNKNOWN = "unknown"

_TRACED_CALL_PREFIXES = (
    "jax.numpy.",
    "jax.lax.",
    "jax.nn.",
    "jax.scipy.",
    "jax.random.",
    "jax.ops.",
    "jax.image.",
)
_STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "nbytes", "itemsize", "weak_type", "sharding"}
#: jnp/jax functions returning *static* (host) metadata, not traced arrays
_STATIC_JNP_FUNCS = {
    "issubdtype", "isdtype", "iinfo", "finfo", "result_type", "promote_types",
    "can_cast", "dtype", "ndim", "shape", "size", "iscomplexobj", "isrealobj",
}
#: method names whose result is host-side bookkeeping even on unknown receivers
_DICTISH_METHODS = {"keys", "values", "items", "get"}
_COERCION_SINKS = {"float", "int", "bool", "complex", "len"}
_METHOD_SINKS = {"item", "tolist", "block_until_ready"}
_INPLACE_METHODS = {"fill", "sort", "partition", "put", "resize", "setflags"}
_HOST_NEUTRAL_CALLS = {
    "isinstance", "hasattr", "getattr", "type", "id", "repr", "str", "print",
    "format", "issubclass", "callable", "super", "list", "tuple", "dict", "set",
    "frozenset", "zip", "enumerate", "reversed", "map", "filter", "vars", "dir",
    "abs", "round", "sum", "divmod",
}
#: device placement / layout annotation under a mesh: the value STAYS on
#: device (GSPMD resharding, not a host transfer) — the result is traced
_SHARDING_TRACED_CALLS = {
    "jax.device_put",
    "jax.lax.with_sharding_constraint",
    "jax.experimental.pjit.with_sharding_constraint",
}
#: mesh/spec/sharding constructors produce static placement METADATA
_SHARDING_STATIC_PREFIXES = ("jax.sharding.",)
_SHARDING_STATIC_CALLS = {"jax.make_mesh"}
#: python builtins that truth-test or compare their argument element-wise —
#: on a traced array that is a host sync (TracerBoolConversionError under jit)
_PY_TRUTH_SINKS = {"any", "all", "min", "max", "sorted"}
_COLLECTIVE_NAMES = {
    "all_reduce", "all_gather", "all_gather_object", "all_to_all",
    "broadcast_object", "psum", "pmean", "pmax", "pmin", "flush", "sync",
    "barrier", "snapshot_barrier", "_sync_state", "sync_context",
}
_RANKISH_NAMES = {"rank", "process_index", "axis_index", "local_rank", "host_id", "task_id", "node_rank"}
#: base-Metric bookkeeping attrs update-reachable code may touch even when the
#: defining class's hierarchy cannot be resolved (lone fixture files)
_WELL_KNOWN_BASE_ATTRS = {
    "_computed", "_update_count", "_cache", "_is_synced", "_to_sync",
    "_should_unsync", "_enable_grad", "_last_good", "degraded", "_degraded",
}


_CONTAINER_WRAPPERS = (
    "Sequence", "List", "Tuple", "Dict", "Mapping", "MutableMapping",
    "Iterable", "Iterator", "Collection", "Set", "FrozenSet",
    "list", "tuple", "dict", "set",
)


def _annotation_state(node: Optional[ast.expr], mod: ModuleInfo) -> Optional[str]:
    """TRACED for ``Array``/``jnp.ndarray``-typed params (``Optional``/
    ``Union`` included), CONTAINER for containers *of* arrays
    (``Sequence[Dict[str, Array]]`` — its len()/truthiness is host-side),
    ``None`` for everything else.  ``np.ndarray`` annotations are host data,
    not traced."""
    if node is None:
        return None
    try:
        text = ast.unparse(node)
    except Exception:  # pragma: no cover - unparse failures on exotic nodes
        return None
    import re

    arrayish = bool(re.search(r"\bArray\b", text))
    if not arrayish:
        for m in re.finditer(r"(?:\b(\w+)\.)?ndarray\b", text):
            head = mod.imports_mod.get(m.group(1) or "", m.group(1) or "")
            if head.startswith("jax"):
                arrayish = True
                break
    if not arrayish:
        return None
    inner = text
    if inner.startswith("Optional[") and inner.endswith("]"):
        inner = inner[len("Optional[") : -1]
    if re.match(r"(?:typing\.)?(%s)\[" % "|".join(_CONTAINER_WRAPPERS), inner):
        return CONTAINER
    return TRACED


def _truncate(node: ast.AST, limit: int = 70) -> str:
    try:
        text = ast.unparse(node)
    except Exception:  # pragma: no cover
        return "<expr>"
    return text if len(text) <= limit else text[: limit - 1] + "…"


def _dotted_name(expr: ast.expr, mod: ModuleInfo) -> Optional[str]:
    """Import-resolved dotted name of a call target (``jnp.sum`` →
    ``jax.numpy.sum``, ``np.asarray`` → ``numpy.asarray``, bare builtins stay
    bare).  ``None`` for anything not a plain name/attribute chain."""
    parts: List[str] = []
    cur = expr
    while isinstance(cur, ast.Attribute):
        parts.insert(0, cur.attr)
        cur = cur.value
    if not isinstance(cur, ast.Name):
        return None
    head = cur.id
    if parts:
        head = mod.imports_mod.get(head, head)
        return ".".join([head] + parts)
    if head in mod.imports_from:
        tmod, orig = mod.imports_from[head]
        return f"{tmod}.{orig}" if tmod else orig
    return head


def _mentions_rankish(test: ast.expr) -> bool:
    for n in ast.walk(test):
        if isinstance(n, ast.Name) and n.id in _RANKISH_NAMES:
            return True
        if isinstance(n, ast.Attribute) and n.attr in _RANKISH_NAMES:
            return True
    return False


def _is_eager_guard(test: ast.expr) -> bool:
    """Recognize the documented eager-guard idiom: the author already routed
    this code to the concrete/eager world, so host reads inside it are fine."""
    import re

    pat = re.compile(r"tracer|is_?traced|in_?trace\b|is_?concrete", re.IGNORECASE)
    for n in ast.walk(test):
        if isinstance(n, ast.Name) and pat.search(n.id):
            return True
        if isinstance(n, ast.Attribute) and pat.search(n.attr):
            return True
    return False


def _join(a: str, b: str) -> str:
    if a == b:
        return a
    if TRACED in (a, b):
        return TRACED
    if CONTAINER in (a, b):
        return CONTAINER
    return UNKNOWN


class _TraceWalker:
    """Forward taint pass over one function; reports TPL101/102/201."""

    def __init__(
        self,
        mod: ModuleInfo,
        index: PackageIndex,
        fi: FuncInfo,
        check_sync: bool,
    ) -> None:
        self.mod = mod
        self.index = index
        self.fi = fi
        self.check_sync = check_sync
        self.guard_depth = 0
        self.env: Dict[str, str] = {}
        self._stmt_end = 0  # last line of the enclosing SIMPLE statement
        self.findings: List[Finding] = []
        self._reported: Set[Tuple[int, int, str]] = set()
        # innermost-first stack of divergent-conditional frames for TPL201
        self.cond_stack: List[dict] = []
        self.traced_attrs: Set[str] = set()
        self.container_attrs: Set[str] = set()
        if fi.owner is not None:
            states = index.broad_state_names(fi.owner)
            list_states = _list_state_names(fi.owner, index)
            self.container_attrs = states & list_states
            self.traced_attrs = states - self.container_attrs
        self._seed_params()

    # ------------------------------------------------------------- plumbing
    def _seed_params(self) -> None:
        node = self.fi.node
        args = node.args  # type: ignore[attr-defined]
        is_update = self.fi.name == "update" and self.fi.owner is not None
        for a in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs):
            if a.arg in ("self", "cls"):
                continue
            ann = _annotation_state(a.annotation, self.mod)
            if ann is not None:
                self.env[a.arg] = ann
            elif is_update and a.annotation is None:
                # update()'s positional inputs are arrays by contract
                self.env[a.arg] = TRACED

    def _report(self, code: str, node: ast.AST, message: str) -> None:
        key = (getattr(node, "lineno", 0), getattr(node, "col_offset", 0), code)
        if key in self._reported:
            return
        self._reported.add(key)
        self.findings.append(
            Finding(
                code,
                message,
                self.mod.path,
                getattr(node, "lineno", 1),
                getattr(node, "col_offset", 0),
                symbol=self.fi.qualname,
                # a trailing disable comment may sit on the LAST line of a
                # multi-line statement — record the extent so it still applies
                end_line=max(self._stmt_end, getattr(node, "end_lineno", 0) or 0),
            )
        )

    def _sync_active(self) -> bool:
        return self.check_sync and self.guard_depth == 0

    # ------------------------------------------------------------ statements
    def run(self) -> List[Finding]:
        self.walk_body(self.fi.node.body)  # type: ignore[attr-defined]
        return self.findings

    def walk_body(self, stmts: Sequence[ast.stmt]) -> None:
        for s in stmts:
            self.walk(s)

    _SIMPLE_STMTS = (
        ast.Assign, ast.AugAssign, ast.AnnAssign, ast.Expr, ast.Return,
        ast.Raise, ast.Assert, ast.Delete,
    )

    def walk(self, node: ast.stmt) -> None:
        prev = self._stmt_end
        if isinstance(node, self._SIMPLE_STMTS):
            # compound statements (if/while/…) are excluded on purpose: their
            # extent covers the whole body, and a comment deep inside must
            # not accidentally suppress a finding on the header line
            self._stmt_end = getattr(node, "end_lineno", 0) or 0
        meth = getattr(self, f"st_{type(node).__name__}", None)
        if meth is not None:
            meth(node)
        else:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.stmt):
                    self.walk(child)
                elif isinstance(child, ast.expr):
                    self.ev(child)
        self._stmt_end = prev

    def st_FunctionDef(self, node: ast.FunctionDef) -> None:  # nested defs: out of scope
        pass

    st_AsyncFunctionDef = st_FunctionDef

    def st_ClassDef(self, node: ast.ClassDef) -> None:
        pass

    def st_Expr(self, node: ast.Expr) -> None:
        self.ev(node.value)

    def st_Return(self, node: ast.Return) -> None:
        if node.value is not None:
            self.ev(node.value)

    def st_Raise(self, node: ast.Raise) -> None:
        if node.exc is not None:
            self.ev(node.exc)

    def st_Assign(self, node: ast.Assign) -> None:
        val = self.ev(node.value)
        for t in node.targets:
            self._bind(t, val)

    def st_AnnAssign(self, node: ast.AnnAssign) -> None:
        ann = _annotation_state(node.annotation, self.mod)
        val = ann if ann is not None else (
            self.ev(node.value) if node.value is not None else UNKNOWN
        )
        self._bind(node.target, val)

    def st_AugAssign(self, node: ast.AugAssign) -> None:
        val = self.ev(node.value)
        if isinstance(node.target, ast.Name):
            self.env[node.target.id] = _join(val, self.env.get(node.target.id, UNKNOWN))
        else:
            self.ev(node.target)

    def _bind(self, target: ast.expr, val: str) -> None:
        if isinstance(target, ast.Name):
            self.env[target.id] = val
        elif isinstance(target, (ast.Tuple, ast.List)):
            elem = TRACED if val in (TRACED, CONTAINER) else UNKNOWN
            for el in target.elts:
                self._bind(el, elem)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, CONTAINER if val in (TRACED, CONTAINER) else UNKNOWN)
        else:
            self.ev(target)

    def st_If(self, node: ast.If) -> None:
        guarded = _is_eager_guard(node.test)
        # `if isinstance(x, Tracer): return` — the author forked on tracedness
        # and one world exited: the remainder of the function is deliberately
        # single-world, so host-sync rules stay quiet from here on (the
        # increment is never undone for this form).
        sticky = guarded and bool(node.body) and isinstance(
            node.body[-1], (ast.Return, ast.Raise)
            # NOT Continue: it only exits a loop iteration — code after the
            # loop still runs in both worlds, so the guard must not stick
        )
        if guarded:
            self.guard_depth += 1
        test_state = self.ev_bool(node.test, "if")
        divergent = test_state == TRACED or _mentions_rankish(node.test)
        frame = None
        if divergent:
            frame = {
                "node": node,
                "kind": "data" if test_state == TRACED else "rank",
                "body": [],
                "orelse": [],
                "branch": "body",
            }
            self.cond_stack.append(frame)
        before = dict(self.env)
        self.walk_body(node.body)
        after_body = self.env
        self.env = dict(before)
        if frame is not None:
            frame["branch"] = "orelse"
        self.walk_body(node.orelse)
        self.env = self._merge_env(after_body, self.env)
        if frame is not None:
            self.cond_stack.pop()
            self._flag_divergent(frame)
        if guarded and not sticky:
            self.guard_depth -= 1

    def _merge_env(self, a: Dict[str, str], b: Dict[str, str]) -> Dict[str, str]:
        out: Dict[str, str] = {}
        for k in set(a) | set(b):
            if k in a and k in b:
                out[k] = a[k] if a[k] == b[k] else UNKNOWN
            else:
                out[k] = UNKNOWN
        return out

    def _flag_divergent(self, frame: dict) -> None:
        from collections import Counter

        body_ops = Counter(name for _, name in frame["body"])
        orelse_ops = Counter(name for _, name in frame["orelse"])
        if body_ops == orelse_ops:
            return
        kind = frame["kind"]
        test_line = frame["node"].test.lineno
        # only the UNMATCHED collectives diverge the schedule: a pair present
        # on both branches runs either way and must not be reported
        for calls, mine, other in (
            (frame["body"], body_ops, orelse_ops),
            (frame["orelse"], orelse_ops, body_ops),
        ):
            for call_node, name in calls:
                if mine[name] == other[name]:
                    continue
                self._report(
                    "TPL201",
                    call_node,
                    f"collective '{name}' makes the sync schedule differ between the "
                    f"branches of a {kind}-dependent conditional (test at line "
                    f"{test_line}): ranks taking different branches deadlock, or raise "
                    "the runtime LockstepViolation if telemetry verification is on. "
                    "Hoist the collective out of the conditional or make the condition "
                    "rank-uniform.",
                )

    def st_While(self, node: ast.While) -> None:
        test_state = self.ev_bool(node.test, "while")
        divergent = test_state == TRACED or _mentions_rankish(node.test)
        frame = None
        if divergent:
            frame = {"node": node, "kind": "data" if test_state == TRACED else "rank",
                     "body": [], "orelse": [], "branch": "body"}
            self.cond_stack.append(frame)
        self.walk_body(node.body)
        self.walk_body(node.orelse)
        if frame is not None:
            self.cond_stack.pop()
            for call_node, name in frame["body"]:
                self._report(
                    "TPL201",
                    call_node,
                    f"collective '{name}' inside a {frame['kind']}-dependent while loop "
                    f"(test at line {node.test.lineno}): ranks may run it a different "
                    "number of times and desynchronize.",
                )

    def st_For(self, node: ast.For) -> None:
        it = self.ev(node.iter)
        # iterating a traced array yields traced rows; iterating a CONTAINER
        # yields UNKNOWN (elements may be dicts/tuples, not arrays themselves)
        self._bind(node.target, TRACED if it == TRACED else UNKNOWN)
        self.walk_body(node.body)
        self.walk_body(node.orelse)

    def st_Assert(self, node: ast.Assert) -> None:
        self.ev_bool(node.test, "assert")
        if node.msg is not None:
            self.ev(node.msg)

    def st_With(self, node: ast.With) -> None:
        for item in node.items:
            self.ev(item.context_expr)
            if item.optional_vars is not None:
                self._bind(item.optional_vars, UNKNOWN)
        self.walk_body(node.body)

    st_AsyncWith = st_With

    def st_Try(self, node: ast.Try) -> None:
        self.walk_body(node.body)
        for h in node.handlers:
            self.walk_body(h.body)
        self.walk_body(node.orelse)
        self.walk_body(node.finalbody)

    # ----------------------------------------------------------- expressions
    def ev_bool(self, node: ast.expr, construct: str) -> str:
        """Evaluate ``node`` in a boolean (truthiness-forcing) context."""
        if isinstance(node, ast.BoolOp):
            state = HOST
            for v in node.values:
                state = _join(state, self.ev_bool(v, construct))
            return state
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
            return self.ev_bool(node.operand, construct)
        state = self.ev(node)
        if state == TRACED and self._sync_active():
            self._report(
                "TPL102",
                node,
                f"`{construct}` on a traced value forces a host sync before .compute(): "
                f"`{_truncate(node)}` — use jnp.where / lax.cond / masking to stay on device.",
            )
        return state

    def ev(self, node: ast.expr) -> str:
        meth = getattr(self, f"ev_{type(node).__name__}", None)
        if meth is not None:
            return meth(node)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self.ev(child)
        return UNKNOWN

    def ev_Constant(self, node: ast.Constant) -> str:
        return HOST

    def ev_Name(self, node: ast.Name) -> str:
        return self.env.get(node.id, UNKNOWN)

    def ev_Attribute(self, node: ast.Attribute) -> str:
        if isinstance(node.value, ast.Name) and node.value.id == "self":
            if node.attr in self.traced_attrs:
                return TRACED
            if node.attr in self.container_attrs:
                return CONTAINER
            return UNKNOWN
        base = self.ev(node.value)
        if node.attr in _STATIC_ATTRS:
            return HOST
        if base == TRACED:
            return TRACED
        return UNKNOWN

    def ev_Subscript(self, node: ast.Subscript) -> str:
        base = self.ev(node.value)
        self.ev(node.slice)
        if base in (TRACED, CONTAINER):
            return TRACED
        if base == HOST:
            return HOST
        return UNKNOWN

    def ev_Slice(self, node: ast.Slice) -> str:
        for part in (node.lower, node.upper, node.step):
            if part is not None:
                self.ev(part)
        return HOST

    def ev_BinOp(self, node: ast.BinOp) -> str:
        return _join(self.ev(node.left), self.ev(node.right))

    def ev_UnaryOp(self, node: ast.UnaryOp) -> str:
        if isinstance(node.op, ast.Not):
            return self.ev_bool(node.operand, "not")
        return self.ev(node.operand)

    def ev_Compare(self, node: ast.Compare) -> str:
        states = [self.ev(node.left)] + [self.ev(c) for c in node.comparators]
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            return HOST
        for op, right_state in zip(node.ops, states[1:]):
            if isinstance(op, (ast.In, ast.NotIn)) and right_state == TRACED and self._sync_active():
                self._report(
                    "TPL101",
                    node,
                    "`in` against a traced array calls __contains__ on device data "
                    f"(host sync): `{_truncate(node)}`",
                )
        return TRACED if TRACED in states else (HOST if all(s == HOST for s in states) else UNKNOWN)

    def ev_BoolOp(self, node: ast.BoolOp) -> str:
        # a and b: every operand but the last is truth-tested
        state = HOST
        for v in node.values[:-1]:
            state = _join(state, self.ev_bool(v, "and/or"))
        return _join(state, self.ev(node.values[-1]))

    def ev_IfExp(self, node: ast.IfExp) -> str:
        self.ev_bool(node.test, "ternary")
        return _join(self.ev(node.body), self.ev(node.orelse))

    def ev_Tuple(self, node: ast.Tuple) -> str:
        states = [self.ev(e) for e in node.elts]
        return CONTAINER if TRACED in states or CONTAINER in states else HOST

    ev_List = ev_Tuple
    ev_Set = ev_Tuple

    def ev_Dict(self, node: ast.Dict) -> str:
        states = [self.ev(v) for v in node.values if v is not None]
        for k in node.keys:
            if k is not None:
                self.ev(k)
        return CONTAINER if TRACED in states or CONTAINER in states else HOST

    def ev_JoinedStr(self, node: ast.JoinedStr) -> str:
        for v in node.values:
            if isinstance(v, ast.FormattedValue):
                self.ev(v.value)
        return HOST

    def ev_Lambda(self, node: ast.Lambda) -> str:
        return HOST

    def ev_Starred(self, node: ast.Starred) -> str:
        return self.ev(node.value)

    def ev_Await(self, node: ast.Await) -> str:
        return self.ev(node.value)

    def _ev_comp(self, node: ast.expr, elts: Sequence[ast.expr]) -> str:
        for gen in node.generators:  # type: ignore[attr-defined]
            it = self.ev(gen.iter)
            self._bind(gen.target, TRACED if it == TRACED else UNKNOWN)
            for cond in gen.ifs:
                self.ev_bool(cond, "comprehension filter")
        states = [self.ev(e) for e in elts]
        return CONTAINER if TRACED in states or CONTAINER in states else UNKNOWN

    def ev_ListComp(self, node: ast.ListComp) -> str:
        return self._ev_comp(node, [node.elt])

    def ev_SetComp(self, node: ast.SetComp) -> str:
        return self._ev_comp(node, [node.elt])

    def ev_GeneratorExp(self, node: ast.GeneratorExp) -> str:
        return self._ev_comp(node, [node.elt])

    def ev_DictComp(self, node: ast.DictComp) -> str:
        return self._ev_comp(node, [node.key, node.value])

    def ev_Call(self, node: ast.Call) -> str:
        dotted = _dotted_name(node.func, self.mod)
        arg_states = [self.ev(a) for a in node.args]
        kw_states = [self.ev(kw.value) for kw in node.keywords]
        any_traced = TRACED in arg_states or TRACED in kw_states
        any_payload = any_traced or CONTAINER in arg_states or CONTAINER in kw_states

        recv_state = None
        if isinstance(node.func, ast.Attribute):
            recv_state = self.ev(node.func.value)
            attr = node.func.attr
            if attr in _METHOD_SINKS and recv_state == TRACED:
                if self._sync_active():
                    self._report(
                        "TPL101",
                        node,
                        f".{attr}() on a traced value is a device→host transfer: "
                        f"`{_truncate(node)}` — keep the value on device until .compute().",
                    )
                return HOST if attr in ("item", "tolist") else TRACED
            if attr in _COLLECTIVE_NAMES and self.cond_stack:
                frame = self.cond_stack[-1]
                frame[frame["branch"]].append((node, attr))
        elif isinstance(node.func, ast.Name) and node.func.id in _COLLECTIVE_NAMES and self.cond_stack:
            frame = self.cond_stack[-1]
            frame[frame["branch"]].append((node, node.func.id))

        if dotted is not None:
            if dotted in _COERCION_SINKS:
                target = arg_states[0] if arg_states else UNKNOWN
                if target == TRACED and self._sync_active():
                    self._report(
                        "TPL101",
                        node,
                        f"{dotted}() coerces a traced value on the host: `{_truncate(node)}` "
                        "— use jnp casts/masking to stay on device until .compute().",
                    )
                return HOST
            if dotted == "range":
                if any_traced and self._sync_active():
                    self._report(
                        "TPL102",
                        node,
                        f"range() over a traced value makes loop bounds data-dependent "
                        f"(host sync): `{_truncate(node)}`",
                    )
                return HOST
            if dotted in _PY_TRUTH_SINKS:
                if any_traced and self._sync_active():
                    self._report(
                        "TPL102",
                        node,
                        f"python {dotted}() truth-tests/compares a traced array on the "
                        f"host: `{_truncate(node)}` — use the jnp.{dotted.rstrip('ed')} "
                        "equivalent to stay on device.",
                    )
                return UNKNOWN
            if dotted.startswith("numpy.") and any_payload:
                if self._sync_active():
                    self._report(
                        "TPL101",
                        node,
                        f"numpy call on a traced value pulls it to the host: "
                        f"`{_truncate(node)}` — use the jnp equivalent.",
                    )
                return UNKNOWN
            if dotted in _SHARDING_TRACED_CALLS:
                # device_put / with_sharding_constraint move or annotate data
                # ON DEVICE (a resharding is device↔device over ICI); they are
                # not host transfers, and their result is traced
                return TRACED
            if dotted in _SHARDING_STATIC_CALLS or any(
                dotted.startswith(p) for p in _SHARDING_STATIC_PREFIXES
            ):
                return HOST  # Mesh/PartitionSpec/NamedSharding: static metadata
            if dotted in ("jax.device_get", "jax.block_until_ready"):
                if any_payload and self._sync_active():
                    self._report(
                        "TPL101",
                        node,
                        f"{dotted} in update()-reachable code is an explicit host sync: "
                        f"`{_truncate(node)}`",
                    )
                return HOST
            if any(dotted.startswith(p) for p in _TRACED_CALL_PREFIXES):
                if dotted.rpartition(".")[2] in _STATIC_JNP_FUNCS:
                    return HOST  # dtype/shape introspection: static under trace
                return TRACED
            if dotted in _HOST_NEUTRAL_CALLS:
                return UNKNOWN

        if isinstance(node.func, ast.Attribute) and node.func.attr in _DICTISH_METHODS:
            return UNKNOWN  # dict-protocol methods: host bookkeeping, not payload
        if recv_state == TRACED:
            return TRACED  # method on a traced value (.sum(), .astype(), .reshape(), …)
        if any_payload:
            return TRACED  # taint through unknown callees: conservative
        return UNKNOWN


def _list_state_names(ci: ClassInfo, index: PackageIndex) -> Set[str]:
    """States declared with an empty-list default anywhere in the hierarchy
    (their truthiness/len is host-side; their *elements* are traced)."""
    names: Set[str] = set()
    for rel in [ci] + index._ancestors(ci) + index._descendants(ci):
        for call, method in rel.add_state_calls:
            default = _default_arg(call)
            if isinstance(default, ast.List):
                names |= _state_names_of_call(rel, call, method)
    return names


def _state_names_of_call(ci: ClassInfo, call: ast.Call, method_name: str) -> Set[str]:
    from tpumetrics.analysis.core import _literal_state_names

    meth = ci.methods.get(method_name)
    scope = meth.node if meth is not None else ci.node
    return _literal_state_names(call, scope)


def _default_arg(call: ast.Call) -> Optional[ast.expr]:
    if len(call.args) >= 2:
        return call.args[1]
    for kw in call.keywords:
        if kw.arg == "default":
            return kw.value
    return None


def _reduce_arg(call: ast.Call) -> Tuple[bool, Optional[ast.expr]]:
    """(explicitly_given, expr) for dist_reduce_fx; omitted means None."""
    if len(call.args) >= 3:
        return True, call.args[2]
    for kw in call.keywords:
        if kw.arg == "dist_reduce_fx":
            return True, kw.value
    return False, None


# default-expression classification for TPL301/TPL303
def _default_kind(expr: Optional[ast.expr], mod: ModuleInfo) -> str:
    """One of: zero / posinf / neginf / nonzero / empty_list / nonempty_list /
    array_unknown (an array-producing call of undecidable value) / unknown."""
    if expr is None:
        return "unknown"
    if isinstance(expr, ast.List):
        return "empty_list" if not expr.elts else "nonempty_list"
    if isinstance(expr, ast.Constant):
        v = expr.value
        if isinstance(v, bool):
            return "nonzero" if v else "zero"
        if isinstance(v, (int, float, complex)):
            if v == 0:
                return "zero"
            if isinstance(v, float) and v == float("inf"):
                return "posinf"
            if isinstance(v, float) and v == float("-inf"):
                return "neginf"
            return "nonzero"
        return "unknown"
    if isinstance(expr, ast.UnaryOp) and isinstance(expr.op, ast.USub):
        inner = _default_kind(expr.operand, mod)
        return {"posinf": "neginf", "neginf": "posinf", "zero": "zero", "nonzero": "nonzero"}.get(
            inner, inner
        )
    if isinstance(expr, ast.Attribute) or isinstance(expr, ast.Name):
        dotted = _dotted_name(expr, mod) or ""
        if dotted.endswith(".inf") or dotted in ("inf", "Inf"):
            return "posinf"
        return "unknown"
    if isinstance(expr, ast.Call):
        dotted = _dotted_name(expr.func, mod) or ""
        tail = dotted.rpartition(".")[2]
        if tail in ("zeros", "zeros_like"):
            return "zero"
        if tail in ("ones", "ones_like"):
            return "nonzero"
        if tail in ("asarray", "array", "tensor"):
            inner = _default_kind(expr.args[0] if expr.args else None, mod)
            return inner if inner != "unknown" else "array_unknown"
        if tail == "full":
            inner = _default_kind(expr.args[1] if len(expr.args) >= 2 else None, mod)
            return inner if inner != "unknown" else "array_unknown"
        if dotted in ("float", "int") and expr.args and isinstance(expr.args[0], ast.Constant):
            v = expr.args[0].value
            if v in ("inf", "Inf", "+inf"):
                return "posinf"
            if v == "-inf":
                return "neginf"
            inner = _default_kind(expr.args[0], mod)
            return inner
        if tail in ("eye", "arange", "linspace", "full_like"):
            return "array_unknown"
        return "unknown"
    return "unknown"


class TraceSafetyRule:
    """TPL101 / TPL102 on update()-reachable code; TPL201 everywhere."""

    codes = ("TPL101", "TPL102", "TPL201")

    def check(self, mod: ModuleInfo, index: PackageIndex) -> Iterator[Finding]:
        funcs: List[FuncInfo] = list(mod.functions.values())
        for ci in mod.classes.values():
            funcs.extend(ci.methods.values())
        for fi in funcs:
            walker = _TraceWalker(mod, index, fi, check_sync=index.is_update_reachable(fi.node))
            yield from walker.run()


class StateDeclRule:
    """TPL301 (defaults vs reduce), TPL302 (in-place mutation), TPL303
    (reduce-None arrays) — all anchored at the declaring class."""

    codes = ("TPL301", "TPL302", "TPL303")

    _EXPECTED = {
        "sum": ("zero",),
        "min": ("posinf",),
        "max": ("neginf",),
    }

    def check(self, mod: ModuleInfo, index: PackageIndex) -> Iterator[Finding]:
        for ci in mod.classes.values():
            yield from self._check_declarations(mod, ci)
            yield from self._check_mutations(mod, ci, index)

    def _check_declarations(self, mod: ModuleInfo, ci: ClassInfo) -> Iterator[Finding]:
        for call, method in ci.add_state_calls:
            names = _state_names_of_call(ci, call, method) or {"<dynamic>"}
            label = "/".join(sorted(names))
            default = _default_arg(call)
            kind = _default_kind(default, mod)
            explicit, reduce_expr = _reduce_arg(call)
            if explicit and not isinstance(reduce_expr, ast.Constant):
                # callable merge (the sketch state kind) / dynamic reduce.
                # The merge's identity is undecidable statically, but some
                # defaults are provably NOT any merge's identity: a finite
                # non-zero scalar or a pre-seeded list contributes real
                # mass on every cross-rank fold from a rank that never
                # updated.  ±inf stays quiet — it IS the identity of
                # extremum-style merges (and of a variable-held "max"/
                # "min" string reduce) — as do empty-sketch constructors
                # (``empty_*``), zeros, and anything dynamic.
                if kind in ("nonzero", "nonempty_list"):
                    yield Finding(
                        "TPL301",
                        f"state '{label}' uses a callable dist_reduce_fx (merge state "
                        "kind) with a non-zero/pre-seeded default — for additive-"
                        "style merges (the common case: sketches, counts) a rank "
                        "that never updated would contribute real mass to every "
                        "cross-rank fold. Use the merge's identity (e.g. an empty "
                        "sketch) as the default; if this IS the identity (a "
                        "product-style merge whose identity is 1), suppress with a "
                        "justification naming the merge.",
                        mod.path, call.lineno, call.col_offset, symbol=f"{ci.name}.{method}",
                    )
                continue  # identity-ness beyond that is undecidable here
            reduce_val = reduce_expr.value if isinstance(reduce_expr, ast.Constant) else None
            reduce_lit = reduce_val if isinstance(reduce_val, str) else None
            is_none = reduce_val is None  # explicit None or omitted (the signature default)
            if reduce_lit in self._EXPECTED:
                expected = self._EXPECTED[reduce_lit]
                if kind not in expected and kind not in ("unknown", "array_unknown", "empty_list"):
                    ident = {"zero": "0", "posinf": "+inf", "neginf": "-inf"}[expected[0]]
                    yield Finding(
                        "TPL301",
                        f"state '{label}' uses dist_reduce_fx='{reduce_lit}' but its default "
                        f"is not the reduce identity ({ident}): a rank that never updated "
                        "would contribute a wrong value to the cross-rank fold.",
                        mod.path, call.lineno, call.col_offset, symbol=f"{ci.name}.{method}",
                    )
            elif reduce_lit == "cat" and kind == "nonempty_list":
                yield Finding(
                    "TPL301",
                    f"state '{label}' uses dist_reduce_fx='cat' with a non-empty default: "
                    "pre-seeded rows are concatenated again on every reset/sync cycle.",
                    mod.path, call.lineno, call.col_offset, symbol=f"{ci.name}.{method}",
                )
            elif is_none and kind in ("zero", "nonzero", "posinf", "neginf", "array_unknown"):
                yield Finding(
                    "TPL303",
                    f"array state '{label}' has dist_reduce_fx=None: its global form is a "
                    "per-rank stack with no world-size-independent meaning, so "
                    "parallel/merge.py cannot fold it and elastic restore refuses it. "
                    "Declare 'sum'/'mean'/'max'/'min'/'cat', or make it a list state.",
                    mod.path, call.lineno, call.col_offset, symbol=f"{ci.name}.{method}",
                )

    def _check_mutations(self, mod: ModuleInfo, ci: ClassInfo, index: PackageIndex) -> Iterator[Finding]:
        states = index.broad_state_names(ci) if index.is_metric_like(ci) else ci.state_names
        if not states:
            return
        for name, fi in ci.methods.items():
            for n in ast.walk(fi.node):
                if isinstance(n, ast.Assign):
                    for t in n.targets:
                        attr = _self_state_subscript(t, states)
                        if attr is not None:
                            yield Finding(
                                "TPL302",
                                f"in-place subscript store into state '{attr}': jax arrays "
                                "are immutable — reassign via "
                                f"`self.{attr} = self.{attr}.at[...].set(...)`.",
                                mod.path, n.lineno, n.col_offset, symbol=f"{ci.name}.{name}",
                            )
                elif isinstance(n, ast.Expr):
                    attr = _discarded_functional_update(n.value, states)
                    if attr is not None:
                        yield Finding(
                            "TPL302",
                            f"discarded `.at[...]` update on state '{attr}': the functional "
                            "result is thrown away, the state never changes — assign it "
                            f"back (`self.{attr} = self.{attr}.at[...]...`).",
                            mod.path, n.lineno, n.col_offset, symbol=f"{ci.name}.{name}",
                        )
                    attr = _inplace_method_call(n.value, states)
                    if attr is not None:
                        yield Finding(
                            "TPL302",
                            f"in-place method call on state '{attr}': jax arrays are "
                            "immutable and this either fails or silently no-ops — use the "
                            "functional jnp equivalent and reassign.",
                            mod.path, n.lineno, n.col_offset, symbol=f"{ci.name}.{name}",
                        )


def _self_state_attr(expr: ast.expr, states: Set[str]) -> Optional[str]:
    if (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
        and expr.attr in states
    ):
        return expr.attr
    return None


def _self_state_subscript(target: ast.expr, states: Set[str]) -> Optional[str]:
    if isinstance(target, ast.Subscript):
        return _self_state_attr(target.value, states)
    return None


def _discarded_functional_update(expr: ast.expr, states: Set[str]) -> Optional[str]:
    """Match `self.<state>.at[...].set/add/...(…)` used as a bare statement."""
    if not isinstance(expr, ast.Call):
        return None
    f = expr.func
    if not isinstance(f, ast.Attribute):
        return None
    sub = f.value  # the `.at[...]` subscript
    if isinstance(sub, ast.Subscript) and isinstance(sub.value, ast.Attribute) and sub.value.attr == "at":
        return _self_state_attr(sub.value.value, states)
    return None


def _inplace_method_call(expr: ast.expr, states: Set[str]) -> Optional[str]:
    if (
        isinstance(expr, ast.Call)
        and isinstance(expr.func, ast.Attribute)
        and expr.func.attr in _INPLACE_METHODS
    ):
        return _self_state_attr(expr.func.value, states)
    return None


class ShadowStateRule:
    """TPL401: stores to undeclared ``self.<attr>`` in update()-reachable code."""

    codes = ("TPL401",)

    def check(self, mod: ModuleInfo, index: PackageIndex) -> Iterator[Finding]:
        for ci in mod.classes.values():
            if not index.is_metric_like(ci):
                continue
            if self._has_dynamic_state_decl(ci, index):
                # a hierarchy declaring states under computed names (e.g.
                # BaseAggregator's add_state(state_name, …)) has an open
                # state set — "undeclared" cannot be proven, so stay quiet
                continue
            allowed = (
                index.broad_state_names(ci)
                | index.declared_attr_names(ci)
                | _WELL_KNOWN_BASE_ATTRS
            )
            for name, fi in ci.methods.items():
                if not index.is_update_reachable(fi.node):
                    continue
                for n in ast.walk(fi.node):
                    targets: List[ast.expr] = []
                    if isinstance(n, ast.Assign):
                        targets = list(n.targets)
                    elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
                        targets = [n.target]
                    for t in targets:
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                            and t.attr not in allowed
                        ):
                            yield Finding(
                                "TPL401",
                                f"'self.{t.attr}' is assigned in update()-reachable code but "
                                "never declared via add_state: it is invisible to reset(), "
                                "snapshots, cross-rank sync, and elastic fold/reshard — "
                                "declare it with add_state or move it out of the update path.",
                                mod.path, t.lineno, t.col_offset, symbol=f"{ci.name}.{name}",
                            )

    @staticmethod
    def _has_dynamic_state_decl(ci: ClassInfo, index: PackageIndex) -> bool:
        for rel in [ci] + index._ancestors(ci) + index._descendants(ci):
            for call, method in rel.add_state_calls:
                if not _state_names_of_call(rel, call, method):
                    return True
        return False


#: the two host-telemetry modules whose calls TPL104 rejects in update paths
_TPL104_MODULES = (
    "tpumetrics.telemetry.spans",
    "tpumetrics.telemetry.instruments",
)
#: package-level re-exports of the same entry points (``telemetry.span(...)``)
_TPL104_NAMES = {
    "span", "start_span", "start_trace", "end_span", "record_span", "activate",
    "counter", "gauge", "histogram",
}


def _import_resolved_dotted(expr: ast.expr, mod: ModuleInfo) -> Optional[str]:
    """Like :func:`_dotted_name`, but ALSO resolves attribute-chain heads
    through ``from``-imports (``from tpumetrics.telemetry import spans;
    spans.span(...)`` → ``tpumetrics.telemetry.spans.span``), which
    _dotted_name leaves unresolved for module objects."""
    parts: List[str] = []
    cur = expr
    while isinstance(cur, ast.Attribute):
        parts.insert(0, cur.attr)
        cur = cur.value
    if not isinstance(cur, ast.Name):
        return None
    head = cur.id
    if head in mod.imports_from:
        tmod, orig = mod.imports_from[head]
        head = f"{tmod}.{orig}" if tmod else orig
    else:
        head = mod.imports_mod.get(head, head)
    return ".".join([head] + parts)


class HostTelemetryRule:
    """TPL104: spans opened / instruments bumped in ``update()``-reachable
    metric code.

    Spans and instruments are **host-side effects by design** (monotonic
    clocks, thread-locals, locked rings) — the exact things a jitted
    ``update()`` must not touch.  Under jit they would not even measure the
    step: trace-time code runs ONCE per compile (and again on every
    retrace), so a span there times tracing, not execution, and a counter
    there drifts with the compile cache.  The runtime instruments the host
    seams (submit, schedule, dispatch, write-back) instead — metric code
    never needs its own telemetry.  Eager-guard idioms are deliberately NOT
    honored here (unlike TPL101): even eagerly, per-update spans belong to
    the runtime layer, not inside metric math."""

    codes = ("TPL104",)

    def check(self, mod: ModuleInfo, index: PackageIndex) -> Iterator[Finding]:
        funcs: List[FuncInfo] = list(mod.functions.values())
        for ci in mod.classes.values():
            funcs.extend(ci.methods.values())
        for fi in funcs:
            if not index.is_update_reachable(fi.node):
                continue
            for n in ast.walk(fi.node):
                if not isinstance(n, ast.Call):
                    continue
                dotted = _import_resolved_dotted(n.func, mod)
                if dotted is None or not self._is_host_telemetry(dotted):
                    continue
                yield Finding(
                    "TPL104",
                    f"telemetry call `{_truncate(n)}` in update()-reachable code: "
                    "spans and instruments are host-side effects — under jit they "
                    "run at trace time only (and re-run per retrace), so nothing "
                    "meaningful is measured. Instrument the runtime seams "
                    "(submit/schedule/dispatch/write-back) instead of metric code.",
                    mod.path, n.lineno, n.col_offset, symbol=fi.qualname,
                )

    @staticmethod
    def _is_host_telemetry(dotted: str) -> bool:
        for m in _TPL104_MODULES:
            if dotted == m or dotted.startswith(m + "."):
                return True
        if dotted.startswith("tpumetrics.telemetry."):
            return dotted.rpartition(".")[2] in _TPL104_NAMES
        return False


#: host-SYNCING entry points of the health layer: each fetches the device
#: counters (device_get).  The trace-safe probes (probe_tree/probe_packed/
#: state_paths/flatten) are deliberately NOT listed — they are pure jnp and
#: belong inside step programs.
_TPL105_SYNC_NAMES = {"summarize", "publish_health", "release_health"}
_TPL105_MODULE = "tpumetrics.telemetry.health"


class HostHealthReadRule:
    """TPL105: host-syncing health reads in ``update()``-reachable code.

    The health layer splits sharply in two: the *probe*
    (``health.probe_tree``/``probe_packed``) is pure ``jnp`` and designed to
    run inside the step program, while the *read*
    (``health.summarize`` and the publish/release plumbing) calls
    ``jax.device_get`` — a device sync.  A read reachable from ``update()``
    would stall the stream once per step, exactly the host round-trip the
    paper contract forbids; reads belong on the ``compute()``/``stats()``
    seam, where the runtime already fetches results.  (The structural twin
    of TPL104, specialized to the health module's split contract.)"""

    codes = ("TPL105",)

    def check(self, mod: ModuleInfo, index: PackageIndex) -> Iterator[Finding]:
        funcs: List[FuncInfo] = list(mod.functions.values())
        for ci in mod.classes.values():
            funcs.extend(ci.methods.values())
        for fi in funcs:
            if not index.is_update_reachable(fi.node):
                continue
            for n in ast.walk(fi.node):
                if not isinstance(n, ast.Call):
                    continue
                dotted = _import_resolved_dotted(n.func, mod)
                if dotted is None or not self._is_sync_read(dotted):
                    continue
                yield Finding(
                    "TPL105",
                    f"host-syncing health read `{_truncate(n)}` in update()-"
                    "reachable code: it device_gets the probe counters, "
                    "stalling the stream once per step. The in-trace probe "
                    "(health.probe_tree/probe_packed) belongs in the step "
                    "program; read the counters on the compute()/stats() "
                    "seam instead.",
                    mod.path, n.lineno, n.col_offset, symbol=fi.qualname,
                )

    @staticmethod
    def _is_sync_read(dotted: str) -> bool:
        if dotted.startswith(_TPL105_MODULE + "."):
            return dotted.rpartition(".")[2] in _TPL105_SYNC_NAMES
        return False


#: backbone constructors / weight-placement entry points: each loads or
#: places a pretrained weight tree.  The registry dedupes by weights digest,
#: but the digest itself hashes every leaf's bytes — calling any of these
#: per step pays a full host walk of the tree (and `device_put` re-places it
#: outright, or burns a retrace under jit).
_TPL107_CONSTRUCTORS = {
    "tpumetrics.backbones.get_backbone",
    "tpumetrics.backbones.registry.get_backbone",
    "tpumetrics.image._backbones.lpips_backbone",
    "tpumetrics.image._inception.load_inception_params",
    "tpumetrics.image._inception.inception_feature_extractor",
}
#: identifier fragments marking a `jax.device_put` operand as a weight tree
_TPL107_WEIGHT_HINTS = ("param", "weight")
#: the same constructors by bare name — function-local ``from`` imports are
#: invisible to the module import table, so a deferred-import call site
#: resolves to the bare callable name; these are distinctive enough to match
_TPL107_BARE = {d.rpartition(".")[2] for d in _TPL107_CONSTRUCTORS}


class BackboneLifecycleRule:
    """TPL107: backbone construction / weight placement in ``update()``-reachable code.

    Pretrained forwards live in the process-global backbone registry
    (:mod:`tpumetrics.backbones`): weights are digested, placed once, and
    shared by every metric instance and service tenant.  Constructing a
    backbone — or ``jax.device_put``-ing a param/weight tree — inside an
    update path defeats exactly that: eagerly it re-digests (a full host
    walk of the tree) or re-places the weights every step; under jit the
    call runs at trace time only and silently re-runs per retrace.  Acquire
    the handle in ``__init__`` (or a resolve seam) and dispatch it in
    ``update()``.  The registry's own modules are exempt — they ARE the
    lifecycle seam."""

    codes = ("TPL107",)

    def check(self, mod: ModuleInfo, index: PackageIndex) -> Iterator[Finding]:
        path = str(mod.path).replace("\\", "/")
        if "tpumetrics/backbones/" in path:
            return
        funcs: List[FuncInfo] = list(mod.functions.values())
        for ci in mod.classes.values():
            funcs.extend(ci.methods.values())
        for fi in funcs:
            if not index.is_update_reachable(fi.node):
                continue
            for n in ast.walk(fi.node):
                if not isinstance(n, ast.Call):
                    continue
                dotted = _import_resolved_dotted(n.func, mod)
                if dotted is None:
                    continue
                if dotted in _TPL107_CONSTRUCTORS or ("." not in dotted and dotted in _TPL107_BARE):
                    yield Finding(
                        "TPL107",
                        f"backbone construction `{_truncate(n)}` in update()-"
                        "reachable code: pretrained weights are digested and "
                        "placed ONCE through the backbone registry — per step "
                        "this re-walks the weight tree on host (or re-runs "
                        "only at retrace under jit). Acquire the handle in "
                        "__init__ and dispatch it in update().",
                        mod.path, n.lineno, n.col_offset, symbol=fi.qualname,
                    )
                elif dotted == "jax.device_put" and self._places_weights(n):
                    yield Finding(
                        "TPL107",
                        f"weight placement `{_truncate(n)}` in update()-"
                        "reachable code: device_put of a param/weight tree "
                        "re-places resident backbone weights every step. "
                        "Placement belongs to the backbone registry "
                        "(tpumetrics.backbones.get_backbone) at construction "
                        "time.",
                        mod.path, n.lineno, n.col_offset, symbol=fi.qualname,
                    )

    @staticmethod
    def _places_weights(call: ast.Call) -> bool:
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            name = None
            if isinstance(arg, ast.Name):
                name = arg.id
            elif isinstance(arg, ast.Attribute):
                name = arg.attr
            if name is not None and any(h in name.lower() for h in _TPL107_WEIGHT_HINTS):
                return True
        return False


#: hibernation points: any of these calls may demote a tenant (or, for the
#: budget paths, demote a *different* tenant to make room) — the spill drops
#: the tenant's device buffers, so a residency read cached before the call is
#: a dangling reference after it
_TPL108_POINTS = {
    "hibernate",
    "sweep_lifecycle",
    "enforce_budget",
    "ensure_resident",
    "revive",
    "maybe_hibernate",
}
#: the per-tenant device-resident attributes whose cached reads go stale
_TPL108_ATTRS = {"state", "device_health"}


class ResidencyLifecycleRule:
    """TPL108: tenant device-state read cached across a hibernation point.

    The lifecycle manager (:mod:`tpumetrics.lifecycle`) may demote a tenant
    at any hibernation point — ``hibernate``/``sweep_lifecycle``/
    ``enforce_budget`` directly, ``ensure_resident``/``revive`` indirectly
    (reviving one tenant can budget-evict another).  Demotion spills the
    tenant's state and *replaces the device buffers with nothing*: a local
    that cached ``<tenant>.state`` or ``<tenant>.device_health`` before the
    point dangles after it — it pins freed device memory at best, computes
    from a stale tree at worst.  The safe shapes are (a) hold the manager's
    ``residency_lock`` across read AND use (demotion takes the same lock),
    or (b) re-read the attribute after the point.  The lifecycle manager's
    own modules are exempt — they ARE the residency seam."""

    codes = ("TPL108",)

    def check(self, mod: ModuleInfo, index: PackageIndex) -> Iterator[Finding]:
        path = str(mod.path).replace("\\", "/")
        if "tpumetrics/lifecycle/" in path:
            return
        funcs: List[FuncInfo] = list(mod.functions.values())
        for ci in mod.classes.values():
            funcs.extend(ci.methods.values())
        for fi in funcs:
            yield from self._check_func(fi, mod)

    def _check_func(self, fi: FuncInfo, mod: ModuleInfo) -> Iterator[Finding]:
        # line spans of `with <...>.residency_lock:` bodies — reads and uses
        # inside one are serialized against demotion by construction
        locked: List[Tuple[int, int]] = []
        for n in ast.walk(fi.node):
            if isinstance(n, (ast.With, ast.AsyncWith)):
                for item in n.items:
                    if self._terminal(item.context_expr) == "residency_lock":
                        locked.append((n.lineno, n.end_lineno or n.lineno))
                        break

        def in_lock(line: int) -> bool:
            return any(lo <= line <= hi for lo, hi in locked)

        # every simple-name assignment, tainted iff it caches a residency
        # attribute of a tenant-named base; later clean rebinds launder
        binds: Dict[str, List[Tuple[int, bool, ast.expr]]] = {}
        points: List[int] = []
        uses: List[Tuple[str, ast.Name]] = []
        for n in ast.walk(fi.node):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 and isinstance(
                n.targets[0], ast.Name
            ):
                binds.setdefault(n.targets[0].id, []).append(
                    (n.lineno, self._residency_read(n.value), n.value)
                )
            elif isinstance(n, ast.Call) and self._terminal(n.func) in _TPL108_POINTS:
                points.append(n.lineno)
            elif isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                uses.append((n.id, n))
        if not points or not binds:
            return

        reported: Set[Tuple[str, int]] = set()
        for name, node in uses:
            history = binds.get(name)
            if not history:
                continue
            prior = [b for b in history if b[0] < node.lineno]
            if not prior:
                continue
            bind_line, tainted, _value = max(prior, key=lambda b: b[0])
            if not tainted:
                continue
            crossed = any(bind_line < p < node.lineno for p in points)
            if not crossed:
                continue
            if in_lock(bind_line) and in_lock(node.lineno):
                continue
            key = (name, bind_line)
            if key in reported:
                continue
            reported.add(key)
            yield Finding(
                "TPL108",
                f"`{name}` caches a tenant residency read (bound at line "
                f"{bind_line}) and is used after a hibernation point: the "
                "lifecycle manager may have spilled the tenant and dropped "
                "its device buffers in between. Hold residency_lock across "
                "the read and the use, or re-read after the point.",
                mod.path, node.lineno, node.col_offset, symbol=fi.qualname,
            )

    @staticmethod
    def _residency_read(expr: ast.expr) -> bool:
        if not (isinstance(expr, ast.Attribute) and expr.attr in _TPL108_ATTRS):
            return False
        base = ResidencyLifecycleRule._terminal(expr.value)
        return base is not None and "tenant" in base.lower()

    @staticmethod
    def _terminal(expr: ast.expr) -> Optional[str]:
        if isinstance(expr, ast.Name):
            return expr.id
        if isinstance(expr, ast.Attribute):
            return expr.attr
        return None


#: migration seams: any of these calls may re-pin a tenant's ring placement
#: and bump the routing epoch — a rank cached before the seam can name a
#: service the tenant has already migrated away from
_TPL109_POINTS = {
    "migrate",
    "migrate_tenant",
    "commit_migration",
    "rebalance",
    "resize",
    "recover_handoffs",
    "reassign",
}
#: routing reads whose cached result goes stale across a seam: the ring's
#: owner lookups (call form) and a census row's owner attribute (attr form)
_TPL109_CALLS = {"owner", "natural_owner"}
_TPL109_ATTRS = {"owner_rank"}


class RoutingEpochRule:
    """TPL109: tenant->rank routing read cached across a migration seam.

    The fleet layer (:mod:`tpumetrics.fleet`) moves tenants between
    evaluation services through zero-loss migrations; every seam —
    ``migrate``/``migrate_tenant`` directly, ``commit_migration`` at the
    handoff's commit point, ``rebalance``/``resize``/``recover_handoffs``
    in bulk, ``reassign`` on the ring itself — re-pins the routing ring and
    bumps its epoch.  A local that cached ``ring.owner(tid)`` (or an
    ``owner_rank`` census attribute) before the seam dangles after it: the
    rank it names may no longer host the tenant, and submitting there
    raises at best, double-routes at worst.  The safe shapes are (a) hold
    the controller's ``routing_lock`` across read AND use (migrations
    serialize on the same lock), or (b) re-read the owner after the seam.
    The fleet package itself is exempt — it IS the routing seam."""

    codes = ("TPL109",)

    def check(self, mod: ModuleInfo, index: PackageIndex) -> Iterator[Finding]:
        path = str(mod.path).replace("\\", "/")
        if "tpumetrics/fleet/" in path:
            return
        funcs: List[FuncInfo] = list(mod.functions.values())
        for ci in mod.classes.values():
            funcs.extend(ci.methods.values())
        for fi in funcs:
            yield from self._check_func(fi, mod)

    def _check_func(self, fi: FuncInfo, mod: ModuleInfo) -> Iterator[Finding]:
        # line spans of `with <...>.routing_lock:` bodies — reads and uses
        # inside one are serialized against migration by construction
        locked: List[Tuple[int, int]] = []
        for n in ast.walk(fi.node):
            if isinstance(n, (ast.With, ast.AsyncWith)):
                for item in n.items:
                    if self._terminal(item.context_expr) == "routing_lock":
                        locked.append((n.lineno, n.end_lineno or n.lineno))
                        break

        def in_lock(line: int) -> bool:
            return any(lo <= line <= hi for lo, hi in locked)

        binds: Dict[str, List[Tuple[int, bool, ast.expr]]] = {}
        points: List[int] = []
        uses: List[Tuple[str, ast.Name]] = []
        for n in ast.walk(fi.node):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 and isinstance(
                n.targets[0], ast.Name
            ):
                binds.setdefault(n.targets[0].id, []).append(
                    (n.lineno, self._routing_read(n.value), n.value)
                )
            elif isinstance(n, ast.Call) and self._terminal(n.func) in _TPL109_POINTS:
                points.append(n.lineno)
            elif isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                uses.append((n.id, n))
        if not points or not binds:
            return

        reported: Set[Tuple[str, int]] = set()
        for name, node in uses:
            history = binds.get(name)
            if not history:
                continue
            prior = [b for b in history if b[0] < node.lineno]
            if not prior:
                continue
            bind_line, tainted, _value = max(prior, key=lambda b: b[0])
            if not tainted:
                continue
            crossed = any(bind_line < p < node.lineno for p in points)
            if not crossed:
                continue
            if in_lock(bind_line) and in_lock(node.lineno):
                continue
            key = (name, bind_line)
            if key in reported:
                continue
            reported.add(key)
            yield Finding(
                "TPL109",
                f"`{name}` caches a tenant->rank routing read (bound at line "
                f"{bind_line}) and is used after a migration seam: the seam "
                "re-pins the ring and bumps the routing epoch, so the cached "
                "rank may no longer host the tenant. Hold routing_lock across "
                "the read and the use, or re-read the owner after the seam.",
                mod.path, node.lineno, node.col_offset, symbol=fi.qualname,
            )

    @classmethod
    def _routing_read(cls, expr: ast.expr) -> bool:
        # `rank = ring.owner(tid)[0]` caches through the subscript too
        if isinstance(expr, ast.Subscript):
            return cls._routing_read(expr.value)
        if isinstance(expr, ast.Call):
            func = expr.func
            if isinstance(func, ast.Attribute) and func.attr in _TPL109_CALLS:
                base = cls._terminal(func.value)
                return base is not None and "ring" in base.lower()
            return False
        if isinstance(expr, ast.Attribute) and expr.attr in _TPL109_ATTRS:
            return True
        return False

    @staticmethod
    def _terminal(expr: ast.expr) -> Optional[str]:
        if isinstance(expr, ast.Name):
            return expr.id
        if isinstance(expr, ast.Attribute):
            return expr.attr
        return None


#: the durability seam modules: every byte they persist must flow through
#: the storage shim (:mod:`tpumetrics.resilience.storage`), which owns
#: retry/backoff, errno classification, quarantine, and fault injection —
#: a bare write in a seam module silently opts out of all four
_TPL110_SEAMS = (
    "tpumetrics/runtime/snapshot.py",
    "tpumetrics/resilience/elastic.py",
    "tpumetrics/lifecycle/store.py",
    "tpumetrics/fleet/migrate.py",
)
#: the shim itself is the one sanctioned bare-write site
_TPL110_EXEMPT = ("tpumetrics/resilience/storage.py",)
#: rename/replace are the atomic-publish step — bypassing the shim there
#: skips the injector AND the post-replace durability fsync
_TPL110_RENAMES = {"os.replace", "os.rename"}
#: any of these mode characters makes an ``open`` write-capable
_TPL110_WRITE_MODES = frozenset("wax+")


class BareDurabilityWriteRule:
    """TPL110: a bare durability write bypassing the storage shim.

    The durability seam modules (``_TPL110_SEAMS`` — snapshot cuts, elastic
    cut groups, lifecycle spills, migration manifests) promise retry on
    transient I/O errors, typed classification of permanent ones,
    corruption quarantine, and seeded fault injection.  All four live in
    ONE place: :func:`tpumetrics.resilience.storage.atomic_write` /
    :func:`~tpumetrics.resilience.storage.run_with_retry`.  A direct
    ``open(path, "w"/"wb")``, ``os.replace`` or ``os.rename`` in a seam
    module writes bytes the shim never sees — it won't retry, won't latch
    durability degradation, and the chaos soak's fault plans can't reach
    it, so the write looks durable in every test and fails only in
    production.  Read-side opens are fine; the shim module itself is
    exempt (it IS the bare-write layer)."""

    codes = ("TPL110",)

    def check(self, mod: ModuleInfo, index: PackageIndex) -> Iterator[Finding]:
        path = str(mod.path).replace("\\", "/")
        if any(path.endswith(exempt) for exempt in _TPL110_EXEMPT):
            return
        if not any(path.endswith(seam) for seam in _TPL110_SEAMS):
            return
        if mod.tree is None:
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted_name(node.func, mod) or ""
            if dotted in _TPL110_RENAMES:
                yield Finding(
                    "TPL110",
                    f"`{dotted}` in a durability seam module bypasses the "
                    "storage shim: the atomic publish step never sees the "
                    "retry policy, the fault injector, or the post-replace "
                    "directory fsync. Route it through "
                    "tpumetrics.resilience.storage.atomic_write (or "
                    "run_with_retry for a bare rename).",
                    mod.path, node.lineno, node.col_offset,
                )
            elif dotted == "open" and self._write_mode(node):
                yield Finding(
                    "TPL110",
                    "write-capable `open` in a durability seam module "
                    "bypasses the storage shim: the bytes get no retry, no "
                    "errno classification, no durability-degradation latch, "
                    "and the soak's fault plans cannot reach them. Route "
                    "the write through "
                    "tpumetrics.resilience.storage.atomic_write.",
                    mod.path, node.lineno, node.col_offset,
                )

    @staticmethod
    def _write_mode(call: ast.Call) -> bool:
        mode: Optional[ast.expr] = None
        if len(call.args) >= 2:
            mode = call.args[1]
        for kw in call.keywords:
            if kw.arg == "mode":
                mode = kw.value
        if mode is None:
            return False  # default mode "r": read-side
        if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
            return any(ch in _TPL110_WRITE_MODES for ch in mode.value)
        return False  # dynamic mode: unknowable statically, stay quiet


#: the serving-layer modules whose entry points TPL106 rejects in update paths
_TPL106_MODULES = (
    "tpumetrics.telemetry.serve",
    "tpumetrics.telemetry.slo",
)
#: package-level re-exports of the same entry points
_TPL106_NAMES = {"start_admin_server", "AdminServer", "SloEngine"}
#: blocking device reads a handler/sampler path must never reach: each one
#: synchronizes the host thread with the device, which makes a scrape (or a
#: sampler tick) wait on an in-flight dispatch
_TPL106_BLOCKING_CALLS = {"jax.device_get", "jax.block_until_ready"}
_TPL106_BLOCKING_METHODS = {"block_until_ready", "item", "tolist"}
#: HTTP-handler dispatch methods (the stdlib BaseHTTPRequestHandler
#: convention): any of these on a handler class roots a serving path
_TPL106_HANDLER_METHODS = {"do_GET", "do_POST", "do_PUT", "do_HEAD", "do_DELETE"}
#: SLO sampler roots: the tick/run loop of an engine class
_TPL106_SAMPLER_METHODS = {"tick", "_run"}


class ServingLayerRule:
    """TPL106: the serving layer's two-sided trace-safety contract.

    Side (a) mirrors TPL104/TPL105 for the new plane: an admin server
    started — or an SLO engine constructed/armed — from ``update()``-
    reachable metric code would run at trace time only under jit (and spawn
    threads per retrace).  The serving plane lives BESIDE the stream
    (constructor / runtime seams), never inside a step.

    Side (b) extends the discipline to the handlers themselves: an admin
    HTTP handler (a ``do_GET``-family method on a
    ``BaseHTTPRequestHandler`` subclass, and everything module-locally
    reachable from one) or an SLO sampler loop (``tick``/``_run`` on an
    ``*SloEngine``-ish class) is a **strict host-side reader** — a
    ``jax.device_get``/``block_until_ready``/``.item()`` (or the
    host-syncing ``health.summarize``) reachable from one makes every
    scrape synchronize with whatever dispatch is in flight, which is
    precisely the stall the never-blocking ``stats()`` contract exists to
    prevent.  Reachability is module-local plus resolvable imports — the
    same resolution power the update-reachability pass has."""

    codes = ("TPL106",)

    def check(self, mod: ModuleInfo, index: PackageIndex) -> Iterator[Finding]:
        yield from self._check_update_reachable(mod, index)
        yield from self._check_serving_paths(mod, index)

    # ------------------------------------------------- (a) update() side

    def _check_update_reachable(
        self, mod: ModuleInfo, index: PackageIndex
    ) -> Iterator[Finding]:
        funcs: List[FuncInfo] = list(mod.functions.values())
        for ci in mod.classes.values():
            funcs.extend(ci.methods.values())
        for fi in funcs:
            if not index.is_update_reachable(fi.node):
                continue
            for n in ast.walk(fi.node):
                if not isinstance(n, ast.Call):
                    continue
                dotted = _import_resolved_dotted(n.func, mod)
                if dotted is None or not self._is_serving_entry(dotted):
                    continue
                yield Finding(
                    "TPL106",
                    f"serving-layer call `{_truncate(n)}` in update()-reachable "
                    "code: the admin server and the SLO engine live beside the "
                    "stream (constructed at the runtime seams), never inside a "
                    "step — under jit this would run at trace time only and "
                    "spawn a thread per retrace.",
                    mod.path, n.lineno, n.col_offset, symbol=fi.qualname,
                )

    @staticmethod
    def _is_serving_entry(dotted: str) -> bool:
        for m in _TPL106_MODULES:
            if dotted == m or dotted.startswith(m + "."):
                return True
        if dotted.startswith("tpumetrics.telemetry."):
            return dotted.rpartition(".")[2] in _TPL106_NAMES
        return dotted in _TPL106_NAMES

    # -------------------------------------------- (b) handler/sampler side

    def _serving_roots(self, mod: ModuleInfo) -> List[Tuple[ClassInfo, FuncInfo, str]]:
        roots: List[Tuple[ClassInfo, FuncInfo, str]] = []
        for ci in mod.classes.values():
            is_handler = any(
                b.rpartition(".")[2] == "BaseHTTPRequestHandler" for b in ci.bases
            )
            is_engine = ci.name.endswith("SloEngine") or ci.name == "SloEngine"
            for name, fi in ci.methods.items():
                if name in _TPL106_HANDLER_METHODS and (
                    is_handler or name.startswith("do_")
                ):
                    roots.append((ci, fi, "admin handler"))
                elif is_engine and name in _TPL106_SAMPLER_METHODS:
                    roots.append((ci, fi, "SLO sampler"))
        return roots

    def _check_serving_paths(
        self, mod: ModuleInfo, index: PackageIndex
    ) -> Iterator[Finding]:
        for ci, root, role in self._serving_roots(mod):
            table = index.method_table(ci)
            queue: List[FuncInfo] = [root]
            seen: set = set()
            while queue:
                fi = queue.pop()
                if id(fi.node) in seen:
                    continue
                seen.add(id(fi.node))
                yield from self._blocking_reads(fi, mod, role, root)
                for key in fi.callees:
                    nxt = (
                        table.get(key[1])
                        if key[0] == "s"
                        else index._resolve_call(fi, key)
                    )
                    if nxt is not None and id(nxt.node) not in seen:
                        queue.append(nxt)

    def _blocking_reads(
        self, fi: FuncInfo, mod: ModuleInfo, role: str, root: FuncInfo
    ) -> Iterator[Finding]:
        for n in ast.walk(fi.node):
            if not isinstance(n, ast.Call):
                continue
            blocked = None
            dotted = _import_resolved_dotted(n.func, mod)
            if dotted is not None and (
                dotted in _TPL106_BLOCKING_CALLS
                or (
                    dotted.startswith(_TPL105_MODULE + ".")
                    and dotted.rpartition(".")[2] in _TPL105_SYNC_NAMES
                )
            ):
                blocked = dotted
            elif (
                isinstance(n.func, ast.Attribute)
                and n.func.attr in _TPL106_BLOCKING_METHODS
            ):
                blocked = n.func.attr
            if blocked is None:
                continue
            yield Finding(
                "TPL106",
                f"blocking device read `{_truncate(n)}` reachable from the "
                f"{role} `{root.qualname}`: a scrape/sampler tick must never "
                "synchronize with an in-flight dispatch — serve the cached "
                "summary (the never-blocking stats() discipline) and leave "
                "device fetches to compute()-side readers.",
                mod.path, n.lineno, n.col_offset, symbol=fi.qualname,
            )


class PartitionRuleDeclRule:
    """TPL304: literal ``StatePartitionRules`` patterns that match no state
    declared anywhere in the analyzed package.

    Partition-rule regexes are matched at runtime against slash-joined state
    pytree paths (``"<leader>/<state>"``, buffer fields as
    ``"<state>/values"`` etc. — see ``tpumetrics/parallel/sharding.py``).  A
    rule whose pattern matches nothing is not an error at runtime — the
    state it meant to shard just stays silently replicated, which is exactly
    the kind of quiet perf/semantics drift a rename produces.  Only literal
    string patterns inside a literal list/tuple are decidable; patterns
    built programmatically (f-strings, ``re.escape``) are skipped."""

    codes = ("TPL304",)

    def _candidate_paths(self, index: PackageIndex) -> Set[str]:
        """Every path form a declared state can take in a state pytree:
        the bare name, class-qualified, and buffer-field variants.  Cached
        ON the index itself — rule instances are module-lifetime while a
        fresh index is built per analyze call, so an id()-keyed cache here
        would serve a freed index's candidates to a new index reusing the
        same address (allocation-order-dependent lint results)."""
        cached = getattr(index, "_tpl304_candidates", None)
        if cached is not None:
            return cached
        out: Set[str] = set()
        for mod in index.modules.values():
            for ci in mod.classes.values():
                for call, method in ci.add_state_calls:
                    for state in _state_names_of_call(ci, call, method):
                        out |= {
                            state,
                            f"{ci.name}/{state}",
                            f"{state}/values",
                            f"{state}/count",
                            f"{state}/requested",
                            f"{ci.name}/{state}/values",
                            f"{state}/0",
                        }
        index._tpl304_candidates = out  # type: ignore[attr-defined]
        return out

    @staticmethod
    def _leader_prefixed_match(pattern: str, candidates: Set[str]) -> bool:
        """Collection state paths are ``"<leader>/<path>"`` where the
        leader is a DYNAMIC collection key no static pass can know.  A
        pattern like ``"acc/tp"`` that fails against every candidate may
        still be live at runtime, so before flagging, retry each
        ``/``-suffix of the pattern (``"tp"``) ANCHORED at the start of a
        candidate — that is exactly where the tail would sit in a runtime
        ``"<leader>/" + <metric path>`` match, and anchoring keeps a tail
        like ``"values"`` from substring-matching ``"scores/values"`` and
        excusing a genuinely stale rule.  A hit means the failure is
        explained by an unknown leader prefix: undecidable, not stale."""
        import re as _re

        parts = pattern.split("/")
        for k in range(1, len(parts)):
            try:
                tail = _re.compile("/".join(parts[k:]))
            except _re.error:
                continue  # splitting broke the regex: try a shorter suffix
            if any(tail.match(c) for c in candidates):
                return True
        return False

    def check(self, mod: ModuleInfo, index: PackageIndex) -> Iterator[Finding]:
        if mod.tree is None:
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted_name(node.func, mod) or ""
            if dotted.rpartition(".")[2] != "StatePartitionRules":
                continue
            rules_arg: Optional[ast.expr] = node.args[0] if node.args else None
            for kw in node.keywords:
                if kw.arg == "rules":
                    rules_arg = kw.value
            if not isinstance(rules_arg, (ast.List, ast.Tuple)):
                continue  # programmatic rules: undecidable here
            candidates = self._candidate_paths(index)
            import re as _re

            for pair in rules_arg.elts:
                if not isinstance(pair, (ast.Tuple, ast.List)) or not pair.elts:
                    continue
                pat = pair.elts[0]
                if not (isinstance(pat, ast.Constant) and isinstance(pat.value, str)):
                    continue  # non-literal pattern: undecidable
                try:
                    compiled = _re.compile(pat.value)
                except _re.error as err:
                    yield Finding(
                        "TPL304",
                        f"partition rule pattern {pat.value!r} is not a valid regex: {err}.",
                        mod.path, pat.lineno, pat.col_offset,
                    )
                    continue
                if (
                    candidates
                    and not any(compiled.search(c) for c in candidates)
                    and not self._leader_prefixed_match(pat.value, candidates)
                ):
                    yield Finding(
                        "TPL304",
                        f"partition rule pattern {pat.value!r} matches no state declared "
                        "in this package: the state it meant to shard stays silently "
                        "replicated. Patterns match slash-joined state paths "
                        "('<leader>/<state>', buffer fields '<state>/values').",
                        mod.path, pat.lineno, pat.col_offset,
                    )


_WINDOWED_CLASSES = {
    "WindowedMean",
    "WindowedSum",
    "WindowedMax",
    "WindowedMin",
    "SketchQuantiles",
    "PSI",
    "KLDrift",
    "KSDistance",
    "DriftMonitor",
}
_WINDOW_KWARGS = ("window", "slots")


class WindowedWindowRule:
    """TPL305: a windowed-metric construction whose ``window``/``slots``
    argument is provably not a static int.

    Window length is state SHAPE (the ring of sub-window slots): a value
    derived from data — a call result, a subscript, a float — changes the
    compiled update's shapes, so every step retraces (the windowed runtime's
    whole point is a bounded compile universe).  The constructors reject
    traced values at runtime; this catches the host-side variants (e.g.
    ``window=int(batch.mean())``) at review time.  Bare names/attributes are
    config constants as far as a static pass can tell — undecidable,
    skipped, like TPL304's programmatic patterns."""

    codes = ("TPL305",)

    @staticmethod
    def _static_verdict(expr: ast.expr) -> str:
        """'static' (a compile-time int), 'dynamic' (provably not), or
        'unknown' (a name/attribute — could be a config constant)."""
        if isinstance(expr, ast.Constant):
            if isinstance(expr.value, bool) or not isinstance(expr.value, int):
                return "dynamic"  # float/str/bool window: never a valid length
            return "static"
        if isinstance(expr, ast.UnaryOp) and isinstance(expr.op, (ast.USub, ast.UAdd)):
            return WindowedWindowRule._static_verdict(expr.operand)
        if isinstance(expr, ast.BinOp):
            left = WindowedWindowRule._static_verdict(expr.left)
            right = WindowedWindowRule._static_verdict(expr.right)
            if "dynamic" in (left, right):
                return "dynamic"
            return "static" if left == right == "static" else "unknown"
        if isinstance(expr, (ast.Name, ast.Attribute)):
            return "unknown"
        return "dynamic"  # calls, subscripts, comprehensions, f-strings, ...

    def check(self, mod: ModuleInfo, index: PackageIndex) -> Iterator[Finding]:
        if mod.tree is None:
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted_name(node.func, mod) or _import_resolved_dotted(node.func, mod) or ""
            if dotted.rpartition(".")[2] not in _WINDOWED_CLASSES:
                continue
            args: List[Tuple[str, ast.expr]] = []
            for kw in node.keywords:
                if kw.arg in _WINDOW_KWARGS and kw.value is not None:
                    if isinstance(kw.value, ast.Constant) and kw.value.value is None:
                        continue  # window=None: the unwindowed sketch mode
                    args.append((kw.arg, kw.value))
            # Windowed* take window as the first positional argument
            if node.args and dotted.rpartition(".")[2].startswith("Windowed"):
                args.append(("window", node.args[0]))
            for name, expr in args:
                if self._static_verdict(expr) == "dynamic":
                    yield Finding(
                        "TPL305",
                        f"`{name}` of {_truncate(node)} is not a static int: window "
                        "length is state shape, so a data-dependent window changes "
                        "the traced shapes and retraces the update step every call. "
                        "Pick the window at construction (a literal or module "
                        "constant).",
                        mod.path, expr.lineno, expr.col_offset,
                    )


# --------------------------------------------------------------------------
# Concurrency rules (TPL120–TPL123): built on the thread-entry reachability
# oracle (core.PackageIndex.thread_reachable / signal_reachable) and the
# lock-context dataflow (analysis/locks.py).

#: the declared lock hierarchy — nesting DOWN this order is the designed
#: discipline and never a finding: the service lock (≡ the lifecycle
#: manager's residency lock, which IS the service lock by delegation) may
#: be held while the ledger lock is taken, and either while an instruments
#: lock is taken.  Tier is inferred from the lock identity's module/attr.
def _tpl120_tier(identity: str) -> Optional[int]:
    modpart = identity.split(":")[0]
    attr = identity.rpartition(".")[2]
    if "residency" in attr:
        return 0
    if ".runtime.service" in modpart or ".runtime.evaluator" in modpart:
        return 0
    if ".lifecycle." in modpart:
        return 0
    if ".telemetry.ledger" in modpart:
        return 1
    if ".telemetry.instruments" in modpart:
        return 2
    return None


def _tpl120_declared_order(held: str, acquired: str) -> bool:
    a, b = _tpl120_tier(held), _tpl120_tier(acquired)
    return a is not None and b is not None and a <= b


class LockOrderRule:
    """TPL120: lock-order inversions over the cross-module acquisition graph.

    Every acquisition site contributes edges ``held -> acquired``.  An edge
    that sits on a cycle (the acquired lock can, on some other path, be
    held while this edge's held lock is taken) is a potential deadlock: two
    threads entering the cycle from different sides block forever.  A
    non-reentrant lock acquired while already held is the one-lock special
    case (self-deadlock, no second thread needed).  Edges consistent with
    the declared hierarchy (service ≡ residency → ledger → instruments)
    are allowlisted — a cycle through them is reported only at its
    order-violating edge.  Lock identity follows ``self.<attr>`` declares
    and module globals (see :mod:`tpumetrics.analysis.locks`); within-
    function nesting only — a lock held across a call into another
    function that locks is not seen (documented approximation)."""

    codes = ("TPL120",)

    def _findings_by_path(self, index: PackageIndex) -> Dict[str, List[Finding]]:
        cached = getattr(index, "_tpl120_by_path", None)
        if cached is not None:
            return cached
        from tpumetrics.analysis import locks as _locks

        model = _locks.lock_model(index)
        funcs_by_id: Dict[int, Tuple[FuncInfo, ModuleInfo]] = {}
        for mod in index.modules.values():
            funcs: List[FuncInfo] = list(mod.functions.values())
            for ci in mod.classes.values():
                funcs.extend(ci.methods.values())
            for fi in funcs:
                funcs_by_id[id(fi.node)] = (fi, mod)
        # transitive acquire-sets: every lock a function may take itself or
        # via its (resolvable) callees — fixed-point over the call graph, so
        # "holds L, calls f, f acquires L" is seen across function boundaries
        callee_ids: Dict[int, Set[int]] = {}
        closure: Dict[int, Set[str]] = {}
        for nid, (fi, mod) in funcs_by_id.items():
            closure[nid] = {s.identity for s in model.acquisition_sites(fi, mod)}
            outs: Set[int] = set()
            table = index.method_table(fi.owner) if fi.owner is not None else {}
            for key in fi.callees:
                nxt = table.get(key[1]) if key[0] == "s" else index._resolve_call(fi, key)
                if nxt is not None and id(nxt.node) in funcs_by_id:
                    outs.add(id(nxt.node))
            callee_ids[nid] = outs
        changed = True
        while changed:
            changed = False
            for nid, outs in callee_ids.items():
                before = len(closure[nid])
                for c in outs:
                    closure[nid] |= closure[c]
                if len(closure[nid]) != before:
                    changed = True

        edges: Dict[Tuple[str, str], List[_locks.AcquisitionSite]] = {}
        for nid, (fi, mod) in funcs_by_id.items():
            for s in model.acquisition_sites(fi, mod):
                for h in s.held:
                    edges.setdefault((h, s.identity), []).append(s)
            # call-mediated edges: a call made while holding H acquires (via
            # the callee's transitive acquire-set) every lock in closure(c)
            table = index.method_table(fi.owner) if fi.owner is not None else {}
            for n in ast.walk(fi.node):
                if not isinstance(n, ast.Call):
                    continue
                held = model.held_at(fi, mod, n.lineno)
                if not held:
                    continue
                key = None
                f = n.func
                if isinstance(f, ast.Name):
                    key = ("n", f.id)
                elif isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
                    key = ("s", f.attr) if f.value.id == "self" else ("a", f.value.id, f.attr)
                if key is None:
                    continue
                nxt = table.get(key[1]) if key[0] == "s" else index._resolve_call(fi, key)
                if nxt is None or id(nxt.node) not in funcs_by_id:
                    continue
                for acquired in closure[id(nxt.node)]:
                    for h in held:
                        edges.setdefault((h, acquired), []).append(
                            _locks.AcquisitionSite(
                                acquired, n.lineno, n.col_offset, n.lineno,
                                (h,), fi.qualname, mod.path,
                            )
                        )
        graph: Dict[str, Set[str]] = {}
        for (a, b), _ in edges.items():
            graph.setdefault(a, set()).add(b)

        def _reaches(src: str, dst: str) -> bool:
            queue, seen = [src], {src}
            while queue:
                cur = queue.pop()
                if cur == dst:
                    return True
                for nxt in graph.get(cur, ()):
                    if nxt not in seen:
                        seen.add(nxt)
                        queue.append(nxt)
            return False

        out: Dict[str, List[Finding]] = {}
        for (held, acquired), sitelist in sorted(edges.items()):
            if held == acquired:
                if not model.is_reentrant(held):
                    for s in sitelist:
                        out.setdefault(s.path, []).append(
                            Finding(
                                "TPL120",
                                f"`{_short_lock(held)}` re-acquired while already "
                                "held: the lock is not reentrant, so this path "
                                "self-deadlocks (no second thread needed). Use an "
                                "RLock only if re-entry is truly the design; "
                                "usually the inner acquisition belongs in a "
                                "_locked variant of the callee.",
                                s.path, s.line, s.col, symbol=s.qualname,
                            )
                        )
                continue
            if _tpl120_declared_order(held, acquired):
                continue
            if not _reaches(acquired, held):
                continue
            back = edges.get((acquired, held))
            where = (
                f" (reverse order at {back[0].path}:{back[0].line})" if back else ""
            )
            for s in sitelist:
                out.setdefault(s.path, []).append(
                    Finding(
                        "TPL120",
                        f"lock-order inversion: `{_short_lock(s.identity)}` "
                        f"acquired while holding `{_short_lock(held)}`, but "
                        "another path nests them in the opposite order"
                        f"{where} — a concurrent pair of threads can deadlock. "
                        "Pick one order (or declare the hierarchy) and nest "
                        "consistently.",
                        s.path, s.line, s.col, symbol=s.qualname,
                    )
                )
        index._tpl120_by_path = out  # type: ignore[attr-defined]
        return out

    def check(self, mod: ModuleInfo, index: PackageIndex) -> Iterator[Finding]:
        if mod.tree is None:
            return
        yield from iter(self._findings_by_path(index).get(mod.path, []))


def _short_lock(identity: str) -> str:
    """``pkg.mod:Class.attr`` → ``Class.attr`` (messages stay readable)."""
    return identity.rpartition(":")[2]


class GuardedAttrRule:
    """TPL121: a guarded attribute accessed bare in thread-reachable code.

    The guarded-attribute sets come from the lock-context census: an
    attribute whose every non-constructor write happens under one lock is
    *consistently guarded* by it (a strict majority of writes, with bare
    writes in the minority, also qualifies — that is exactly the historical
    bug shape: N disciplined writers plus the one forgotten one).  A bare
    read or write of such an attribute in a **thread-reachable** method of
    the same class is then a torn-read/lost-update race.  Constructors are
    exempt (construction happens-before publication), as is code no thread
    root reaches — a deliberate join-outside-the-lock in a close() only
    the owner calls stays quiet."""

    codes = ("TPL121",)

    def check(self, mod: ModuleInfo, index: PackageIndex) -> Iterator[Finding]:
        if mod.tree is None:
            return
        from tpumetrics.analysis import locks as _locks

        model = _locks.lock_model(index)
        for ci in mod.classes.values():
            guarded = model.class_locks(ci, mod).consistently_guarded()
            if not guarded:
                continue
            for name, fi in ci.methods.items():
                if name in ("__init__", "__post_init__", "__del__"):
                    continue
                if not index.is_thread_reachable(fi.node):
                    continue
                root = index.thread_reachable[id(fi.node)]
                seen_lines: Set[Tuple[str, int]] = set()
                accesses = [
                    (attr, line, col)
                    for attr, line, col in _locks._attr_reads(fi.node)
                ] + [(attr, line, 0) for attr, line in _locks._attr_writes(fi.node)]
                for attr, line, col in accesses:
                    guard = guarded.get(attr)
                    if guard is None or (attr, line) in seen_lines:
                        continue
                    if guard in model.held_at(fi, mod, line):
                        continue
                    seen_lines.add((attr, line))
                    yield Finding(
                        "TPL121",
                        f"`self.{attr}` accessed without `{_short_lock(guard)}` "
                        f"in thread-reachable code (via {root}): every other "
                        f"write of `{attr}` holds that lock, so this access "
                        "races them (torn read / lost update). Take the lock, "
                        "or serve a snapshot captured under it.",
                        mod.path, line, col, symbol=fi.qualname,
                    )


#: calls a signal handler must never reach.  ``Event.set()`` is absent by
#: design — setting an event to wake a pre-spawned parked runner thread is
#: the sanctioned handler idiom (see runtime/drain.py).
_TPL122_LEDGER_TAILS = {"record_event", "mint_series", "close_series"}
_TPL122_BLOCKING_CALLS = {"time.sleep", "open", "io.open"}
_TPL122_BLOCKING_PREFIXES = (
    "requests.", "urllib.request.", "http.client.", "subprocess.", "socket.",
)


class SignalSafetyRule:
    """TPL122: async-signal-unsafe work reachable from an installed handler.

    A signal handler runs *on top of* whatever frame the interrupted thread
    was in.  Acquiring any lock can deadlock against the interrupted
    holder; ``Thread.start()`` takes CPython's own interpreter-level
    threading lock, so a handler that spawns its drain thread deadlocks
    against an in-flight ``start()`` (the PR-11 bug this rule
    retro-covers); blocking I/O stalls the whole process; a ledger write
    takes the ledger lock *and* does I/O.  The safe shape is: record the
    signum, ``Event.set()`` a pre-spawned parked runner, return.
    Reachability comes from the signal-entry oracle (``signal.signal`` /
    ``install_preemption_handler`` registrations, nested handler defs
    included)."""

    codes = ("TPL122",)

    def check(self, mod: ModuleInfo, index: PackageIndex) -> Iterator[Finding]:
        if mod.tree is None:
            return
        from tpumetrics.analysis import locks as _locks

        model = _locks.lock_model(index)
        funcs: List[FuncInfo] = list(mod.functions.values())
        for ci in mod.classes.values():
            funcs.extend(ci.methods.values())
        scanned: Set[int] = set()
        for fi in funcs:
            yield from self._scan(fi, mod, index, model, scanned)
        # nested defs registered as handlers (e.g. a `_handler` closed over
        # by its installer) — walk enclosing functions for nested FunctionDefs
        # that the oracle marked reachable
        for fi in funcs:
            for n in ast.walk(fi.node):
                if (
                    isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and n is not fi.node
                    and id(n) in index.signal_reachable
                ):
                    nested = _nested_func_info(n, fi)
                    yield from self._scan(nested, mod, index, model, scanned)

    def _scan(
        self,
        fi: FuncInfo,
        mod: ModuleInfo,
        index: PackageIndex,
        model: "object",
        scanned: Set[int],
    ) -> Iterator[Finding]:
        if id(fi.node) not in index.signal_reachable or id(fi.node) in scanned:
            return
        scanned.add(id(fi.node))
        root = index.signal_reachable[id(fi.node)]

        def _finding(n: ast.AST, what: str, fix: str) -> Finding:
            return Finding(
                "TPL122",
                f"{what} in signal-handler-reachable code ({root}): a handler "
                "preempts an arbitrary frame, so "
                f"{fix} Record the signum, `Event.set()` a pre-spawned parked "
                "runner thread, and return.",
                mod.path, n.lineno, n.col_offset, symbol=fi.qualname,
            )

        for site in model.acquisition_sites(fi, mod):  # type: ignore[attr-defined]
            yield Finding(
                "TPL122",
                f"lock `{_short_lock(site.identity)}` acquired in signal-"
                f"handler-reachable code ({root}): the interrupted thread may "
                "hold it, and it can never release while the handler runs — "
                "self-deadlock. Record the signum, `Event.set()` a pre-spawned "
                "parked runner thread, and return.",
                mod.path, site.line, site.col, symbol=fi.qualname,
            )
        for n in ast.walk(fi.node):
            if not isinstance(n, ast.Call):
                continue
            dotted = _import_resolved_dotted(n.func, mod) or ""
            tail = dotted.rpartition(".")[2]
            if dotted in ("threading.Thread", "Thread"):
                yield _finding(
                    n, "`Thread(...)` constructed",
                    "`Thread.start()` would take CPython's interpreter-level "
                    "threading lock and deadlock against any in-flight start.",
                )
            elif isinstance(n.func, ast.Attribute) and n.func.attr == "start":
                yield _finding(
                    n, f"`{_truncate(n)}`",
                    "`Thread.start()` takes CPython's interpreter-level "
                    "threading lock and deadlocks against any in-flight start.",
                )
            elif (
                dotted in _TPL122_BLOCKING_CALLS
                or dotted.startswith(_TPL122_BLOCKING_PREFIXES)
            ):
                yield _finding(
                    n, f"blocking call `{_truncate(n)}`",
                    "blocking I/O stalls the entire interrupted thread.",
                )
            elif (
                ".telemetry.ledger" in dotted
                or (
                    tail in _TPL122_LEDGER_TAILS
                    and dotted.startswith("tpumetrics.")
                )
            ):
                yield _finding(
                    n, f"ledger write `{_truncate(n)}`",
                    "the ledger write takes the ledger lock and appends to "
                    "sinks (I/O) — both forbidden in a handler.",
                )


def _nested_func_info(node: ast.AST, outer: FuncInfo) -> FuncInfo:
    """FuncInfo for a nested def (a closure handler) — owner carried from
    the enclosing function so ``self.<lock>`` still resolves."""
    from tpumetrics.analysis.core import _func_info

    return _func_info(node, outer.modname, outer.owner)


#: blocking calls TPL123 rejects while a declared lock is held
_TPL123_BLOCKING_CALLS = {"jax.device_get", "jax.block_until_ready", "time.sleep"}
_TPL123_BLOCKING_METHODS = {"item", "tolist", "block_until_ready"}
_TPL123_OPEN_CALLS = {"open", "io.open"}
_TPL123_BLOCKING_PREFIXES = (
    "requests.", "urllib.request.", "http.client.", "subprocess.",
)


class BlockingUnderLockRule:
    """TPL123: a blocking call while a declared lock is held.

    Every other reader and writer of that lock inherits the block: a
    device sync under the evaluator lock stalls `submit()` on another
    thread for the duration of an in-flight dispatch (the PR-15 `stats()`
    bug, fixed there with bounded acquisition + a cached snapshot — this
    rule generalizes that one call site to the whole repo).  Flagged while
    holding ANY declared lock, bounded spans included (the timeout caps
    the *acquisition* wait, not the time the holder then sits on the lock).
    ``Condition.wait()`` is exempt — it releases the lock while parked —
    as is a ``.wait()`` whose receiver resolves to a held condition/lock;
    an ``Event.wait()`` (which releases nothing) is flagged."""

    codes = ("TPL123",)

    def check(self, mod: ModuleInfo, index: PackageIndex) -> Iterator[Finding]:
        if mod.tree is None:
            return
        from tpumetrics.analysis import locks as _locks

        model = _locks.lock_model(index)
        funcs: List[FuncInfo] = list(mod.functions.values())
        for ci in mod.classes.values():
            funcs.extend(ci.methods.values())
        for fi in funcs:
            spans = model.held_spans(fi, mod)
            if not spans:
                continue
            for n in ast.walk(fi.node):
                if not isinstance(n, ast.Call):
                    continue
                held = model.held_at(fi, mod, n.lineno)
                if not held:
                    continue
                what = self._blocking(n, fi, mod, model, held)
                if what is None:
                    continue
                lock = sorted(held)[0]
                yield Finding(
                    "TPL123",
                    f"{what} while holding `{_short_lock(lock)}`: every other "
                    "reader/writer of that lock inherits the stall. Move the "
                    "blocking work outside the critical section, or serve a "
                    "cached snapshot (the bounded-lock stats() discipline).",
                    mod.path, n.lineno, n.col_offset, symbol=fi.qualname,
                )

    def _blocking(
        self,
        n: ast.Call,
        fi: FuncInfo,
        mod: ModuleInfo,
        model: "object",
        held: Set[str],
    ) -> Optional[str]:
        dotted = _import_resolved_dotted(n.func, mod) or ""
        if dotted in _TPL123_BLOCKING_CALLS:
            return f"blocking call `{_truncate(n)}`"
        if dotted in _TPL123_OPEN_CALLS:
            return f"file I/O `{_truncate(n)}`"
        if dotted.startswith(_TPL123_BLOCKING_PREFIXES):
            return f"network/subprocess call `{_truncate(n)}`"
        if isinstance(n.func, ast.Attribute):
            attr = n.func.attr
            if attr in _TPL123_BLOCKING_METHODS:
                return f"blocking device read `{_truncate(n)}`"
            if attr == "wait":
                # Condition.wait releases the held lock while parked — exempt
                # when the receiver resolves to a held lock/condition; an
                # Event.wait (releases nothing) or unknown receiver is flagged
                ident = model.resolve(n.func.value, fi, mod)  # type: ignore[attr-defined]
                if ident is None or ident not in held:
                    return f"`{_truncate(n)}`"
        return None


RULES = [
    TraceSafetyRule(),
    HostTelemetryRule(),
    HostHealthReadRule(),
    BackboneLifecycleRule(),
    ResidencyLifecycleRule(),
    RoutingEpochRule(),
    BareDurabilityWriteRule(),
    ServingLayerRule(),
    LockOrderRule(),
    GuardedAttrRule(),
    SignalSafetyRule(),
    BlockingUnderLockRule(),
    StateDeclRule(),
    ShadowStateRule(),
    PartitionRuleDeclRule(),
    WindowedWindowRule(),
]
