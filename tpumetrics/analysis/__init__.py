"""tpumetrics.analysis — static trace-safety & sync-schedule linter ("tpulint").

The package's correctness guarantees — "no host sync until ``.compute()``",
collectives in lockstep across ranks, every accumulator declared via
``add_state`` — are otherwise enforced only at runtime (the telemetry
lockstep verifier catches a divergent sync schedule when ranks actually
diverge on the wire; elastic fold/reshard silently loses undeclared state).
This subsystem rejects those bug classes *statically*: a pure-AST pass over
the source, on one CPU host, in milliseconds, with no jax import required
at analysis time.

Usage::

    python -m tpumetrics.analysis tpumetrics/            # text report, exit 1 on findings
    python -m tpumetrics.analysis --format json paths…   # machine-readable

Inline suppression (same line, or a standalone comment on the line above)::

    x = float(arr)  # tpulint: disable=TPL101 -- eager-only debug path

Rule catalog: see :mod:`tpumetrics.analysis.rules` and ``docs/analysis.md``.
"""

from tpumetrics.analysis.core import Finding, PackageIndex, analyze_paths, analyze_source
from tpumetrics.analysis.report import render_json, render_sarif, render_text
from tpumetrics.analysis.rules import RULES

__all__ = [
    "Finding",
    "PackageIndex",
    "RULES",
    "analyze_paths",
    "analyze_source",
    "render_json",
    "render_sarif",
    "render_text",
]
