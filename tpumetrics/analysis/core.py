"""Analyzer core: findings, suppressions, the package index, and the driver.

The analyzer is pure AST — it never imports the code under analysis (no jax
required at lint time, so it runs on a host-only CPU box in milliseconds).
It works in two passes:

1. **Index pass** (:class:`PackageIndex`): parse every file once and record,
   per module, its imports, module-level functions, and classes (bases,
   methods, ``add_state`` declarations, ``__init__`` attributes).  From
   that it resolves which classes are :class:`~tpumetrics.metric.Metric`
   subclasses (transitively, across modules) and computes the set of
   functions **reachable from any** ``update()`` — following ``self.m()``
   virtual dispatch through each concrete class's method table and bare /
   ``module.attr`` calls through the import graph.  This is what lets the
   host-sync rules flag a hazard inside a ``tpumetrics.functional`` helper
   three calls below ``update()`` while leaving ``compute()``-only code
   alone.
2. **Rule pass** (:mod:`tpumetrics.analysis.rules`): each registered rule
   walks the per-module ASTs with the index available and yields
   :class:`Finding`\\ s.

Known approximations (documented, deliberate): calls through variables
holding callables, ``getattr`` dispatch, and nested closures are not
followed; loop-carried taint is not fix-pointed.  The runtime lockstep
verifier (:mod:`tpumetrics.telemetry.lockstep`) remains the authoritative
dynamic check — tpulint is the cheap static complement.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

#: codes that may never be silenced (meta-findings about the lint run itself)
UNSUPPRESSABLE = ("TPL900", "TPL901", "TPL902")

#: thread-entry roots shared with the serving-layer rule: HTTP-handler
#: dispatch methods (stdlib BaseHTTPRequestHandler convention) and the
#: sampler/tick loops of SLO-engine-shaped classes
_THREAD_HANDLER_METHODS = {"do_GET", "do_POST", "do_PUT", "do_HEAD", "do_DELETE"}
_THREAD_SAMPLER_METHODS = {"tick", "_run"}

_SUPPRESS_RE = re.compile(
    r"#\s*tpulint:\s*(?P<kind>disable|disable-next)\s*="
    r"\s*(?P<codes>TPL[0-9]{3}(?:\s*,\s*TPL[0-9]{3})*)"
    r"(?:\s+--\s*(?P<why>\S.*))?"
)


@dataclass(frozen=True)
class Finding:
    """One analyzer finding, anchored to a source location.  ``end_line``
    is the last line of the enclosing statement (0 ⇒ same as ``line``):
    a trailing ``# tpulint: disable`` on ANY line of a multi-line statement
    suppresses the finding."""

    code: str
    message: str
    path: str
    line: int
    col: int
    symbol: str = ""
    suppressed: bool = False
    justification: str = ""
    end_line: int = 0

    def key(self) -> Tuple[str, int, int, str]:
        return (self.path, self.line, self.col, self.code)


@dataclass
class Suppression:
    line: int  # the source line the suppression APPLIES to
    codes: Set[str]
    justification: str
    comment_line: int  # where the comment itself lives (for TPL901)
    used: bool = False


@dataclass
class FuncInfo:
    """One function or method: its AST plus resolved-enough call edges."""

    name: str
    qualname: str
    modname: str
    node: ast.AST
    # edges: ("s", meth) self-call | ("n", name) bare call | ("a", base, attr)
    callees: Set[Tuple[str, ...]] = field(default_factory=set)
    owner: Optional["ClassInfo"] = None


@dataclass
class ClassInfo:
    name: str
    qualname: str
    modname: str
    node: ast.ClassDef
    bases: List[str] = field(default_factory=list)  # dotted, import-resolved
    methods: Dict[str, FuncInfo] = field(default_factory=dict)
    state_names: Set[str] = field(default_factory=set)
    # every self.add_state(...) call site: (call node, declaring method name)
    add_state_calls: List[Tuple[ast.Call, str]] = field(default_factory=list)
    init_attrs: Set[str] = field(default_factory=set)
    class_attrs: Set[str] = field(default_factory=set)
    property_names: Set[str] = field(default_factory=set)
    children: Set[str] = field(default_factory=set)  # qualified "mod:Class"


@dataclass
class ModuleInfo:
    modname: str
    path: str
    tree: Optional[ast.Module]
    lines: List[str]
    parse_error: Optional[SyntaxError] = None
    imports_mod: Dict[str, str] = field(default_factory=dict)  # alias -> dotted
    imports_from: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    functions: Dict[str, FuncInfo] = field(default_factory=dict)
    classes: Dict[str, ClassInfo] = field(default_factory=dict)
    suppressions: List[Suppression] = field(default_factory=list)


def _module_name(path: str) -> str:
    """Dotted module name, derived by walking up while ``__init__.py`` exists
    (so ``…/tpumetrics/image/fid.py`` → ``tpumetrics.image.fid`` regardless of
    the CWD the CLI ran from; a bare fixture file is just its stem)."""
    path = os.path.abspath(path)
    stem = os.path.splitext(os.path.basename(path))[0]
    parts = [] if stem == "__init__" else [stem]
    d = os.path.dirname(path)
    while os.path.isfile(os.path.join(d, "__init__.py")):
        parts.insert(0, os.path.basename(d))
        d = os.path.dirname(d)
    return ".".join(parts) or "<module>"


def _scan_suppressions(src: str) -> List[Suppression]:
    """Parse ``tpulint: disable`` directives from actual COMMENT tokens only
    (a docstring or string literal *quoting* the syntax is not a directive —
    raw-line matching produced phantom TPL901s for documentation)."""
    out: List[Suppression] = []
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(src).readline))
    except (tokenize.TokenizeError, SyntaxError, IndentationError):
        return out  # unparsable file: TPL900 covers it
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        i = tok.start[0]
        codes = {c.strip() for c in m.group("codes").split(",")}
        target = i + 1 if m.group("kind") == "disable-next" else i
        out.append(Suppression(target, codes, (m.group("why") or "").strip(), i))
    return out


class _CalleeCollector(ast.NodeVisitor):
    def __init__(self) -> None:
        self.callees: Set[Tuple[str, ...]] = set()

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Name):
            self.callees.add(("n", f.id))
        elif isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            if f.value.id == "self":
                self.callees.add(("s", f.attr))
            else:
                self.callees.add(("a", f.value.id, f.attr))
        self.generic_visit(node)


def _func_info(node: ast.AST, modname: str, owner: Optional[ClassInfo] = None) -> FuncInfo:
    coll = _CalleeCollector()
    for stmt in node.body:  # type: ignore[attr-defined]
        coll.visit(stmt)
    qual = f"{owner.name}.{node.name}" if owner else node.name  # type: ignore[attr-defined]
    return FuncInfo(node.name, qual, modname, node, coll.callees, owner)  # type: ignore[attr-defined]


def _literal_state_names(call: ast.Call, method: ast.AST) -> Set[str]:
    """State name(s) a ``self.add_state(name, …)`` call declares.  The name is
    usually a literal; the stat-scores idiom loops over a literal tuple
    (``for name in ("tp", "fp", …): self.add_state(name, …)``) — resolve that
    too by finding the enclosing ``for`` whose target binds the name arg."""
    args = call.args or []
    name_arg: Optional[ast.expr] = args[0] if args else None
    for kw in call.keywords:
        if kw.arg == "name":
            name_arg = kw.value
    if isinstance(name_arg, ast.Constant) and isinstance(name_arg.value, str):
        return {name_arg.value}
    if isinstance(name_arg, ast.Name):
        for loop in ast.walk(method):
            if (
                isinstance(loop, ast.For)
                and isinstance(loop.target, ast.Name)
                and loop.target.id == name_arg.id
                and isinstance(loop.iter, (ast.Tuple, ast.List))
            ):
                return {
                    e.value
                    for e in loop.iter.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                }
    return set()


def _self_attr_stores(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for n in ast.walk(fn):
        targets: List[ast.expr] = []
        if isinstance(n, ast.Assign):
            targets = list(n.targets)
        elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
            targets = [n.target]
        for t in targets:
            for el in ast.walk(t):
                if (
                    isinstance(el, ast.Attribute)
                    and isinstance(el.value, ast.Name)
                    and el.value.id == "self"
                ):
                    out.add(el.attr)
    return out


_PROPERTY_DECOS = {"property", "cached_property"}


def _is_property(fn: ast.AST) -> bool:
    for d in getattr(fn, "decorator_list", []):
        if isinstance(d, ast.Name) and d.id in _PROPERTY_DECOS:
            return True
        if isinstance(d, ast.Attribute) and d.attr in ("setter", "deleter", "getter"):
            return True
    return False


class PackageIndex:
    """Cross-file symbol index + ``update()``-reachability oracle."""

    def __init__(self) -> None:
        self.modules: Dict[str, ModuleInfo] = {}
        self.classes_by_name: Dict[str, List[ClassInfo]] = {}
        self._metric_like: Dict[int, bool] = {}
        self._ancestor_cache: Dict[int, List[ClassInfo]] = {}
        self._children: Optional[Dict[int, List[ClassInfo]]] = None
        self._broad_states: Dict[int, Set[str]] = {}
        self._declared_attrs: Dict[int, Set[str]] = {}
        self.update_reachable: Set[int] = set()  # id(func node)
        #: thread-entry oracle: id(func node) -> description of the concurrent
        #: root it is reachable from (Thread target, HTTP handler, sampler
        #: loop, soak worker loop).  Signal-handler reachability is tracked
        #: separately — a handler preempts ANY thread, so it is also a member
        #: of the thread-reachable set.
        self.thread_reachable: Dict[int, str] = {}
        self.signal_reachable: Dict[int, str] = {}

    # ------------------------------------------------------------- building
    @classmethod
    def from_files(cls, files: Sequence[str]) -> "PackageIndex":
        idx = cls()
        for path in files:
            idx._index_file(path)
        idx._compute_reachability()
        idx._compute_thread_reachability()
        return idx

    def _index_file(self, path: str) -> None:
        with open(path, "r", encoding="utf-8") as fh:
            src = fh.read()
        modname = _module_name(path)
        lines = src.splitlines()
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError as err:
            self.modules[modname] = ModuleInfo(modname, path, None, lines, parse_error=err)
            return
        mod = ModuleInfo(modname, path, tree, lines, suppressions=_scan_suppressions(src))
        for node in tree.body:
            self._index_toplevel(mod, node)
        self.modules[modname] = mod

    def _index_toplevel(self, mod: ModuleInfo, node: ast.stmt) -> None:
        if isinstance(node, ast.Import):
            for alias in node.names:
                mod.imports_mod[alias.asname or alias.name.partition(".")[0]] = (
                    alias.name if alias.asname else alias.name.partition(".")[0]
                )
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            if node.level:  # relative import: anchor on this module's package
                pkg = mod.modname.split(".")
                pkg = pkg[: len(pkg) - node.level]
                base = ".".join(pkg + ([node.module] if node.module else []))
            for alias in node.names:
                mod.imports_from[alias.asname or alias.name] = (base, alias.name)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            mod.functions[node.name] = _func_info(node, mod.modname)
        elif isinstance(node, ast.ClassDef):
            self._index_class(mod, node)

    def _index_class(self, mod: ModuleInfo, node: ast.ClassDef) -> None:
        ci = ClassInfo(node.name, f"{mod.modname}:{node.name}", mod.modname, node)
        for b in node.bases:
            dotted = self._resolve_base(mod, b)
            if dotted:
                ci.bases.append(dotted)
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if _is_property(item):
                    ci.property_names.add(item.name)
                else:
                    ci.methods[item.name] = _func_info(item, mod.modname, ci)
                if item.name in ("__init__", "__post_init__"):
                    ci.init_attrs |= _self_attr_stores(item)
                for sub in ast.walk(item):
                    if (
                        isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "add_state"
                        and isinstance(sub.func.value, ast.Name)
                        and sub.func.value.id == "self"
                    ):
                        ci.add_state_calls.append((sub, item.name))
                        ci.state_names |= _literal_state_names(sub, item)
            elif isinstance(item, ast.Assign):
                for t in item.targets:
                    if isinstance(t, ast.Name):
                        ci.class_attrs.add(t.id)
            elif isinstance(item, ast.AnnAssign) and isinstance(item.target, ast.Name):
                ci.class_attrs.add(item.target.id)
        mod.classes[node.name] = ci
        self.classes_by_name.setdefault(node.name, []).append(ci)

    def _resolve_base(self, mod: ModuleInfo, expr: ast.expr) -> Optional[str]:
        if isinstance(expr, ast.Name):
            if expr.id in mod.imports_from:
                m, orig = mod.imports_from[expr.id]
                return f"{m}.{orig}" if m else orig
            if expr.id in mod.classes:
                return f"{mod.modname}.{expr.id}"
            return expr.id
        if isinstance(expr, ast.Attribute):
            parts: List[str] = []
            cur: ast.expr = expr
            while isinstance(cur, ast.Attribute):
                parts.insert(0, cur.attr)
                cur = cur.value
            if isinstance(cur, ast.Name):
                head = mod.imports_mod.get(cur.id, cur.id)
                return ".".join([head] + parts)
        if isinstance(expr, ast.Subscript):  # Generic[...] bases
            return self._resolve_base(mod, expr.value)
        return None

    # ----------------------------------------------------------- hierarchy
    def _base_classinfos(self, ci: ClassInfo) -> List[ClassInfo]:
        out: List[ClassInfo] = []
        for dotted in ci.bases:
            modpart, _, name = dotted.rpartition(".")
            hit = None
            if modpart and modpart in self.modules:
                hit = self.modules[modpart].classes.get(name)
            if hit is None:
                cands = self.classes_by_name.get(name or dotted, [])
                hit = cands[0] if len(cands) >= 1 else None
            if hit is not None and hit is not ci:
                out.append(hit)
        return out

    def is_metric_like(self, ci: ClassInfo, _seen: Optional[Set[int]] = None) -> bool:
        if id(ci) in self._metric_like:
            return self._metric_like[id(ci)]
        seen = _seen or set()
        if id(ci) in seen:
            return False
        seen.add(id(ci))
        result = False
        for dotted in ci.bases:
            tail = dotted.rpartition(".")[2]
            if tail == "Metric":
                result = True
                break
        if not result:
            for base in self._base_classinfos(ci):
                if self.is_metric_like(base, seen):
                    result = True
                    break
        self._metric_like[id(ci)] = result
        return result

    def _ancestors(self, ci: ClassInfo) -> List[ClassInfo]:
        cached = self._ancestor_cache.get(id(ci))
        if cached is not None:
            return cached
        out: List[ClassInfo] = []
        queue, seen = [ci], {id(ci)}
        while queue:
            cur = queue.pop(0)
            for base in self._base_classinfos(cur):
                if id(base) not in seen:
                    seen.add(id(base))
                    out.append(base)
                    queue.append(base)
        self._ancestor_cache[id(ci)] = out
        return out

    def _child_map(self) -> Dict[int, List[ClassInfo]]:
        if self._children is None:
            self._children = {}
            for mod in self.modules.values():
                for ci in mod.classes.values():
                    for base in self._base_classinfos(ci):
                        self._children.setdefault(id(base), []).append(ci)
        return self._children

    def _descendants(self, ci: ClassInfo) -> List[ClassInfo]:
        children = self._child_map()
        out: List[ClassInfo] = []
        queue, seen = [ci], {id(ci)}
        while queue:
            cur = queue.pop(0)
            for kid in children.get(id(cur), []):
                if id(kid) not in seen:
                    seen.add(id(kid))
                    out.append(kid)
                    queue.append(kid)
        return out

    def broad_state_names(self, ci: ClassInfo) -> Set[str]:
        """``add_state`` names declared anywhere in the class's hierarchy
        (ancestors + itself + descendants): a method defined on an abstract
        base reads states its concrete subclasses declare."""
        if id(ci) not in self._broad_states:
            names = set(ci.state_names)
            for rel in self._ancestors(ci) + self._descendants(ci):
                names |= rel.state_names
            self._broad_states[id(ci)] = names
        return self._broad_states[id(ci)]

    def declared_attr_names(self, ci: ClassInfo) -> Set[str]:
        """Attributes the hierarchy legitimately owns besides states:
        ``__init__`` assignments, class-level attributes, properties."""
        if id(ci) not in self._declared_attrs:
            names: Set[str] = set()
            for rel in [ci] + self._ancestors(ci) + self._descendants(ci):
                names |= rel.init_attrs | rel.class_attrs | rel.property_names
            self._declared_attrs[id(ci)] = names
        return self._declared_attrs[id(ci)]

    # -------------------------------------------------------- reachability
    def method_table(self, ci: ClassInfo) -> Dict[str, FuncInfo]:
        table: Dict[str, FuncInfo] = {}
        for c in [ci] + self._ancestors(ci):
            for name, fi in c.methods.items():
                table.setdefault(name, fi)
        return table

    def _resolve_call(self, fi: FuncInfo, key: Tuple[str, ...]) -> Optional[FuncInfo]:
        mod = self.modules.get(fi.modname)
        if mod is None:
            return None
        if key[0] == "n":
            name = key[1]
            if name in mod.functions:
                return mod.functions[name]
            if name in mod.imports_from:
                tmod, orig = mod.imports_from[name]
                target = self.modules.get(tmod)
                if target:
                    return target.functions.get(orig)
        elif key[0] == "a":
            base, attr = key[1], key[2]
            dotted = mod.imports_mod.get(base)
            if dotted and dotted in self.modules:
                return self.modules[dotted].functions.get(attr)
        return None

    def _compute_reachability(self) -> None:
        for mod in self.modules.values():
            for ci in mod.classes.values():
                if not self.is_metric_like(ci):
                    continue
                table = self.method_table(ci)
                if "update" not in table:
                    continue
                queue: List[FuncInfo] = [table["update"]]
                seen: Set[int] = set()
                while queue:
                    fi = queue.pop()
                    if id(fi.node) in seen:
                        continue
                    seen.add(id(fi.node))
                    self.update_reachable.add(id(fi.node))
                    for key in fi.callees:
                        nxt = table.get(key[1]) if key[0] == "s" else self._resolve_call(fi, key)
                        if nxt is not None and id(nxt.node) not in seen:
                            queue.append(nxt)

    def is_update_reachable(self, node: ast.AST) -> bool:
        return id(node) in self.update_reachable

    # ------------------------------------------- thread-entry reachability
    #
    # The thread-entry oracle answers "can this function run on something
    # other than the caller's own thread?" — the precondition for the
    # concurrency rules (TPL120–TPL123).  Roots:
    #
    #   * functions/methods passed as ``threading.Thread(target=...)``
    #     (bare names, ``self.m``, and nested defs of the spawning function)
    #   * ``do_GET``-family HTTP handler methods (each request runs on a
    #     ThreadingHTTPServer worker thread)
    #   * sampler loops (``tick``/``_run`` of SLO-engine-shaped classes)
    #   * the soak worker's command loop (a separate *process*, but its
    #     telemetry objects are shared-shape with the supervisor's)
    #   * functions installed as signal handlers (``signal.signal``,
    #     ``install_preemption_handler`` callbacks) — tracked in the
    #     stricter ``signal_reachable`` set AND as thread roots, since a
    #     handler preempts whatever thread holds whatever lock
    #
    # Propagation follows the same call-edge graph as update-reachability;
    # the same documented approximations apply (callables in variables,
    # ``getattr`` dispatch, and attribute-chain receivers are not followed).

    def _callback_target(self, mod: ModuleInfo, fi: FuncInfo, expr: ast.expr) -> Optional[FuncInfo]:
        """Resolve a callback expression (a ``Thread`` target, a signal
        handler): a bare name (nested def of the registering function,
        module function, or ``from``-import) or ``self.m`` on the
        registering method's own class."""
        if isinstance(expr, ast.Name):
            for n in ast.walk(fi.node):
                if (
                    isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and n.name == expr.id
                    and n is not fi.node
                ):
                    return _func_info(n, mod.modname, fi.owner)
            if expr.id in mod.functions:
                return mod.functions[expr.id]
            if expr.id in mod.imports_from:
                tmod, orig = mod.imports_from[expr.id]
                target = self.modules.get(tmod)
                if target is not None:
                    return target.functions.get(orig)
        elif isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
            if expr.value.id == "self" and fi.owner is not None:
                return self.method_table(fi.owner).get(expr.attr)
        return None

    @staticmethod
    def _call_dotted(mod: ModuleInfo, expr: ast.expr) -> Optional[str]:
        """Import-resolved dotted name of a call target (the core-side twin
        of the rules module's resolver — core cannot import rules)."""
        parts: List[str] = []
        cur = expr
        while isinstance(cur, ast.Attribute):
            parts.insert(0, cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        head = cur.id
        if head in mod.imports_from:
            tmod, orig = mod.imports_from[head]
            head = f"{tmod}.{orig}" if tmod else orig
        else:
            head = mod.imports_mod.get(head, head)
        return ".".join([head] + parts)

    def _registration_roots(self, mod: ModuleInfo, fi: FuncInfo) -> List[Tuple[FuncInfo, str, bool]]:
        """(callback, description, is_signal) triples registered inside one
        function: ``Thread(target=...)`` spawns and signal-handler installs."""
        out: List[Tuple[FuncInfo, str, bool]] = []
        for n in ast.walk(fi.node):
            if not isinstance(n, ast.Call):
                continue
            dotted = self._call_dotted(mod, n.func) or ""
            if dotted == "threading.Thread" or dotted == "Thread":
                for kw in n.keywords:
                    if kw.arg == "target":
                        cb = self._callback_target(mod, fi, kw.value)
                        if cb is not None:
                            out.append(
                                (cb, f"thread target spawned in `{fi.qualname}`", False)
                            )
            elif dotted == "signal.signal":
                if len(n.args) >= 2:
                    cb = self._callback_target(mod, fi, n.args[1])
                    if cb is not None:
                        out.append(
                            (cb, f"signal handler installed in `{fi.qualname}`", True)
                        )
            elif dotted.rpartition(".")[2] == "install_preemption_handler":
                # any resolvable callable argument is treated as the handler
                for arg in list(n.args) + [k.value for k in n.keywords]:
                    cb = self._callback_target(mod, fi, arg)
                    if cb is not None:
                        out.append(
                            (cb, f"preemption handler installed in `{fi.qualname}`", True)
                        )
        return out

    def _thread_entry_roots(self) -> List[Tuple[FuncInfo, str, bool]]:
        roots: List[Tuple[FuncInfo, str, bool]] = []
        for mod in self.modules.values():
            for ci in mod.classes.values():
                is_handler = any(
                    b.rpartition(".")[2] == "BaseHTTPRequestHandler" for b in ci.bases
                )
                is_engine = ci.name.endswith("SloEngine")
                for name, mfi in ci.methods.items():
                    if name in _THREAD_HANDLER_METHODS and (
                        is_handler or name.startswith("do_")
                    ):
                        roots.append((mfi, f"HTTP handler `{mfi.qualname}`", False))
                    elif is_engine and name in _THREAD_SAMPLER_METHODS:
                        roots.append((mfi, f"sampler loop `{mfi.qualname}`", False))
            if mod.modname.endswith("soak.worker") and "main" in mod.functions:
                roots.append((mod.functions["main"], "soak worker loop", False))
            funcs: List[FuncInfo] = list(mod.functions.values())
            for ci in mod.classes.values():
                funcs.extend(ci.methods.values())
            for fi in funcs:
                roots.extend(self._registration_roots(mod, fi))
        return roots

    def _mark_reachable(self, root: FuncInfo, why: str, out: Dict[int, str]) -> None:
        queue: List[FuncInfo] = [root]
        while queue:
            fi = queue.pop()
            if id(fi.node) in out:
                continue
            out[id(fi.node)] = why
            table = self.method_table(fi.owner) if fi.owner is not None else {}
            for key in fi.callees:
                nxt = table.get(key[1]) if key[0] == "s" else self._resolve_call(fi, key)
                if nxt is not None and id(nxt.node) not in out:
                    queue.append(nxt)

    def _compute_thread_reachability(self) -> None:
        for root, why, is_signal in self._thread_entry_roots():
            self._mark_reachable(root, why, self.thread_reachable)
            if is_signal:
                self._mark_reachable(root, why, self.signal_reachable)

    def is_thread_reachable(self, node: ast.AST) -> bool:
        return id(node) in self.thread_reachable

    def is_signal_reachable(self, node: ast.AST) -> bool:
        return id(node) in self.signal_reachable


# ------------------------------------------------------------------ driver
def _collect_files(paths: Sequence[str]) -> List[str]:
    """Expand paths to .py files; a nonexistent path or an expansion that
    yields NOTHING raises — a typo'd CI invocation must not read as a clean
    lint run (exit 0 on zero files analyzed is the silent-green failure
    mode the tier-1 gates exist to prevent)."""
    files: List[str] = []
    for p in paths:
        if not os.path.exists(p):
            raise ValueError(f"path does not exist: {p}")
        if os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(d for d in dirs if d != "__pycache__")
                files.extend(os.path.join(root, n) for n in sorted(names) if n.endswith(".py"))
        elif p.endswith(".py"):
            files.append(p)
        else:
            raise ValueError(f"not a .py file or directory: {p}")
    if not files:
        raise ValueError(f"no .py files found under: {', '.join(map(str, paths))}")
    return files


def _apply_suppressions(findings: List[Finding], modules: Iterable[ModuleInfo]) -> List[Finding]:
    by_path: Dict[str, List[Suppression]] = {}
    for mod in modules:
        by_path[mod.path] = mod.suppressions
    out: List[Finding] = []
    for f in findings:
        hit = None
        if f.code not in UNSUPPRESSABLE:
            last = max(f.end_line, f.line)
            for sup in by_path.get(f.path, []):
                # a directive on ANY line of the finding's statement applies
                # (a trailing comment on a multi-line call sits on the last line)
                if f.line <= sup.line <= last and (f.code in sup.codes):
                    hit = sup
                    break
        if hit is not None:
            hit.used = True
            out.append(
                Finding(
                    f.code, f.message, f.path, f.line, f.col, f.symbol,
                    suppressed=True, justification=hit.justification,
                    end_line=f.end_line,
                )
            )
        else:
            out.append(f)
    for mod in modules:
        for sup in mod.suppressions:
            # a suppression is a claim someone audited the finding — require the why
            if not sup.justification:
                out.append(
                    Finding(
                        "TPL901",
                        "tpulint suppression without a justification: append "
                        "'-- <why this is safe>' to the disable comment",
                        mod.path,
                        sup.comment_line,
                        0,
                    )
                )
            # and a stale one (nothing left to silence) must be deleted, not
            # accumulate — the next edit on that line would be silently muted
            elif not sup.used:
                out.append(
                    Finding(
                        "TPL902",
                        "unused tpulint suppression: no "
                        f"{'/'.join(sorted(sup.codes))} finding on the target "
                        "line — delete the stale disable comment",
                        mod.path,
                        sup.comment_line,
                        0,
                    )
                )
    return out


def analyze_paths(
    paths: Sequence[str],
    select: Optional[Set[str]] = None,
    ignore: Optional[Set[str]] = None,
) -> List[Finding]:
    """Analyze ``paths`` (files and/or directories) and return all findings,
    suppressed ones included (callers filter on ``Finding.suppressed``)."""
    from tpumetrics.analysis.rules import RULES

    files = _collect_files(paths)
    index = PackageIndex.from_files(files)
    findings: List[Finding] = []
    for mod in index.modules.values():
        if mod.parse_error is not None:
            findings.append(
                Finding(
                    "TPL900",
                    f"syntax error: {mod.parse_error.msg}",
                    mod.path,
                    mod.parse_error.lineno or 1,
                    mod.parse_error.offset or 0,
                )
            )
            continue
        for rule in RULES:
            findings.extend(rule.check(mod, index))
    findings = _apply_suppressions(findings, list(index.modules.values()))
    if select:
        findings = [f for f in findings if f.code in select or f.code in UNSUPPRESSABLE]
    if ignore:
        findings = [f for f in findings if f.code not in ignore]
    seen: Set[Tuple[str, int, int, str]] = set()
    unique: List[Finding] = []
    for f in sorted(findings, key=lambda f: f.key()):
        if f.key() not in seen:
            seen.add(f.key())
            unique.append(f)
    return unique


def analyze_source(src: str, path: str = "<fixture>.py") -> List[Finding]:
    """Analyze one in-memory source blob (test/fixture convenience)."""
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        target = os.path.join(td, os.path.basename(path))
        with open(target, "w", encoding="utf-8") as fh:
            fh.write(src)
        found = analyze_paths([target])
    return [
        Finding(
            f.code, f.message, path, f.line, f.col, f.symbol,
            f.suppressed, f.justification, f.end_line,
        )
        for f in found
    ]
