"""Finding reporters: human text and machine JSON (round-trippable)."""

from __future__ import annotations

import json
from typing import Dict, List, Sequence

from tpumetrics.analysis.core import Finding


def render_text(findings: Sequence[Finding], show_suppressed: bool = False) -> str:
    lines: List[str] = []
    shown = [f for f in findings if show_suppressed or not f.suppressed]
    for f in shown:
        mark = " [suppressed]" if f.suppressed else ""
        sym = f" ({f.symbol})" if f.symbol else ""
        lines.append(f"{f.path}:{f.line}:{f.col}: {f.code}{mark}{sym} {f.message}")
    active = sum(1 for f in findings if not f.suppressed)
    muted = len(findings) - active
    lines.append(
        f"tpulint: {active} finding{'s' if active != 1 else ''}"
        + (f" ({muted} suppressed)" if muted else "")
    )
    return "\n".join(lines)


def render_json(findings: Sequence[Finding]) -> str:
    return json.dumps(
        {
            "version": 1,
            "findings": [
                {
                    "code": f.code,
                    "message": f.message,
                    "path": f.path,
                    "line": f.line,
                    "col": f.col,
                    "symbol": f.symbol,
                    "suppressed": f.suppressed,
                    "justification": f.justification,
                    "end_line": f.end_line,
                }
                for f in findings
            ],
            "counts": _counts(findings),
        },
        indent=2,
        sort_keys=True,
    )


def parse_json(text: str) -> List[Finding]:
    """Inverse of :func:`render_json` (the report round-trips losslessly)."""
    payload = json.loads(text)
    return [
        Finding(
            d["code"], d["message"], d["path"], d["line"], d["col"],
            d.get("symbol", ""), d.get("suppressed", False), d.get("justification", ""),
            d.get("end_line", 0),
        )
        for d in payload["findings"]
    ]


def _counts(findings: Sequence[Finding]) -> Dict[str, int]:
    out: Dict[str, int] = {"total": len(findings), "active": 0, "suppressed": 0}
    for f in findings:
        out["suppressed" if f.suppressed else "active"] += 1
        out[f.code] = out.get(f.code, 0) + 1
    return out
