"""Finding reporters: human text, machine JSON (round-trippable), and SARIF.

The JSON payload carries a ``version`` field; :func:`parse_json` rejects
any version it does not understand (:class:`ReportVersionError`) — a CI
consumer silently mis-reading a future payload shape is the same
silent-green failure mode the zero-files guard exists for.  SARIF 2.1.0
output (``--format sarif``) is the static-analysis interchange format PR
annotation tooling ingests; suppressed findings are carried as in-source
suppressions with their justifications, so an annotator can render them
greyed-out instead of dropping them.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Sequence

from tpumetrics.analysis.core import Finding

#: the JSON payload shape this module writes and can read back
PAYLOAD_VERSION = 1

#: SARIF pin: schema URI + spec version emitted by :func:`render_sarif`
SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


class ReportVersionError(ValueError):
    """A JSON report payload declares a version this reader cannot parse."""


def render_text(findings: Sequence[Finding], show_suppressed: bool = False) -> str:
    lines: List[str] = []
    shown = [f for f in findings if show_suppressed or not f.suppressed]
    for f in shown:
        mark = " [suppressed]" if f.suppressed else ""
        sym = f" ({f.symbol})" if f.symbol else ""
        lines.append(f"{f.path}:{f.line}:{f.col}: {f.code}{mark}{sym} {f.message}")
    active = sum(1 for f in findings if not f.suppressed)
    muted = len(findings) - active
    lines.append(
        f"tpulint: {active} finding{'s' if active != 1 else ''}"
        + (f" ({muted} suppressed)" if muted else "")
    )
    return "\n".join(lines)


def render_json(findings: Sequence[Finding]) -> str:
    return json.dumps(
        {
            "version": PAYLOAD_VERSION,
            "findings": [
                {
                    "code": f.code,
                    "message": f.message,
                    "path": f.path,
                    "line": f.line,
                    "col": f.col,
                    "symbol": f.symbol,
                    "suppressed": f.suppressed,
                    "justification": f.justification,
                    "end_line": f.end_line,
                }
                for f in findings
            ],
            "counts": _counts(findings),
        },
        indent=2,
        sort_keys=True,
    )


def parse_json(text: str) -> List[Finding]:
    """Inverse of :func:`render_json` (the report round-trips losslessly).

    Raises :class:`ReportVersionError` when the payload's ``version`` is
    missing or not one this reader understands — a consumer must never
    silently mis-read a future payload shape as an empty/clean run."""
    payload = json.loads(text)
    version = payload.get("version") if isinstance(payload, dict) else None
    if version != PAYLOAD_VERSION:
        raise ReportVersionError(
            f"unsupported tpulint report version {version!r} "
            f"(this reader understands version {PAYLOAD_VERSION}); "
            "regenerate the report with a matching tpumetrics checkout"
        )
    return [
        Finding(
            d["code"], d["message"], d["path"], d["line"], d["col"],
            d.get("symbol", ""), d.get("suppressed", False), d.get("justification", ""),
            d.get("end_line", 0),
        )
        for d in payload["findings"]
    ]


def render_sarif(findings: Sequence[Finding]) -> str:
    """SARIF 2.1.0 report: one run, one rule descriptor per catalog entry
    that actually fired, one result per finding.  Suppressed findings get
    a SARIF ``suppressions`` entry (``kind: inSource``) carrying the
    ``-- why`` justification instead of being dropped."""
    from tpumetrics.analysis.rules import CATALOG

    fired = sorted({f.code for f in findings})
    rules: List[Dict[str, Any]] = []
    for code in fired:
        name, desc = CATALOG.get(code, (code.lower(), ""))
        rules.append(
            {
                "id": code,
                "name": name,
                "shortDescription": {"text": desc or name},
            }
        )
    results: List[Dict[str, Any]] = []
    for f in findings:
        result: Dict[str, Any] = {
            "ruleId": f.code,
            "level": "error",
            "message": {"text": f.message},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {"uri": f.path},
                        "region": {
                            "startLine": max(f.line, 1),
                            # SARIF columns are 1-based; tpulint cols are 0-based
                            "startColumn": f.col + 1,
                        },
                    },
                }
            ],
        }
        if f.symbol:
            result["partialFingerprints"] = {"tpulint/symbol": f.symbol}
        if f.suppressed:
            suppression: Dict[str, Any] = {"kind": "inSource"}
            if f.justification:
                suppression["justification"] = f.justification
            result["suppressions"] = [suppression]
        results.append(result)
    payload = {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "tpulint",
                        "rules": rules,
                    }
                },
                "results": results,
            }
        ],
    }
    return json.dumps(payload, indent=2, sort_keys=True)


def _counts(findings: Sequence[Finding]) -> Dict[str, int]:
    out: Dict[str, int] = {"total": len(findings), "active": 0, "suppressed": 0}
    for f in findings:
        out["suppressed" if f.suppressed else "active"] += 1
        out[f.code] = out.get(f.code, 0) + 1
    return out
