"""Entry point for ``python -m tpumetrics.analysis``."""

import sys

from tpumetrics.analysis.cli import main

if __name__ == "__main__":
    sys.exit(main())
