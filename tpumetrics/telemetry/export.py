"""Export: Prometheus text exposition, JSONL dumps, and the flight recorder.

Three ways observability data leaves the process:

- :func:`prometheus_text` — the whole instruments registry (and, by
  default, the global ledger's aggregates as derived families) in
  Prometheus text exposition format, ready to serve from any ``/metrics``
  handler.  A round-trip validator test parses what this emits, so the
  exposition cannot silently drift from the format.
- :func:`spans_jsonl` / :func:`instruments_jsonl` — machine-readable JSON
  lines of the span ring / instrument registry.
- The **flight recorder** — a bounded in-memory ring of the most recent
  spans, ledger records, and incident marks that auto-dumps to a JSONL
  file when the runtime hits a fatal seam (tenant quarantine, dispatcher
  poison, crash-loop exhaustion); the raised error carries the dump path.
  Think cockpit voice recorder: nobody reads it until something crashes,
  and then the last N seconds are exactly what you need.

The flight recorder is opt-in (:func:`enable_flight_recorder`); while
enabled it installs itself as the span tracer's and ledger's forwarding
hook, so it sees traffic even when nobody else is recording — the ring is
the only cost (bounded, a few thousand dicts).  Dump files are JSON lines:
one ``flight_header`` line (reason, error, counters), then the ring oldest
→ newest, so the *tail* of the file is the most recent activity before the
incident.  Every line carries a ``type`` field from a closed set — the
JSONL round-trip validator test pins the schema.
"""

from __future__ import annotations

import itertools
import json
import os
import tempfile
import threading
from collections import deque
from typing import IO, Any, Dict, Iterator, List, Optional, Union

from tpumetrics.telemetry import instruments as _instruments
from tpumetrics.telemetry import ledger as _ledger
from tpumetrics.telemetry import spans as _spans

__all__ = [
    "FlightRecorder",
    "disable_flight_recorder",
    "enable_flight_recorder",
    "flight_dump",
    "flight_recorder",
    "instruments_jsonl",
    "note_incident",
    "perfetto_trace",
    "prometheus_text",
    "spans_jsonl",
]

ENV_FLIGHT_DIR = "TPUMETRICS_FLIGHT_DIR"

#: every JSONL line type a dump may contain (the round-trip validator and
#: any replay tooling key off this closed set)
FLIGHT_RECORD_TYPES = ("flight_header", "span", "ledger", "incident")


# ------------------------------------------------------------ prometheus text


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    f = float(v)
    return repr(int(f)) if f == int(f) else repr(f)


def _fmt_labels(names: tuple, values: tuple, extra: Optional[Dict[str, str]] = None) -> str:
    pairs = [(n, v) for n, v in zip(names, values)]
    if extra:
        pairs += list(extra.items())
    if not pairs:
        return ""
    body = ",".join(
        '%s="%s"' % (n, str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n"))
        for n, v in pairs
    )
    return "{" + body + "}"


def _prometheus_families() -> Iterator[str]:
    for inst in _instruments.registry():
        if inst.help:
            yield f"# HELP {inst.name} {inst.help}"
        yield f"# TYPE {inst.name} {inst.kind}"
        if inst.kind == "histogram":
            for lv, data in inst.collect():
                cum = 0
                for edge, c in data["buckets"]:
                    cum += c
                    yield (
                        f"{inst.name}_bucket"
                        f"{_fmt_labels(inst.labelnames, lv, {'le': _fmt_value(edge)})} {cum}"
                    )
                cum += data["overflow"]
                yield (
                    f"{inst.name}_bucket"
                    f"{_fmt_labels(inst.labelnames, lv, {'le': '+Inf'})} {cum}"
                )
                yield f"{inst.name}_sum{_fmt_labels(inst.labelnames, lv)} {_fmt_value(data['sum'])}"
                yield f"{inst.name}_count{_fmt_labels(inst.labelnames, lv)} {data['count']}"
        else:
            for lv, value in inst.collect():
                yield f"{inst.name}{_fmt_labels(inst.labelnames, lv)} {_fmt_value(value)}"


def _ledger_families() -> Iterator[str]:
    summ = _ledger.summary()
    yield "# TYPE tpumetrics_ledger_events_total counter"
    for kind in sorted(summ["counts_by_kind"]):
        yield (
            f"tpumetrics_ledger_events_total{_fmt_labels(('kind',), (kind,))} "
            f"{summ['counts_by_kind'][kind]}"
        )
    yield "# TYPE tpumetrics_ledger_collectives_total counter"
    yield f"tpumetrics_ledger_collectives_total {summ['collectives_issued']}"
    yield "# TYPE tpumetrics_ledger_wire_bytes_total counter"
    yield f"tpumetrics_ledger_wire_bytes_total {_fmt_value(summ['wire_bytes_total'])}"


def prometheus_text(include_ledger: bool = True) -> str:
    """The instruments registry (+ ledger aggregates) in Prometheus text
    exposition format.  The ledger's aggregate counters are exported as
    derived families (``tpumetrics_ledger_events_total{kind=…}`` etc.) —
    views over the same numbers ``telemetry.summary()`` reports, so one
    scrape covers both layers."""
    lines = list(_prometheus_families())
    if include_ledger:
        lines.extend(_ledger_families())
    return "\n".join(lines) + "\n"


# -------------------------------------------------------------- JSONL dumps


def _open_target(target: Union[str, IO[str]]):
    if isinstance(target, str):
        return open(target, "w"), True
    return target, False


def spans_jsonl(target: Union[str, IO[str]], span_list: Optional[List[Any]] = None) -> int:
    """Write spans (default: the current ring) as JSON lines; returns the
    line count."""
    if span_list is None:
        span_list = _spans.spans()
    fh, owns = _open_target(target)
    try:
        n = 0
        for sp in span_list:
            fh.write(json.dumps(sp.to_dict(), sort_keys=True, default=repr) + "\n")
            n += 1
        return n
    finally:
        if owns:
            fh.close()


def instruments_jsonl(target: Union[str, IO[str]]) -> int:
    """Write every registered instrument (name, labels, series) as JSON
    lines; returns the line count."""
    fh, owns = _open_target(target)
    try:
        n = 0
        for inst in _instruments.registry():
            fh.write(json.dumps(inst.to_dict(), sort_keys=True, default=repr) + "\n")
            n += 1
        return n
    finally:
        if owns:
            fh.close()


# ------------------------------------------------------------- perfetto trace


def _as_dict(obj: Any) -> Dict[str, Any]:
    return obj if isinstance(obj, dict) else obj.to_dict()


def _perfetto_span_events(
    span_dicts: List[Dict[str, Any]],
    ts_of,
    pid_of,
    tid_fallback: str = "spans",
) -> Iterator[Dict[str, Any]]:
    """Complete ("ph":"X") events for spans.  Track (tid) resolution: the
    root span of each trace names the stream/tenant it belongs to (the
    runtime stamps ``stream=`` on every batch root), and every child of
    that trace inherits the track — one track per tenant, as Perfetto
    renders it."""
    tid_by_trace: Dict[Any, str] = {}
    for sp in span_dicts:
        stream = sp.get("attrs", {}).get("stream")
        if stream is not None and sp.get("trace") not in tid_by_trace:
            tid_by_trace[sp["trace"]] = str(stream)
    for sp in span_dicts:
        start = ts_of(sp)
        end_ns = sp.get("end_ns")
        dur_us = (
            max(0.0, (end_ns - sp["start_ns"]) / 1e3) if end_ns is not None else 0.0
        )
        args = {k: repr(v) for k, v in sp.get("attrs", {}).items()}
        args.update(trace=sp.get("trace"), span=sp.get("span"), parent=sp.get("parent"))
        yield {
            "name": sp["name"],
            "cat": "span",
            "ph": "X",
            "ts": start,
            "dur": dur_us,
            "pid": pid_of(sp),
            "tid": tid_by_trace.get(sp.get("trace"), tid_fallback),
            "args": args,
        }


def _perfetto_ledger_events(
    record_dicts: List[Dict[str, Any]], ts_of, pid_of
) -> Iterator[Dict[str, Any]]:
    """Ledger records as slices/instants: an ``xla_compile`` event becomes a
    compile-mark slice (its ``seconds`` is a real duration, drawn ending at
    the record's stamp); payload-carrying collectives become short device
    slices on a per-kind track; bookkeeping events are instants."""
    for rec in record_dicts:
        kind = rec.get("kind", "event")
        ts = ts_of(rec)
        args = {
            k: rec[k]
            for k in ("op", "tag", "world_size", "wire_bytes", "source", "rank")
            if k in rec and rec[k] not in ("", 0, None)
        }
        args.update(rec.get("extra", {}))
        if kind == "xla_compile":
            secs = float(rec.get("extra", {}).get("seconds", 0.0) or 0.0)
            dur_us = secs * 1e6
            yield {
                "name": "xla_compile",
                "cat": "compile",
                "ph": "X",
                "ts": max(0.0, ts - dur_us),
                "dur": dur_us,
                "pid": pid_of(rec),
                "tid": "compiles",
                "args": args,
            }
        elif rec.get("source") in ("backend", "reducer", "spmd"):
            yield {
                "name": f"{kind}:{rec.get('op', '')}",
                "cat": "collective",
                "ph": "X",
                "ts": ts,
                "dur": 1.0,  # payload ops render as visible 1us slices
                "pid": pid_of(rec),
                "tid": "collectives",
                "args": args,
            }
        else:
            yield {
                "name": kind,
                "cat": "ledger",
                "ph": "i",
                "s": "p",  # process-scoped instant
                "ts": ts,
                "pid": pid_of(rec),
                "tid": "events",
                "args": args,
            }


def perfetto_trace(
    target: Union[None, str, IO[str]] = None,
    *,
    span_list: Optional[List[Any]] = None,
    record_list: Optional[List[Any]] = None,
    rank_of=None,
    process_names: Optional[Dict[int, str]] = None,
) -> Union[Dict[str, Any], str]:
    """Chrome trace-event JSON (the format Perfetto / ``chrome://tracing``
    open directly) over spans + ledger records.

    Defaults to the live process: the span ring and the global ledger's
    records, as ``pid 0``.  Every span becomes one complete ("X") slice
    (one track per tenant — the batch root's ``stream`` attribute names
    the track, children inherit it), every ``xla_compile`` ledger event a
    compile-mark slice, every payload collective a device slice, and every
    other ledger record an instant — **each input exactly once**, sorted by
    timestamp (the round-trip validator pins all of this).

    ``rank_of`` maps a span/record dict to its process row (pid) — the
    multi-rank merge (:mod:`tpumetrics.telemetry.timeline`) passes the
    rank, so a whole soak opens as one Perfetto view with one process per
    rank; ``process_names`` adds ``process_name`` metadata per pid.
    Timestamps are monotonic-clock microseconds unless the caller's dicts
    carry ``t_global_ns`` (the timeline's wall-aligned axis), which wins.

    ``target=None`` returns the trace dict; a path/handle writes JSON and
    returns the path (for a handle: the dict)."""
    if span_list is None:
        span_list = _spans.spans()
    if record_list is None:
        record_list = list(_ledger.get_ledger().records)
    span_dicts = [_as_dict(s) for s in span_list]
    record_dicts = [_as_dict(r) for r in record_list]

    def ts_of_span(sp: Dict[str, Any]) -> float:
        if "t_global_ns" in sp:
            return sp["t_global_ns"] / 1e3
        return sp["start_ns"] / 1e3

    def ts_of_rec(rec: Dict[str, Any]) -> float:
        if "t_global_ns" in rec:
            return rec["t_global_ns"] / 1e3
        return rec.get("mono_ns", 0) / 1e3

    if rank_of is None:
        rank_of = lambda d: int(d.get("rank", 0))  # noqa: E731

    events = list(_perfetto_span_events(span_dicts, ts_of_span, rank_of))
    events.extend(_perfetto_ledger_events(record_dicts, ts_of_rec, rank_of))
    events.sort(key=lambda e: (e["ts"], e["pid"], str(e["tid"])))
    pids = sorted({e["pid"] for e in events})
    meta = []
    for pid in pids:
        name = (process_names or {}).get(pid, f"rank {pid}" if pids != [0] else "process")
        meta.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "args": {"name": name},
            }
        )
    trace = {"traceEvents": meta + events, "displayTimeUnit": "ms"}
    if target is None:
        return trace
    fh, owns = _open_target(target)
    try:
        json.dump(trace, fh, sort_keys=True, default=repr)
    finally:
        if owns:
            fh.close()
    return target if isinstance(target, str) else trace


# ------------------------------------------------------------ flight recorder


class FlightRecorder:
    """Bounded ring of recent observability records, dumped on incidents.

    While installed (:func:`enable_flight_recorder`) it receives every
    finished span and every ledger record regardless of whether span
    tracing or the ledger is otherwise enabled — the ring is cheap and the
    whole point is having the last seconds of context when something dies
    unobserved.  :meth:`dump` writes the ring to a JSONL file (oldest
    first — the file's tail is the newest activity) and returns the path,
    which the runtime splices into the raised error's message.
    """

    def __init__(self, directory: str, capacity: int = 2048) -> None:
        if int(capacity) <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self.directory = directory
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=int(capacity))
        self._seq = 0
        self._dumps = 0

    @property
    def capacity(self) -> int:
        return self._ring.maxlen  # type: ignore[return-value]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # ------------------------------------------------------------ recording

    def _append(self, entry: Dict[str, Any]) -> None:
        with self._lock:
            self._seq += 1
            entry["seq"] = self._seq
            self._ring.append(entry)

    def record_span(self, sp: Any) -> None:
        self._append(sp.to_dict())

    def record_ledger(self, rec: Any) -> None:
        entry = rec.to_dict()
        entry["type"] = "ledger"
        self._append(entry)

    def note(self, kind: str, **info: Any) -> None:
        """Mark a non-fatal incident (a sync timeout, a fence) in the ring."""
        self._append({"type": "incident", "kind": kind, **info})

    def entries(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(e) for e in self._ring]

    # -------------------------------------------------------------- dumping

    def dump(self, reason: str, error: Optional[BaseException] = None, **info: Any) -> str:
        """Write the ring (oldest → newest) to a fresh JSONL file under
        ``directory``; returns the path.  Names carry the pid and a
        PROCESS-wide dump sequence (not per-recorder: re-enabling a
        recorder over a fixed directory must never reuse a name and
        silently overwrite an earlier incident's forensics)."""
        with self._lock:
            entries = [dict(e) for e in self._ring]
            self._dumps += 1
        n = next(_DUMP_IDS)
        os.makedirs(self.directory, exist_ok=True)
        path = os.path.join(
            self.directory, f"flight-{os.getpid()}-{n:04d}-{reason}.jsonl"
        )
        header = {
            "type": "flight_header",
            "reason": reason,
            "error": repr(error) if error is not None else None,
            "entries": len(entries),
            **info,
        }
        with open(path, "w") as fh:
            fh.write(json.dumps(header, sort_keys=True, default=repr) + "\n")
            for e in entries:
                fh.write(json.dumps(e, sort_keys=True, default=repr) + "\n")
        return path


_RECORDER: Optional[FlightRecorder] = None
#: process-wide dump numbering — survives recorder replacement, so a fixed
#: $TPUMETRICS_FLIGHT_DIR accumulates incidents instead of overwriting them
_DUMP_IDS = itertools.count(1)


def flight_recorder() -> Optional[FlightRecorder]:
    """The installed :class:`FlightRecorder`, or ``None``."""
    return _RECORDER


def enable_flight_recorder(
    directory: Optional[str] = None, capacity: int = 2048
) -> FlightRecorder:
    """Install a flight recorder.  ``directory`` resolves: argument →
    ``$TPUMETRICS_FLIGHT_DIR`` → a fresh temp directory.  Installs the span
    and ledger forwarding hooks; idempotent reconfiguration replaces the
    previous recorder (its ring is dropped, dump files stay)."""
    global _RECORDER
    directory = directory or os.environ.get(ENV_FLIGHT_DIR) or tempfile.mkdtemp(
        prefix="tpumetrics-flight-"
    )
    rec = FlightRecorder(os.path.abspath(directory), capacity=capacity)
    _RECORDER = rec
    _spans._FLIGHT_HOOK = rec.record_span
    _ledger._FLIGHT_HOOK = rec.record_ledger
    return rec


def disable_flight_recorder() -> None:
    global _RECORDER
    _RECORDER = None
    _spans._FLIGHT_HOOK = None
    _ledger._FLIGHT_HOOK = None


def flight_dump(reason: str, error: Optional[BaseException] = None, **info: Any) -> Optional[str]:
    """Dump the flight ring on a fatal incident; returns the file path, or
    ``None`` when no recorder is installed (the runtime's call sites are
    one ``is-None`` test when flight recording is off)."""
    rec = _RECORDER
    if rec is None:
        return None
    rec.note(reason, error=repr(error) if error is not None else None, **info)
    return rec.dump(reason, error=error, **info)


def note_incident(kind: str, **info: Any) -> None:
    """Mark a non-fatal incident in the flight ring (no dump)."""
    rec = _RECORDER
    if rec is not None:
        rec.note(kind, **info)
