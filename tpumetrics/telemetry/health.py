"""In-trace metric-state health: NaN/inf/saturation counters on device.

The compute-time non-finite guard (``guard_non_finite``) discovers a
poisoned state only when it is already being served or snapshotted.  The
**health probe** closes that gap from inside the device program: with
``health_probe=True`` a :class:`~tpumetrics.parallel.fuse_update.
FusedCollectionStep` appends :func:`probe_tree` — pure ``jnp`` reductions
over the *new* state — to every step it compiles, so each dispatch also
yields a tiny counter pytree (one ``(3,)`` int32 vector per state leaf:
``[nan, inf, saturated]``) describing the state it just produced.

Trace-safety argument (the contract ``docs/observability.md`` documents):

- the probe reads only the state the transition already produced — it adds
  reductions to the SAME XLA program, no second dispatch;
- its outputs stay **on device** next to the state; nothing here calls
  ``device_get``/``float()``/``item()``, so arming the probe adds **zero
  device→host transfers** to the steady-state loop.  The counters ride
  down on the host fetches ``compute()``/``stats()`` already make
  (:func:`summarize` is the ONLY host-syncing entry point, and tpulint
  TPL105 rejects it in ``update()``-reachable metric code);
- the state-transition subgraph is untouched — the probe's reductions are
  pure consumers of the output leaves, so a probed and an unprobed step
  produce **bit-identical** metric state (pinned by the parity test and
  the ``device_observability`` bench assert).

Semantics: the probe describes the CURRENT state, not a running total — a
leaf's ``nan`` count is "NaN elements in this state now".  Corruption is
monotone in practice (a NaN accumulator stays NaN), and the runtime latches
the first nonzero reading into one ``state_health`` ledger event per
(stream, state) plus the ``tpumetrics_state_nonfinite_total{stream,state}``
series, so a poisoned stream pages exactly once, *before* compute.

Saturation: a float leaf element counts as saturated when it is finite but
``|x| >= SATURATION_FRACTION * finfo(dtype).max`` (the last stop before
inf — fp16/bf16 accumulators overflow long before f32 ones); an integer
element when it sits exactly at its dtype's min/max (a clamped counter).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Set, Tuple

from tpumetrics.telemetry import instruments as _instruments
from tpumetrics.telemetry import ledger as _ledger

__all__ = [
    "SATURATION_FRACTION",
    "flatten",
    "probe_packed",
    "probe_tree",
    "publish_health",
    "release_health",
    "state_paths",
    "summarize",
]

_NONFINITE_GAUGE = _instruments.gauge(
    _instruments.STATE_NONFINITE,
    help="non-finite (NaN+inf) elements currently in the stream's metric state",
    labels=("stream", "state"),
)

#: |x| >= this fraction of the dtype's max counts as saturated (finite
#: values only — inf has its own counter)
SATURATION_FRACTION = 0.99


def _probe_leaf(leaf: Any) -> Any:
    """(3,) int32 ``[nan, inf, saturated]`` for one array leaf (pure jnp —
    safe inside any trace).  Non-numeric / non-array leaves probe as zeros."""
    import jax.numpy as jnp

    try:
        arr = jnp.asarray(leaf)
    except (TypeError, ValueError):
        return jnp.zeros((3,), jnp.int32)
    if arr.dtype == jnp.bool_:
        return jnp.zeros((3,), jnp.int32)
    if jnp.issubdtype(arr.dtype, jnp.floating) or jnp.issubdtype(arr.dtype, jnp.complexfloating):
        finfo = jnp.finfo(arr.dtype)
        mag = jnp.abs(arr)
        nan = jnp.sum(jnp.isnan(arr), dtype=jnp.int32)
        inf = jnp.sum(jnp.isinf(arr), dtype=jnp.int32)
        sat = jnp.sum(
            jnp.isfinite(arr) & (mag >= SATURATION_FRACTION * float(finfo.max)),
            dtype=jnp.int32,
        )
        return jnp.stack([nan, inf, sat])
    if jnp.issubdtype(arr.dtype, jnp.integer):
        iinfo = jnp.iinfo(arr.dtype)
        sat = jnp.sum((arr == iinfo.min) | (arr == iinfo.max), dtype=jnp.int32)
        zero = jnp.zeros((), jnp.int32)
        return jnp.stack([zero, zero, sat])
    return jnp.zeros((3,), jnp.int32)


def probe_tree(state: Any) -> Any:
    """Mirror ``state``'s pytree structure with a ``(3,)`` int32
    ``[nan, inf, saturated]`` vector per leaf.  Pure ``jnp`` reductions —
    designed to be appended to an existing jitted step, where XLA fuses the
    probe into the program it already built.  NamedTuple nodes — the
    :class:`~tpumetrics.buffers.MaskedBuffer` state kind — rebuild
    positionally (``type(state)(*children)``; the generator form would call
    the NamedTuple constructor with one argument)."""
    if isinstance(state, dict):
        return {k: probe_tree(v) for k, v in state.items()}
    if isinstance(state, tuple) and hasattr(state, "_fields"):
        return type(state)(*(probe_tree(v) for v in state))
    if isinstance(state, (list, tuple)):
        return type(state)(probe_tree(v) for v in state)
    return _probe_leaf(state)


def probe_packed(state: Any) -> Any:
    """:func:`probe_tree` packed into ONE ``(N, 3)`` int32 array (rows in
    :func:`state_paths` order).  This is what the runtime's probed step
    programs emit: a single extra output buffer per dispatch instead of one
    per state leaf — the probe's host-side dispatch overhead is one array
    handle regardless of how many states the collection holds."""
    import jax.numpy as jnp

    rows = [vec for _path, vec in flatten(probe_tree(state))]
    if not rows:
        return jnp.zeros((0, 3), jnp.int32)
    return jnp.stack(rows)


def state_paths(state: Any) -> List[str]:
    """The slash-joined leaf paths of ``state`` in packed-row order — the
    label vocabulary a packed probe's rows map onto.  Deliberately THE SAME
    traversal as :func:`flatten` (``probe_tree`` mirrors the state's pytree
    structure, so flattening the state IS flattening the probe): one
    recursion defines the row order, nothing to keep in sync."""
    return [path for path, _leaf in flatten(state)]


def flatten(tree: Any, prefix: str = "") -> List[Tuple[str, Any]]:
    """``[("leader/attr", leaf), ...]`` — slash-joined leaf paths in stable
    (sorted-dict) order; the label vocabulary of the
    ``tpumetrics_state_nonfinite_total{stream,state}`` series.  NamedTuple
    nodes (the :class:`~tpumetrics.buffers.MaskedBuffer` state kind) name
    their components by FIELD (``rows/values``), matching the buffer-field
    path convention of ``parallel/sharding.py``."""
    out: List[Tuple[str, Any]] = []
    if isinstance(tree, dict):
        for k in sorted(tree):
            path = f"{prefix}/{k}" if prefix else str(k)
            out.extend(flatten(tree[k], path))
        return out
    if isinstance(tree, tuple) and hasattr(tree, "_fields"):
        for name, v in zip(tree._fields, tree):
            path = f"{prefix}/{name}" if prefix else str(name)
            out.extend(flatten(v, path))
        return out
    if isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            path = f"{prefix}/{i}" if prefix else str(i)
            out.extend(flatten(v, path))
        return out
    return [(prefix or "<state>", tree)]


def summarize(
    health: Optional[Any], paths: Optional[List[str]] = None
) -> Dict[str, Any]:
    """Fetch a device health probe result and fold it to a host summary::

        {"per_state": {"acc/tp": {"nan": 0, "inf": 2, "saturated": 0,
                                  "nonfinite": 2}, ...},
         "nonfinite_total": 2, "saturated_total": 0}

    ``health`` is either a :func:`probe_tree` pytree, or — the runtime's
    form — a :func:`probe_packed` ``(N, 3)`` array with ``paths`` naming
    its rows (:func:`state_paths` of the probed state).

    THE host-syncing read of the health layer (one ``device_get`` of a few
    int32 counters): call it from ``stats()``/``compute()``-side code only —
    tpulint TPL105 rejects it in ``update()``-reachable metric code, where
    it would force a device sync per step.  ``None`` (no probed step ran
    yet) summarizes as all-zero."""
    if health is None:
        return {"per_state": {}, "nonfinite_total": 0, "saturated_total": 0}
    import jax

    if paths is not None:
        packed = jax.device_get(health)
        pairs = list(zip(paths, packed))
    else:
        pairs = flatten(jax.device_get(health))
    per_state: Dict[str, Dict[str, int]] = {}
    nonfinite_total = 0
    saturated_total = 0
    for path, vec in pairs:
        nan, inf, sat = (int(v) for v in vec)
        per_state[path] = {
            "nan": nan, "inf": inf, "saturated": sat, "nonfinite": nan + inf,
        }
        nonfinite_total += nan + inf
        saturated_total += sat
    return {
        "per_state": per_state,
        "nonfinite_total": nonfinite_total,
        "saturated_total": saturated_total,
    }


def publish_health(stream: str, summary: Dict[str, Any], alerted: Set[str]) -> None:
    """Latch a health summary into the telemetry stack for one stream:

    - a state path whose non-finite count is nonzero for the FIRST time
      emits ONE ``state_health`` ledger event naming the stream, the state,
      and the counts (the page an operator gets *before* the compute-time
      non-finite guard trips), and joins ``alerted``;
    - every alerted-or-corrupt path keeps its
      ``tpumetrics_state_nonfinite_total{stream,state}`` series current (a
      restored-clean state reads 0 again, the series stays until the
      stream's ``close()`` releases it via :func:`release_health`).

    ``alerted`` is the caller-owned latch set (per stream) — it doubles as
    the minted-label ledger the release path walks.  Saturation pages too:
    a finite-but-at-the-edge accumulator is exactly the early warning the
    probe exists for (low-precision state overflows to inf only AFTER
    sitting at the edge), so waiting for ``nonfinite`` would re-create the
    late detection the probe preempts."""
    for path, row in summary.get("per_state", {}).items():
        corrupt = row["nonfinite"] > 0 or row["saturated"] > 0
        if corrupt and path not in alerted:
            alerted.add(path)
            _ledger.record_event(
                None, "state_health", stream=stream, state=path,
                nan=row["nan"], inf=row["inf"], saturated=row["saturated"],
            )
        if corrupt or path in alerted:
            _NONFINITE_GAUGE.set(row["nonfinite"], stream, path)


def release_health(stream: str, alerted: Set[str]) -> None:
    """Drop the stream's minted health series (the ``close()`` contract)."""
    for path in alerted:
        _NONFINITE_GAUGE.remove(stream, path)
    alerted.clear()
