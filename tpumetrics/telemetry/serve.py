"""The embedded admin server — the live side of the observability stack.

Everything the telemetry layers record (instruments, ledger, spans, device
profiles, health, flight ring) is host-resident process state; this module
*serves* it while the job runs, so an operator can point a Prometheus
scraper, a k8s probe, or a pager at a live evaluator instead of calling
python functions from their own code:

========== ===============================================================
endpoint   payload
========== ===============================================================
/metrics   Prometheus text exposition — the whole instruments registry +
           ledger-derived families (:func:`~tpumetrics.telemetry.export.
           prometheus_text`); with a federation provider installed, the
           MERGED multi-process view (``?local=1`` forces this process)
/healthz   process liveness + per-stream degraded / quarantine / state-
           health + latched SLO breaches.  **200** while everything is
           healthy, **503** otherwise — wire it as a k8s readiness probe
           (the process answering at all is the liveness signal)
/statusz   JSON: per-target ``stats()`` (the ``device`` section included),
           per-tenant queue depths and DRR shares, signature-cache
           occupancy, SLO engine status, federation membership
/spanz     the recent finished-span ring as JSON (``?limit=N``)
/flightz   trigger a flight dump and download it as JSONL (404 when no
           flight recorder is installed)
========== ===============================================================

**Strict reader discipline** (the PR 13 contract, now load-bearing for a
scraper): every handler only ever *reads* host-side state — instrument
locks, ``stats()`` (documented never-blocking: health reads serve the
cached summary while a dispatch is in flight), the span ring.  Handlers
additionally run under ``jax.transfer_guard_device_to_host("disallow")``
when jax is loaded, so a reader that would synchronize with the device
raises a 500 instead of silently stalling the scrape — and an in-flight-
step concurrency test pins that a scrape returns while a slow device
program is still executing.  Nothing in this module is ever reachable
from ``update()`` and no handler may issue a blocking device read —
tpulint **TPL106** enforces both statically.

The server is a stdlib ``ThreadingHTTPServer`` on a **daemon thread**:
``port=0`` binds an ephemeral port (read it back from
:attr:`AdminServer.port`), startup is synchronous (the constructor returns
with the socket listening), and ``close()`` is idempotent.  Construct one
directly, via :func:`start_admin_server`, or let the runtime own it —
``StreamingEvaluator(admin_port=0)`` / ``EvaluationService(admin_port=0)``
start one scoped to that instance and stop it on ``close()``.
"""

from __future__ import annotations

import contextlib
import json
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from tpumetrics.telemetry import export as _export
from tpumetrics.telemetry import spans as _spans

__all__ = ["AdminServer", "start_admin_server"]

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _no_device_sync():
    """A transfer guard for the handler body: device→host syncs raise
    instead of stalling the scrape.  Inert (a null context) when jax was
    never imported — serving pure-host telemetry must not pull in jax."""
    jax = sys.modules.get("jax")
    if jax is None:
        return contextlib.nullcontext()
    return jax.transfer_guard_device_to_host("disallow")


def _target_kind(obj: Any) -> str:
    return "service" if hasattr(obj, "tenant_ids") else "evaluator"


class _Handler(BaseHTTPRequestHandler):
    """Thin HTTP shim: parse, delegate to the server's render table, write.
    All state lives on ``self.server`` (the :class:`_AdminHTTPServer`)."""

    server_version = "tpumetrics-admin"
    protocol_version = "HTTP/1.1"

    def do_GET(self) -> None:  # noqa: N802 - stdlib naming
        parsed = urlparse(self.path)
        query = {k: v[-1] for k, v in parse_qs(parsed.query).items()}
        try:
            with _no_device_sync():
                status, ctype, body = self.server.admin.render(parsed.path, query)
        except Exception as err:  # noqa: BLE001 — a broken reader is a 500,
            # never a dead serving thread (and never a device stall)
            status, ctype = 500, "application/json"
            body = json.dumps(
                {"error": f"{type(err).__name__}: {err}"}
            ).encode()
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, format: str, *args: Any) -> None:  # noqa: A002
        pass  # scrapes must not spam stderr; /statusz carries the counters


class _AdminHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True
    admin: "AdminServer"


class AdminServer:
    """The embedded admin/introspection server (module docstring).

    Args:
        port: TCP port (0 = ephemeral; read :attr:`port` back).
        host: bind address (default loopback — expose deliberately).
        targets: ``{name: evaluator_or_service}`` to surface in
            ``/healthz`` / ``/statusz``; add more with :meth:`add_target`.
        slo: optional :class:`~tpumetrics.telemetry.slo.SloEngine` (or a
            list of them) whose latched breaches flip ``/healthz``.
        federation: optional zero-arg callable returning a list of
            :func:`~tpumetrics.telemetry.federate.local_snapshot` dicts
            (one per rank/process); installs the merged ``/metrics`` +
            ``/statusz`` view.
        name: served in ``/statusz`` (defaults to ``tpumetrics-admin``).
    """

    def __init__(
        self,
        port: int = 0,
        host: str = "127.0.0.1",
        *,
        targets: Optional[Dict[str, Any]] = None,
        slo: Any = None,
        federation: Optional[Callable[[], Optional[List[Dict[str, Any]]]]] = None,
        name: str = "tpumetrics-admin",
    ) -> None:
        self.name = str(name)
        self._lock = threading.Lock()
        self._targets: Dict[str, Any] = dict(targets or {})
        engines = slo if isinstance(slo, (list, tuple)) else ([slo] if slo else [])
        self._slo: List[Any] = list(engines)
        self._federation = federation
        self._started = time.monotonic()
        self._scrapes = 0
        self._closed = False
        self._httpd = _AdminHTTPServer((host, int(port)), _Handler)
        self._httpd.admin = self
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"tpumetrics-admin[{self._httpd.server_address[1]}]",
            daemon=True,
        )
        self._thread.start()

    # ------------------------------------------------------------- plumbing

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def add_target(self, name: str, obj: Any) -> None:
        with self._lock:
            self._targets[str(name)] = obj

    def remove_target(self, name: str) -> None:
        with self._lock:
            self._targets.pop(str(name), None)

    def add_slo(self, engine: Any) -> None:
        with self._lock:
            self._slo.append(engine)

    def set_federation(
        self, provider: Optional[Callable[[], Optional[List[Dict[str, Any]]]]]
    ) -> None:
        with self._lock:
            self._federation = provider

    def close(self) -> None:
        """Stop serving (idempotent).  Attached SLO engines are NOT closed
        — they belong to whoever constructed them (the runtime's
        ``admin_port`` convenience owns and closes both)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=10.0)

    def __enter__(self) -> "AdminServer":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    # ------------------------------------------------------------ rendering

    def render(self, path: str, query: Dict[str, str]) -> Tuple[int, str, bytes]:
        """(status, content type, body) for one request path — the whole
        routing table, callable without a socket (tests exercise it both
        ways)."""
        with self._lock:
            self._scrapes += 1
        if path in ("/metrics", "/metrics/"):
            return self._metrics(query)
        if path in ("/healthz", "/healthz/"):
            return self._healthz()
        if path in ("/statusz", "/statusz/"):
            return self._statusz()
        if path in ("/spanz", "/spanz/"):
            return self._spanz(query)
        if path in ("/flightz", "/flightz/"):
            return self._flightz()
        if path in ("", "/"):
            body = json.dumps(
                {"endpoints": ["/metrics", "/healthz", "/statusz", "/spanz", "/flightz"]}
            ).encode()
            return 200, "application/json", body
        return 404, "application/json", json.dumps({"error": f"unknown path {path}"}).encode()

    def _metrics(self, query: Dict[str, str]) -> Tuple[int, str, bytes]:
        with self._lock:
            provider = self._federation
        if provider is not None and not query.get("local"):
            snaps = provider()
            if snaps:
                from tpumetrics.telemetry import federate as _federate

                text = _federate.merge_snapshots(snaps).prometheus_text()
                return 200, PROMETHEUS_CONTENT_TYPE, text.encode()
        return 200, PROMETHEUS_CONTENT_TYPE, _export.prometheus_text().encode()

    # -------------------------------------------------------------- healthz

    def _healthz(self) -> Tuple[int, str, bytes]:
        reasons: List[str] = []
        streams: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            targets = dict(self._targets)
            engines = list(self._slo)
        for name, obj in targets.items():
            for label, stats in self._target_streams(name, obj):
                entry = self._stream_health(label, stats)
                streams[label] = entry
                reasons.extend(entry["reasons"])
        breached: List[str] = []
        for engine in engines:
            breached.extend(engine.breached())
        if breached:
            reasons.append(f"slo_breach:{','.join(sorted(breached))}")
        status = "ok" if not reasons else "degraded"
        body = json.dumps(
            {
                "status": status,
                "reasons": sorted(set(reasons)),
                "streams": streams,
                "slo_breached": sorted(breached),
            },
            sort_keys=True,
        ).encode()
        return (200 if status == "ok" else 503), "application/json", body

    @staticmethod
    def _target_streams(name: str, obj: Any):
        """``(label, stats)`` per stream of one target.  A service's whole
        tenant census reads under ONE bounded lock acquire
        (``all_tenant_stats``) — per-tenant reads would stack one bounded
        wait per tenant while a dispatch holds the service lock."""
        if _target_kind(obj) != "service":
            yield name, obj.stats()
            return
        census = getattr(obj, "all_tenant_stats", None)
        if census is not None:
            for tid, stats in census().items():
                yield f"{name}/{tid}", stats
        else:  # duck-typed service targets without the census read
            for tid in obj.tenant_ids():
                yield f"{name}/{tid}", obj.tenant_stats(tid)

    @staticmethod
    def _stream_health(label: str, stats: Dict[str, Any]) -> Dict[str, Any]:
        """One stream's health row from its (never-blocking) stats dict."""
        reasons: List[str] = []
        quarantined = bool(stats.get("quarantined", False))
        degraded = bool(stats.get("degraded", False))
        if quarantined:
            reasons.append(f"quarantined:{label}")
        if degraded:
            reasons.append(f"degraded:{label}")
        nonfinite = 0
        device = stats.get("device") or {}
        health = device.get("health")
        if health is not None:
            nonfinite = int(health.get("nonfinite_total", 0))
            if nonfinite:
                reasons.append(f"state_health:{label}")
        # durability degraded: cut saves suspended behind the heal probe —
        # the stream still SERVES (no degraded: flag), but a preemption in
        # this window loses the uncovered tail, so the probe must page
        storage = stats.get("storage") or {}
        durability_degraded = bool(storage.get("degraded", False))
        if durability_degraded:
            reasons.append(f"durability_degraded:{label}")
        # a service-wide stats dict counts quarantines across tenants
        q_tenants = int(stats.get("quarantined_tenants", 0) or 0)
        if q_tenants:
            reasons.append(f"quarantined_tenants:{label}")
        return {
            "quarantined": quarantined,
            "degraded": degraded,
            "durability_degraded": durability_degraded,
            "state_nonfinite": nonfinite,
            "reasons": reasons,
        }

    # -------------------------------------------------------------- statusz

    def _statusz(self) -> Tuple[int, str, bytes]:
        with self._lock:
            targets = dict(self._targets)
            engines = list(self._slo)
            provider = self._federation
            scrapes = self._scrapes
        payload: Dict[str, Any] = {
            "name": self.name,
            "uptime_s": round(time.monotonic() - self._started, 3),
            "scrapes": scrapes,
            "targets": {},
            "slo": [engine.status() for engine in engines],
        }
        for name, obj in targets.items():
            kind = _target_kind(obj)
            entry: Dict[str, Any] = {"kind": kind, "stats": obj.stats()}
            if kind == "service":
                census = getattr(obj, "all_tenant_stats", None)
                entry["tenants"] = (
                    census()
                    if census is not None
                    else {tid: obj.tenant_stats(tid) for tid in obj.tenant_ids()}
                )
            payload["targets"][name] = entry
        if provider is not None:
            snaps = provider()
            if snaps:
                from tpumetrics.telemetry import federate as _federate

                payload["federation"] = _federate.merge_snapshots(snaps).statusz()
        body = json.dumps(payload, sort_keys=True, default=repr).encode()
        return 200, "application/json", body

    # ---------------------------------------------------------- spanz/flight

    @staticmethod
    def _spanz(query: Dict[str, str]) -> Tuple[int, str, bytes]:
        ring = [sp.to_dict() for sp in _spans.spans()]
        try:
            limit = int(query.get("limit", 0))
        except ValueError:
            limit = 0
        if limit > 0:
            ring = ring[-limit:]
        body = json.dumps(
            {"enabled": _spans.enabled(), "spans": ring}, default=repr
        ).encode()
        return 200, "application/json", body

    @staticmethod
    def _flightz() -> Tuple[int, str, bytes]:
        if _export.flight_recorder() is None:
            return 404, "application/json", json.dumps(
                {"error": "no flight recorder installed (enable_flight_recorder)"}
            ).encode()
        path = _export.flight_dump("admin_flightz")
        with open(path, "rb") as fh:  # type: ignore[arg-type]
            body = fh.read()
        return 200, "application/x-ndjson", body


def start_admin_server(
    port: int = 0,
    host: str = "127.0.0.1",
    **kwargs: Any,
) -> AdminServer:
    """Start an :class:`AdminServer` (daemon thread, listening on return).
    ``port=0`` binds an ephemeral port — ``server.port`` has the real one."""
    return AdminServer(port, host, **kwargs)
