"""The collective ledger — process-local accounting of every wire op.

The sync machinery (``tpumetrics/parallel/backend.py`` collectives,
``tpumetrics/parallel/fuse.py`` fused flushes) reports each collective it
issues here: op class, dtype, element count, payload/wire bytes, backend
class, and an attribution tag naming the metric (class name) or collection
member (key) the traffic belongs to.  ``bench.py`` and tests read the
aggregate counters instead of hand-deriving wire bytes analytically.

Design rules (load-bearing):

- **Trace-safe.** Records carry *static* metadata only — ``shape``/``dtype``/
  ``size`` of a traced array are compile-time constants, so recording inside
  a ``jit``/``shard_map`` trace never forces a host sync.  Records made
  during tracing describe the collectives of the *compiled program*; a cached
  executable does not re-trace and therefore does not re-record — capture one
  traced step to account a steady-state step.
- **Near-zero cost when disabled.** Every report funnels through
  :func:`record_collective`/:func:`record_flush`, whose first statement is a
  module-flag check; with telemetry off the instrumentation is one function
  call + one bool test per collective (collectives themselves cost ~µs-ms).

Wire-byte model (per-device traffic, ring algorithms):

- ``all_reduce`` of ``payload`` bytes over ``N`` ranks moves
  ``2*(N-1)/N * payload`` bytes per device (reduce-scatter + all-gather).
- ``all_gather`` of a ``payload``-byte local shard receives ``(N-1)*payload``
  bytes per device (its own shard does not travel).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

__all__ = [
    "CollectiveRecord",
    "CollectiveLedger",
    "attribution",
    "capture",
    "current_tag",
    "disable",
    "enable",
    "enabled",
    "get_ledger",
    "gather_wire_bytes",
    "record_collective",
    "record_event",
    "record_flush",
    "recording",
    "reduce_wire_bytes",
    "reset",
    "summary",
]


def reduce_wire_bytes(payload_bytes: int, world_size: int) -> float:
    """Per-device wire bytes of a ring all_reduce."""
    if world_size <= 1:
        return 0.0
    return 2.0 * (world_size - 1) / world_size * payload_bytes


def gather_wire_bytes(payload_bytes: int, world_size: int) -> float:
    """Per-device wire bytes of a ring all_gather (local shard stays put)."""
    if world_size <= 1:
        return 0.0
    return float(world_size - 1) * payload_bytes


@dataclass(frozen=True)
class CollectiveRecord:
    """One wire op (or ledger event) as seen by the instrumentation.

    ``source`` separates the two reporting layers so aggregation never double
    counts: ``"backend"`` records are actual wire calls
    (``DistributedBackend.all_gather``/``all_reduce``); ``"reducer"`` records
    are the logical per-(op, dtype) classes a :class:`FusedReducer` flush
    hands to the backend (useful for attribution even under a custom,
    uninstrumented backend); ``"spmd"`` records are the GSPMD-inserted
    in-trace collectives of a sharded step, recorded at trace time with
    ``extra["static"]=True`` (once per compile, no per-step host cost);
    ``"event"`` records are bookkeeping marks (flushes, lockstep
    fingerprints) that carry no payload.
    """

    kind: str  # "all_gather" | "all_reduce" | "fused_class" | "flush" | "lockstep" | ...
    op: str  # "sum"/"mean"/"max"/"min" for reduces, "gather"/"object" otherwise
    dtype: str
    shape: Tuple[int, ...]
    element_count: int
    payload_bytes: int
    wire_bytes: float  # per-device traffic under the ring model (0.0 for world 1)
    backend: str  # backend class name
    tag: str  # attribution path, e.g. "acc/MulticlassAccuracy"
    world_size: int
    in_trace: bool
    source: str = "backend"  # "backend" | "reducer" | "spmd" | "event"
    extra: Dict[str, Any] = field(default_factory=dict)
    #: monotonic + wall clock PAIR stamped when the record was made.  The
    #: monotonic clock orders records exactly within one process; the wall
    #: anchor lets :mod:`tpumetrics.telemetry.timeline` align per-rank JSONL
    #: streams from DIFFERENT processes onto one global axis.  Trace-safe:
    #: a record made at trace time stamps the trace instant (once per
    #: compile), never forcing a host sync.
    mono_ns: int = 0
    wall_ns: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "kind": self.kind,
            "mono_ns": self.mono_ns,
            "wall_ns": self.wall_ns,
            "op": self.op,
            "dtype": self.dtype,
            "shape": list(self.shape),
            "element_count": self.element_count,
            "payload_bytes": self.payload_bytes,
            "wire_bytes": self.wire_bytes,
            "backend": self.backend,
            "tag": self.tag,
            "world_size": self.world_size,
            "in_trace": self.in_trace,
            "source": self.source,
            **({"extra": dict(self.extra)} if self.extra else {}),
        }


class CollectiveLedger:
    """Accumulates :class:`CollectiveRecord`s with cheap aggregate counters."""

    def __init__(self, sinks: Sequence[Any] = ()) -> None:
        self._sinks: List[Any] = list(sinks)
        self.reset()

    # ------------------------------------------------------------- recording

    def record(self, rec: CollectiveRecord) -> None:
        self.records.append(rec)
        if rec.source == "backend":
            self.collectives_issued += 1
            self.wire_bytes_total += rec.wire_bytes
            self.payload_bytes_total += rec.payload_bytes
            self.bytes_by_op[rec.op] = self.bytes_by_op.get(rec.op, 0.0) + rec.wire_bytes
        elif rec.source == "spmd":
            # GSPMD-inserted in-trace collectives of a sharded step, recorded
            # at trace time (static metadata, once per compile) — kept apart
            # from eager wire accounting so neither pollutes the other
            self.spmd_collectives += 1
            self.spmd_wire_bytes += rec.wire_bytes
        elif rec.kind == "flush":
            self.flush_count += 1
            self.fused_entries += int(rec.extra.get("entries", 0))
        elif rec.kind == "lockstep":
            self.lockstep_fingerprints += 1
        elif rec.kind == "runtime_drop":
            # the streaming runtime's drop-oldest evictions (dispatch.py)
            self.runtime_drops += 1
        elif rec.kind == "runtime_drain":
            # one worker drain cycle: micro-batch size + queue depth after
            self.runtime_drain_cycles += 1
            self.runtime_items_drained += int(rec.extra.get("items", 0))
            self.runtime_max_depth = max(self.runtime_max_depth, int(rec.extra.get("depth", 0)))
        elif rec.kind == "sync_timeout":
            # a guarded eager collective missed its SyncPolicy deadline
            self.sync_timeouts += 1
        elif rec.kind == "sync_retry":
            # one backoff-retry of a transiently-failing collective
            self.sync_retries += 1
        elif rec.kind == "sync_failed":
            # retries exhausted: the typed SyncFailedError surfaced
            self.sync_failures += 1
        elif rec.kind == "degraded_compute":
            # a compute served unsynced-local or last-good state
            self.degraded_computes += 1
        elif rec.kind == "fault_injected":
            # a FaultInjectionBackend fired one scheduled fault
            self.faults_injected += 1
        elif rec.kind == "non_finite_state":
            # guard_non_finite caught NaN/Inf before the wire (or a snapshot)
            self.non_finite_states += 1
        elif rec.kind == "runtime_crash":
            # the streaming runtime's worker died applying a batch
            self.runtime_crashes += 1
        elif rec.kind == "runtime_restore":
            # crash policy restored from a snapshot and replayed the journal
            self.runtime_restores += 1
        elif rec.kind == "elastic_barrier":
            # one coordinated snapshot barrier (step agreement + cut stamp)
            self.elastic_barriers += 1
        elif rec.kind == "elastic_restore":
            # one rank adopted a folded + resharded consistent cut
            self.elastic_restores += 1
        elif rec.kind == "elastic_degraded":
            # a quorum policy admitted an INCOMPLETE cut (missing ranks' data
            # is absent from the fold) — never silent
            self.elastic_degraded_cuts += 1
        elif rec.kind == "megabatch_step":
            # the service drove K tenants' same-signature updates through
            # ONE vmapped device program (extra["tenants"] = K)
            self.megabatch_steps += 1
            self.megabatch_tenants += int(rec.extra.get("tenants", 0))
        elif rec.kind == "tenant_quarantined":
            # one tenant's crash was fenced off; the service kept serving
            self.tenant_quarantines += 1
        elif rec.kind == "xla_compile":
            # one attributed backend compile (telemetry/xla.py): the event
            # carries tenant + seconds; the per-tenant histogram has the rest
            self.xla_attributed_compiles += 1
        elif rec.kind == "xla_retrace":
            # a previously-seen (token, signature) compiled AGAIN — the jit
            # executable cache should have served it (retrace detector)
            self.xla_retraces += 1
        elif rec.kind == "drift_alert":
            # a drift monitor's score crossed its threshold upward
            # (hysteresis-latched: one event per crossing, not per compute)
            self.drift_alerts += 1
        elif rec.kind == "state_health":
            # an armed health probe surfaced NaN/inf/saturation in a stream's
            # metric state (one event per stream+state on FIRST corruption —
            # before the compute-time non-finite guard would trip)
            self.state_health_events += 1
        elif rec.kind == "slo_violation":
            # an SLO rule's burn rate crossed its fast/slow threshold
            # (hysteresis-latched: one event per crossing — telemetry/slo.py)
            self.slo_violations += 1
        self.counts_by_kind[rec.kind] = self.counts_by_kind.get(rec.kind, 0) + 1
        for sink in self._sinks:
            sink.emit(rec)

    def reset(self) -> None:
        self.records: List[CollectiveRecord] = []
        self.collectives_issued = 0
        self.wire_bytes_total = 0.0
        self.payload_bytes_total = 0
        self.flush_count = 0
        self.fused_entries = 0
        self.lockstep_fingerprints = 0
        self.runtime_drops = 0
        self.runtime_drain_cycles = 0
        self.runtime_items_drained = 0
        self.runtime_max_depth = 0
        self.sync_timeouts = 0
        self.sync_retries = 0
        self.sync_failures = 0
        self.degraded_computes = 0
        self.faults_injected = 0
        self.non_finite_states = 0
        self.runtime_crashes = 0
        self.runtime_restores = 0
        self.elastic_barriers = 0
        self.elastic_restores = 0
        self.elastic_degraded_cuts = 0
        self.megabatch_steps = 0
        self.megabatch_tenants = 0
        self.tenant_quarantines = 0
        self.xla_attributed_compiles = 0
        self.xla_retraces = 0
        self.drift_alerts = 0
        self.state_health_events = 0
        self.slo_violations = 0
        self.spmd_collectives = 0
        self.spmd_wire_bytes = 0.0
        self.bytes_by_op: Dict[str, float] = {}
        self.counts_by_kind: Dict[str, int] = {}

    # ----------------------------------------------------------------- sinks

    def add_sink(self, sink: Any) -> None:
        self._sinks.append(sink)

    def remove_sink(self, sink: Any) -> None:
        self._sinks.remove(sink)

    # --------------------------------------------------------------- reading

    def summary(self) -> Dict[str, Any]:
        """Aggregate view (the dict ``bench.py`` consumes)."""
        return {
            "collectives_issued": self.collectives_issued,
            "wire_bytes_total": self.wire_bytes_total,
            "payload_bytes_total": self.payload_bytes_total,
            "bytes_by_op": dict(self.bytes_by_op),
            "counts_by_kind": dict(self.counts_by_kind),
            "flush_count": self.flush_count,
            "fused_entries": self.fused_entries,
            "lockstep_fingerprints": self.lockstep_fingerprints,
            "runtime_drops": self.runtime_drops,
            "runtime_drain_cycles": self.runtime_drain_cycles,
            "runtime_items_drained": self.runtime_items_drained,
            "runtime_max_depth": self.runtime_max_depth,
            "sync_timeouts": self.sync_timeouts,
            "sync_retries": self.sync_retries,
            "sync_failures": self.sync_failures,
            "degraded_computes": self.degraded_computes,
            "faults_injected": self.faults_injected,
            "non_finite_states": self.non_finite_states,
            "runtime_crashes": self.runtime_crashes,
            "runtime_restores": self.runtime_restores,
            "elastic_barriers": self.elastic_barriers,
            "elastic_restores": self.elastic_restores,
            "elastic_degraded_cuts": self.elastic_degraded_cuts,
            "megabatch_steps": self.megabatch_steps,
            "megabatch_tenants": self.megabatch_tenants,
            "tenant_quarantines": self.tenant_quarantines,
            "xla_attributed_compiles": self.xla_attributed_compiles,
            "xla_retraces": self.xla_retraces,
            "drift_alerts": self.drift_alerts,
            "state_health_events": self.state_health_events,
            "slo_violations": self.slo_violations,
            "spmd_collectives": self.spmd_collectives,
            "spmd_wire_bytes": self.spmd_wire_bytes,
            "records": len(self.records),
        }


# ---------------------------------------------------------------- module state
#
# One global ledger (opt-in via enable()) plus a stack of capture() scopes.
# The hot-path predicate is `_ENABLED or _ACTIVE` — two loads and a bool test.

_LEDGER = CollectiveLedger()
_ACTIVE: List[CollectiveLedger] = []
_ENABLED = False
_LOCK = threading.Lock()

#: installed by export.enable_flight_recorder(): every record additionally
#: lands in the flight ring while a recorder is active, even when neither
#: the global ledger nor a capture scope is recording — the crash dump must
#: carry the last events regardless of who else was listening
_FLIGHT_HOOK = None

# attribution is a plain thread-local stack of tags; pushed around sync
# collection so records name the metric/collection member they belong to
_TAGS = threading.local()


def enabled() -> bool:
    """Whether the *global* ledger is recording."""
    return _ENABLED


def recording() -> bool:
    """Whether any ledger (global or captured) is recording."""
    return _ENABLED or bool(_ACTIVE)


def enable() -> None:
    """Start recording into the global ledger (see :func:`get_ledger`)."""
    global _ENABLED
    _ENABLED = True


def disable() -> None:
    """Stop recording into the global ledger (capture scopes still record)."""
    global _ENABLED
    _ENABLED = False


def reset() -> None:
    """Clear the global ledger's records and counters."""
    _LEDGER.reset()


def get_ledger() -> CollectiveLedger:
    """The process-global ledger (records only while :func:`enabled`)."""
    return _LEDGER


def summary() -> Dict[str, Any]:
    """Shorthand for ``get_ledger().summary()``."""
    return _LEDGER.summary()


@contextmanager
def capture(sinks: Sequence[Any] = ()) -> Iterator[CollectiveLedger]:
    """Scoped measurement: records everything issued inside the ``with`` into
    a fresh ledger (independent of the global enable flag)::

        with telemetry.capture() as led:
            step(state, preds, target)   # first call traces -> records
        print(led.summary()["wire_bytes_total"])
    """
    led = CollectiveLedger(sinks=sinks)
    with _LOCK:
        _ACTIVE.append(led)
    try:
        yield led
    finally:
        with _LOCK:  # after removal no _emit can reach these sinks
            _ACTIVE.remove(led)
        for sink in led._sinks:
            close = getattr(sink, "close", None)
            if close is not None:
                close()


def _tag_stack() -> List[str]:
    stack = getattr(_TAGS, "stack", None)
    if stack is None:
        stack = _TAGS.stack = []
    return stack


@contextmanager
def attribution(tag: Optional[str]) -> Iterator[None]:
    """Push an attribution tag for collectives issued inside the scope.

    Nested scopes join with ``/`` (a collection pushes its member key, the
    member metric its class name: ``"acc/MulticlassAccuracy"``).
    """
    if not tag:
        yield
        return
    stack = _tag_stack()
    stack.append(str(tag))
    try:
        yield
    finally:
        stack.pop()


def current_tag() -> str:
    stack = getattr(_TAGS, "stack", None)
    return "/".join(stack) if stack else ""


# ------------------------------------------------------------- report helpers


def _clocks() -> Tuple[int, int]:
    """The (monotonic_ns, wall_ns) stamp every record carries — captured
    only on the recording path (the disabled fast path never reaches it)."""
    return time.monotonic_ns(), time.time_ns()


def _emit(rec: CollectiveRecord) -> None:
    if _ENABLED:
        _LEDGER.record(rec)
    hook = _FLIGHT_HOOK
    if hook is not None:
        hook(rec)
    # the lock pairs with capture()'s remove-then-close: once a ledger is
    # removed under the lock, no emitter can still deliver to its sinks
    with _LOCK:
        for led in _ACTIVE:
            led.record(rec)


def record_collective(
    backend: Any,
    kind: str,
    op: str,
    shape: Tuple[int, ...],
    dtype: Any,
    itemsize: int,
    world_size: int,
    in_trace: bool = False,
    source: str = "backend",
    tag: Optional[str] = None,
    **extra: Any,
) -> None:
    """Report one collective.  First line is the disabled fast path."""
    if not (_ENABLED or _ACTIVE or _FLIGHT_HOOK is not None):
        return
    count = 1
    for d in shape:
        count *= int(d)
    payload = count * int(itemsize)
    if op in ("sum", "mean", "max", "min"):
        wire = reduce_wire_bytes(payload, world_size)
    else:
        wire = gather_wire_bytes(payload, world_size)
    mono_ns, wall_ns = _clocks()
    _emit(
        CollectiveRecord(
            kind=kind,
            op=op,
            dtype=str(dtype),
            shape=tuple(int(d) for d in shape),
            element_count=count,
            payload_bytes=payload,
            wire_bytes=wire,
            backend=type(backend).__name__,
            tag=tag if tag is not None else current_tag(),
            world_size=int(world_size),
            in_trace=bool(in_trace),
            source=source,
            extra=extra,
            mono_ns=mono_ns,
            wall_ns=wall_ns,
        )
    )


def record_flush(backend: Any, entries: int, classes: int, in_trace: bool = False) -> None:
    """Report one :class:`FusedReducer` flush (bookkeeping only, no payload)."""
    if not (_ENABLED or _ACTIVE or _FLIGHT_HOOK is not None):
        return
    mono_ns, wall_ns = _clocks()
    _emit(
        CollectiveRecord(
            kind="flush",
            op="flush",
            dtype="",
            shape=(),
            element_count=0,
            payload_bytes=0,
            wire_bytes=0.0,
            backend=type(backend).__name__,
            tag=current_tag(),
            world_size=0,
            in_trace=bool(in_trace),
            source="event",
            extra={"entries": int(entries), "classes": int(classes)},
            mono_ns=mono_ns,
            wall_ns=wall_ns,
        )
    )


def record_event(backend: Any, kind: str, in_trace: bool = False, **extra: Any) -> None:
    """Report a payload-free bookkeeping event (e.g. a lockstep fingerprint)."""
    if not (_ENABLED or _ACTIVE or _FLIGHT_HOOK is not None):
        return
    mono_ns, wall_ns = _clocks()
    _emit(
        CollectiveRecord(
            kind=kind,
            op=kind,
            dtype="",
            shape=(),
            element_count=0,
            payload_bytes=0,
            wire_bytes=0.0,
            backend=type(backend).__name__,
            tag=current_tag(),
            world_size=0,
            in_trace=bool(in_trace),
            source="event",
            extra=extra,
            mono_ns=mono_ns,
            wall_ns=wall_ns,
        )
    )
