"""Span tracing — where a batch's wall time goes, host-side only.

The ledger (:mod:`~tpumetrics.telemetry.ledger`) counts *that* things
happened (collectives, drops, crashes); spans record *where the time went*:
one submitted batch = one **trace**, with child spans for every host-side
seam the runtime drives it through — queue wait, DRR scheduling delay,
bucket/pad planning, the device dispatch, and the state write-back.  The
paper's contract ("no host sync until ``compute()``") means those seams are
the only place the system may observe itself, so spans are **strictly
host-side**: nothing here is ever called inside a ``jit`` trace (tpulint
TPL104 enforces it for ``update()``-reachable metric code), and a span
records wall time on the **monotonic** clock — immune to NTP steps.

Design rules (the ``SyncPolicy`` inert-predicate discipline):

- **Near-zero cost when disabled.**  Tracing is off by default; every public
  entry point's first statement is one module-flag test.  A disabled
  :func:`span` returns a shared singleton no-op context manager —
  *no allocation per call* (pinned by test and benched as
  ``observability_overhead``); a disabled :func:`start_span` returns ``None``
  so queue entries carry a ``None`` instead of a span object.
- **Bounded memory.**  Finished spans land in a ring (``deque(maxlen=…)``);
  an unobserved long-running process evicts oldest-first and counts the
  evictions instead of leaking.
- **Thread-safe, cross-thread capable.**  Same-thread nesting rides a
  thread-local context stack (:func:`span`); spans whose start and end live
  on different threads (a batch enqueued on a request thread, drained on the
  worker) use the explicit :func:`start_span`/:func:`end_span` pair, and a
  worker adopts a batch's trace as its ambient parent with
  :func:`activate`.  Retroactive measurements (a scheduling window timed
  under a lock) record in one shot via :func:`record_span`.

Quick start::

    from tpumetrics.telemetry import spans

    spans.enable()
    with spans.span("plan", bucket=32):
        ...
    for s in spans.drain():
        print(s.name, s.duration_ms, s.trace_id)

Export: :func:`tpumetrics.telemetry.export.spans_jsonl` writes the ring as
JSON lines; the flight recorder (:mod:`~tpumetrics.telemetry.export`)
additionally receives every finished span while it is enabled, so a crash
dump carries the poisoned batch's trace.  See ``docs/observability.md``.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple, Union

__all__ = [
    "Span",
    "SpanTracer",
    "activate",
    "current",
    "disable",
    "drain",
    "enable",
    "enabled",
    "end_span",
    "get_tracer",
    "record_span",
    "reset",
    "span",
    "spans",
    "start_span",
    "start_trace",
    "suppress",
]

_ENABLED = False
#: monotonically increasing ids shared by traces and spans (itertools.count
#: is effectively atomic under the GIL; ids only need process-uniqueness)
_IDS = itertools.count(1)
_CTX = threading.local()  # .stack: [(trace_id, span_id), ...] innermost last

#: installed by export.enable_flight_recorder(): every finished span is
#: forwarded here so crash dumps carry the recent traces even when nobody
#: is polling the ring
_FLIGHT_HOOK = None


def _now_ns() -> int:
    return time.monotonic_ns()


class Span:
    """One finished (or in-flight) host-side measurement.

    ``trace_id`` groups every span of one logical unit of work (one
    submitted batch); ``parent_id`` nests children under the root.  Times
    are monotonic-clock nanoseconds — durations are exact, absolute epochs
    are deliberately absent (compare spans only within one process).
    """

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "start_ns", "end_ns",
        "attrs", "thread", "wall_ns",
    )

    def __init__(
        self,
        name: str,
        trace_id: int,
        span_id: int,
        parent_id: Optional[int],
        start_ns: int,
        end_ns: Optional[int],
        attrs: Dict[str, Any],
    ) -> None:
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_ns = start_ns
        self.end_ns = end_ns
        self.attrs = attrs
        self.thread = threading.get_ident()
        # monotonic + wall clock PAIR anchored at the same instant: the
        # monotonic clock orders spans exactly within a process, and the wall
        # anchor lets timeline.py align streams from DIFFERENT processes/
        # ranks onto one global axis (for a retroactive record_span the
        # anchor is back-dated by the same monotonic distance, so the pair
        # stays consistent)
        self.wall_ns = time.time_ns() - (_now_ns() - start_ns)

    @property
    def duration_ms(self) -> Optional[float]:
        if self.end_ns is None:
            return None
        return (self.end_ns - self.start_ns) / 1e6

    def context(self) -> Tuple[int, int]:
        """The ``(trace_id, span_id)`` pair children parent under."""
        return (self.trace_id, self.span_id)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "type": "span",
            "name": self.name,
            "trace": self.trace_id,
            "span": self.span_id,
            "parent": self.parent_id,
            "start_ns": self.start_ns,
            "end_ns": self.end_ns,
            "wall_ns": self.wall_ns,
            "duration_ms": self.duration_ms,
            "thread": self.thread,
            "attrs": dict(self.attrs),
        }

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        dur = f"{self.duration_ms:.3f}ms" if self.end_ns is not None else "open"
        return f"Span({self.name!r}, trace={self.trace_id}, {dur})"


class SpanTracer:
    """Thread-safe bounded ring of finished spans."""

    def __init__(self, capacity: int = 4096) -> None:
        if int(capacity) <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=int(capacity))
        self.finished = 0  # lifetime count (ring may have evicted)
        self.evicted = 0

    @property
    def capacity(self) -> int:
        return self._ring.maxlen  # type: ignore[return-value]

    def record(self, sp: Span) -> None:
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self.evicted += 1
            self._ring.append(sp)
            self.finished += 1
        hook = _FLIGHT_HOOK
        if hook is not None:
            hook(sp)

    def spans(self) -> List[Span]:
        """Snapshot of the ring, oldest first."""
        with self._lock:
            return list(self._ring)

    def drain(self) -> List[Span]:
        """Snapshot AND clear the ring (lifetime counters kept)."""
        with self._lock:
            out = list(self._ring)
            self._ring.clear()
            return out

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self.finished = 0
            self.evicted = 0


_TRACER = SpanTracer()


# ------------------------------------------------------------- module switch


def enabled() -> bool:
    return _ENABLED


def enable(capacity: Optional[int] = None) -> None:
    """Turn tracing on (optionally resizing the ring, which clears it)."""
    global _ENABLED, _TRACER
    if capacity is not None and capacity != _TRACER.capacity:
        _TRACER = SpanTracer(capacity)
    _ENABLED = True


def disable() -> None:
    global _ENABLED
    _ENABLED = False


def reset() -> None:
    _TRACER.reset()


def get_tracer() -> SpanTracer:
    return _TRACER


def spans() -> List[Span]:
    """Snapshot of the finished-span ring, oldest first."""
    return _TRACER.spans()


def drain() -> List[Span]:
    """Snapshot and clear the ring."""
    return _TRACER.drain()


# ---------------------------------------------------------- context plumbing


def _stack() -> List[Tuple[int, int]]:
    st = getattr(_CTX, "stack", None)
    if st is None:
        st = _CTX.stack = []
    return st


def _suppressed() -> bool:
    return bool(getattr(_CTX, "suppress", 0))


class _Suppression:
    """Span-less mode for this thread (re-entrant): crash replays re-apply
    batches whose traces already ended at the crash — child spans fired
    during the replay would root fresh fragment traces, so the replay loop
    suppresses them instead."""

    __slots__ = ()

    def __enter__(self) -> "_Suppression":
        _CTX.suppress = getattr(_CTX, "suppress", 0) + 1
        return self

    def __exit__(self, *exc: Any) -> bool:
        _CTX.suppress -= 1
        return False


def suppress() -> _Suppression:
    """Context manager: no spans are created on this thread inside the
    ``with`` (even with tracing enabled).  Explicit ``end_span`` on spans
    started OUTSIDE still records — suppression gates creation only."""
    return _Suppression()


def current() -> Optional[Tuple[int, int]]:
    """The innermost active ``(trace_id, span_id)`` on this thread."""
    st = getattr(_CTX, "stack", None)
    return st[-1] if st else None


def _resolve_parent(parent: Union[None, Span, Tuple[int, int]]) -> Tuple[int, Optional[int]]:
    """(trace_id, parent_span_id) for a new span: explicit parent wins, then
    the thread's current span, then a fresh trace."""
    if parent is not None:
        if isinstance(parent, Span):
            return parent.trace_id, parent.span_id
        return int(parent[0]), int(parent[1])
    cur = current()
    if cur is not None:
        return cur[0], cur[1]
    return next(_IDS), None


class _NullSpan:
    """Shared no-op stand-in for every disabled-path context manager: one
    module-lifetime instance, so a disabled ``span()`` allocates nothing."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False

    def set(self, **attrs: Any) -> "_NullSpan":
        return self


_NULL = _NullSpan()


class _ActiveSpan:
    """Same-thread span context manager (returned by :func:`span`)."""

    __slots__ = ("span",)

    def __init__(self, name: str, attrs: Dict[str, Any]) -> None:
        tid, pid = _resolve_parent(None)
        sp = Span(name, tid, next(_IDS), pid, _now_ns(), None, attrs)
        self.span = sp
        _stack().append((tid, sp.span_id))

    def __enter__(self) -> "_ActiveSpan":
        return self

    def set(self, **attrs: Any) -> "_ActiveSpan":
        self.span.attrs.update(attrs)
        return self

    def __exit__(self, exc_type: Any, exc: Any, tb: Any) -> bool:
        _stack().pop()
        sp = self.span
        sp.end_ns = _now_ns()
        if exc_type is not None:
            sp.attrs.setdefault("error", f"{exc_type.__name__}: {exc}")
        _TRACER.record(sp)
        return False


class _Activation:
    """Adopt an explicit span context as this thread's ambient parent (the
    worker thread nesting its child spans under a batch's root span)."""

    __slots__ = ()

    def __init__(self, ctx: Tuple[int, int]) -> None:
        _stack().append((int(ctx[0]), int(ctx[1])))

    def __enter__(self) -> "_Activation":
        return self

    def __exit__(self, *exc: Any) -> bool:
        _stack().pop()
        return False


# ------------------------------------------------------------------- the API


def span(name: str, **attrs: Any) -> Union[_NullSpan, _ActiveSpan]:
    """Context manager measuring one same-thread operation::

        with spans.span("dispatch", bucket=32):
            state = program(state, batch)

    Nests under the thread's current span (or an :func:`activate`-d batch
    context); with no ambient context it roots a fresh trace.  Disabled:
    returns the shared no-op singleton — no allocation."""
    if not _ENABLED or _suppressed():
        return _NULL
    return _ActiveSpan(name, attrs)


def start_trace(name: str, **attrs: Any) -> Optional[Span]:
    """Start a ROOT span for a fresh trace, regardless of any ambient span
    on this thread — "one batch = one trace" is anchored here.  Returns the
    open root (``None`` when disabled); finish with :func:`end_span`."""
    if not _ENABLED or _suppressed():
        return None
    return Span(name, next(_IDS), next(_IDS), None, _now_ns(), None, dict(attrs))


def start_span(
    name: str, parent: Union[None, Span, Tuple[int, int]] = None, **attrs: Any
) -> Optional[Span]:
    """Explicitly start a span whose end may happen on another thread (the
    queue-wait span: started at submit, ended at the worker's pop).  Returns
    the open :class:`Span` handle, or ``None`` when tracing is disabled —
    pass the handle wherever the work travels and finish it with
    :func:`end_span`.  Does NOT touch the thread-local context stack."""
    if not _ENABLED or _suppressed():
        return None
    tid, pid = _resolve_parent(parent)
    return Span(name, tid, next(_IDS), pid, _now_ns(), None, dict(attrs))


def end_span(sp: Optional[Span], **attrs: Any) -> None:
    """Finish a :func:`start_span` handle (``None``-safe: the disabled path
    hands ``None`` around and this is then a no-op)."""
    if sp is None or sp.end_ns is not None:
        return
    sp.end_ns = _now_ns()
    if attrs:
        sp.attrs.update(attrs)
    _TRACER.record(sp)


def record_span(
    name: str,
    start_ns: int,
    end_ns: int,
    parent: Union[None, Span, Tuple[int, int]] = None,
    **attrs: Any,
) -> None:
    """Record a retroactive span in one shot — for windows measured under a
    lock where opening a live span would be awkward (the DRR scheduling
    delay, a megabatch group's shared dispatch)."""
    if not _ENABLED or _suppressed():
        return
    tid, pid = _resolve_parent(parent)
    _TRACER.record(Span(name, tid, next(_IDS), pid, int(start_ns), int(end_ns), dict(attrs)))


def activate(ctx: Union[None, Span, Tuple[int, int]]) -> Union[_NullSpan, _Activation]:
    """Make ``ctx`` (a Span or ``(trace_id, span_id)``) the ambient parent
    for :func:`span` calls on this thread — the worker adopting a batch's
    root span.  ``None`` (or disabled tracing) is the no-op singleton."""
    if not _ENABLED or ctx is None:
        return _NULL
    if isinstance(ctx, Span):
        ctx = ctx.context()
    return _Activation(ctx)
