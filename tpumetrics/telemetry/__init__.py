"""``tpumetrics.telemetry`` — the observability stack.

Six parts (see ``docs/telemetry.md`` and ``docs/observability.md``):

- **Collective ledger** (:mod:`~tpumetrics.telemetry.ledger`): every
  ``DistributedBackend.all_gather``/``all_reduce`` call and every
  ``FusedReducer.flush`` reports op, dtype, element count, wire bytes,
  backend class, and an attribution tag; aggregate counters plus a
  :func:`capture` context manager for scoped measurement.  Trace-safe
  (static metadata only) and near-zero-cost when disabled.
- **Lockstep verification** (:mod:`~tpumetrics.telemetry.lockstep`): before
  an eager multi-host flush each rank fingerprints its intended collective
  schedule and exchanges digests over the host-object channel; a mismatch
  raises :class:`LockstepViolation` naming the diverging rank and the first
  differing entry instead of deadlocking (ADVICE r5 #3).
- **Sinks** (:mod:`~tpumetrics.telemetry.sinks`): pluggable record
  consumers — stdlib logging and JSON-lines.
- **Spans** (:mod:`~tpumetrics.telemetry.spans`): where a batch's wall time
  goes — one submitted batch = one trace, with child spans for queue wait,
  scheduling delay, planning, device dispatch, and write-back.  Strictly
  host-side, ring-buffered, near-zero cost when disabled.
- **Instruments** (:mod:`~tpumetrics.telemetry.instruments`): process-global
  counters, gauges, and fixed-bucket latency histograms cheap enough for the
  submit path; ``stats()`` latency sections and the bench soak gate read
  them.
- **Export + flight recorder** (:mod:`~tpumetrics.telemetry.export`):
  Prometheus text exposition, JSONL span/instrument dumps, and a bounded
  ring of recent records that auto-dumps to a JSONL file on tenant
  quarantine, dispatcher poison, and crash-loop exhaustion.
- **XLA compile attribution** (:mod:`~tpumetrics.telemetry.xla`, lazy —
  imports jax): every backend compile charged to the (tenant, step token,
  trace signature) that triggered it, with a retrace detector.
- **Device-side observability** (:mod:`~tpumetrics.telemetry.device`,
  :mod:`~tpumetrics.telemetry.health`, lazy): a program-profile registry
  (per-program XLA flops/HBM, resolved lazily) and the in-trace state
  health probe (NaN/inf/saturation counters computed inside the step
  program, zero extra device→host transfers).
- **Cross-rank timelines** (:mod:`~tpumetrics.telemetry.timeline`): merge
  per-rank JSONL streams onto one wall-anchored axis, per-collective entry
  skew, straggler reports, and :func:`perfetto_trace` rendering.
- **The live introspection plane** (:mod:`~tpumetrics.telemetry.serve`,
  :mod:`~tpumetrics.telemetry.slo`,
  :mod:`~tpumetrics.telemetry.federate`, lazy): an embedded admin server
  (``/metrics``, ``/healthz``, ``/statusz``, ``/spanz``, ``/flightz``),
  declarative SLOs with multi-window burn-rate alerting, and cross-rank
  federation of the instruments/ledger state into one merged live view.

Quick start::

    from tpumetrics import telemetry

    with telemetry.capture() as led:
        value = metric.compute()            # or trace a jitted step
    print(led.summary())                    # counts, wire bytes by op class

    telemetry.enable()                      # or: record globally
    ...
    print(telemetry.summary())
"""

from tpumetrics.telemetry.ledger import (
    CollectiveLedger,
    CollectiveRecord,
    attribution,
    capture,
    current_tag,
    disable,
    enable,
    enabled,
    gather_wire_bytes,
    get_ledger,
    record_collective,
    record_event,
    record_flush,
    recording,
    reduce_wire_bytes,
    reset,
    summary,
)
from tpumetrics.telemetry.sinks import JsonlSink, LoggingSink, TelemetrySink
from tpumetrics.telemetry import instruments, spans
from tpumetrics.telemetry import export
from tpumetrics.telemetry.export import (
    FlightRecorder,
    disable_flight_recorder,
    enable_flight_recorder,
    flight_dump,
    flight_recorder,
    note_incident,
    perfetto_trace,
    prometheus_text,
    spans_jsonl,
)
from tpumetrics.telemetry import timeline
from tpumetrics.telemetry.instruments import counter, gauge, histogram
from tpumetrics.telemetry.spans import span, start_span, end_span, record_span

# Lockstep names resolve lazily (PEP 562): lockstep.py pulls in
# tpumetrics.utils (for the exception base class), whose distributed module
# imports parallel/backend.py — which itself imports the ledger at module
# top.  Deferring lockstep breaks that bootstrap cycle while keeping
# ``telemetry.verify_lockstep`` / ``telemetry.LockstepViolation`` public.
_LOCKSTEP_NAMES = (
    "LockstepViolation",
    "configure",
    "lockstep_verification_enabled",
    "normalize_schedule",
    "schedule_fingerprint",
    "should_verify",
    "verify_lockstep",
)


def __getattr__(name: str):
    if name in _LOCKSTEP_NAMES or name == "lockstep":
        import importlib

        mod = importlib.import_module("tpumetrics.telemetry.lockstep")
        return mod if name == "lockstep" else getattr(mod, name)
    if name in ("xla", "device", "health", "serve", "slo", "federate"):
        # lazy like lockstep: xla.py imports jax at module top, and device/
        # health defer their jax imports — keeping them lazy means the
        # pure-AST analysis tooling never pulls heavy deps just to name the
        # package.  serve/slo/federate (the live introspection plane) are
        # pure host-side but stay lazy for symmetry: importing telemetry
        # must never start threads or touch sockets implicitly.
        import importlib

        return importlib.import_module(f"tpumetrics.telemetry.{name}")
    if name in ("AdminServer", "start_admin_server"):
        import importlib

        return getattr(importlib.import_module("tpumetrics.telemetry.serve"), name)
    if name in ("SloEngine", "SloRule"):
        import importlib

        return getattr(importlib.import_module("tpumetrics.telemetry.slo"), name)
    if name in ("local_snapshot", "merge_snapshots"):
        import importlib

        return getattr(importlib.import_module("tpumetrics.telemetry.federate"), name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

__all__ = [
    "AdminServer",
    "CollectiveLedger",
    "CollectiveRecord",
    "FlightRecorder",
    "JsonlSink",
    "LockstepViolation",
    "LoggingSink",
    "SloEngine",
    "SloRule",
    "TelemetrySink",
    "attribution",
    "counter",
    "disable_flight_recorder",
    "enable_flight_recorder",
    "end_span",
    "export",
    "flight_dump",
    "flight_recorder",
    "federate",
    "gauge",
    "histogram",
    "instruments",
    "local_snapshot",
    "merge_snapshots",
    "note_incident",
    "perfetto_trace",
    "prometheus_text",
    "record_span",
    "serve",
    "slo",
    "span",
    "spans",
    "spans_jsonl",
    "start_admin_server",
    "start_span",
    "timeline",
    "capture",
    "configure",
    "current_tag",
    "disable",
    "enable",
    "enabled",
    "gather_wire_bytes",
    "get_ledger",
    "lockstep_verification_enabled",
    "normalize_schedule",
    "record_collective",
    "record_event",
    "record_flush",
    "recording",
    "reduce_wire_bytes",
    "reset",
    "schedule_fingerprint",
    "should_verify",
    "summary",
    "verify_lockstep",
]
