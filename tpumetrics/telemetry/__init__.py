"""``tpumetrics.telemetry`` — observability for the sync machinery.

Three parts (see ``docs/telemetry.md`` for the guide):

- **Collective ledger** (:mod:`~tpumetrics.telemetry.ledger`): every
  ``DistributedBackend.all_gather``/``all_reduce`` call and every
  ``FusedReducer.flush`` reports op, dtype, element count, wire bytes,
  backend class, and an attribution tag; aggregate counters plus a
  :func:`capture` context manager for scoped measurement.  Trace-safe
  (static metadata only) and near-zero-cost when disabled.
- **Lockstep verification** (:mod:`~tpumetrics.telemetry.lockstep`): before
  an eager multi-host flush each rank fingerprints its intended collective
  schedule and exchanges digests over the host-object channel; a mismatch
  raises :class:`LockstepViolation` naming the diverging rank and the first
  differing entry instead of deadlocking (ADVICE r5 #3).
- **Sinks** (:mod:`~tpumetrics.telemetry.sinks`): pluggable record
  consumers — stdlib logging and JSON-lines.

Quick start::

    from tpumetrics import telemetry

    with telemetry.capture() as led:
        value = metric.compute()            # or trace a jitted step
    print(led.summary())                    # counts, wire bytes by op class

    telemetry.enable()                      # or: record globally
    ...
    print(telemetry.summary())
"""

from tpumetrics.telemetry.ledger import (
    CollectiveLedger,
    CollectiveRecord,
    attribution,
    capture,
    current_tag,
    disable,
    enable,
    enabled,
    gather_wire_bytes,
    get_ledger,
    record_collective,
    record_event,
    record_flush,
    recording,
    reduce_wire_bytes,
    reset,
    summary,
)
from tpumetrics.telemetry.sinks import JsonlSink, LoggingSink, TelemetrySink

# Lockstep names resolve lazily (PEP 562): lockstep.py pulls in
# tpumetrics.utils (for the exception base class), whose distributed module
# imports parallel/backend.py — which itself imports the ledger at module
# top.  Deferring lockstep breaks that bootstrap cycle while keeping
# ``telemetry.verify_lockstep`` / ``telemetry.LockstepViolation`` public.
_LOCKSTEP_NAMES = (
    "LockstepViolation",
    "configure",
    "lockstep_verification_enabled",
    "normalize_schedule",
    "schedule_fingerprint",
    "should_verify",
    "verify_lockstep",
)


def __getattr__(name: str):
    if name in _LOCKSTEP_NAMES or name == "lockstep":
        import importlib

        mod = importlib.import_module("tpumetrics.telemetry.lockstep")
        return mod if name == "lockstep" else getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

__all__ = [
    "CollectiveLedger",
    "CollectiveRecord",
    "JsonlSink",
    "LockstepViolation",
    "LoggingSink",
    "TelemetrySink",
    "attribution",
    "capture",
    "configure",
    "current_tag",
    "disable",
    "enable",
    "enabled",
    "gather_wire_bytes",
    "get_ledger",
    "lockstep_verification_enabled",
    "normalize_schedule",
    "record_collective",
    "record_event",
    "record_flush",
    "recording",
    "reduce_wire_bytes",
    "reset",
    "schedule_fingerprint",
    "should_verify",
    "summary",
    "verify_lockstep",
]
