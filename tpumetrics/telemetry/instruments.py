"""Low-overhead instruments: counters, gauges, fixed-bucket histograms.

Where spans (:mod:`~tpumetrics.telemetry.spans`) answer "where did THIS
batch's time go", instruments answer "what is the distribution" — cheaply
enough to sit on the submit path of a 1000-stream service: one
``observe()`` is a flag test, a label-tuple dict lookup, a bisect over a
dozen bucket edges, and four integer/float updates under a per-instrument
lock.  No allocation after the first observation of a label set.

The registry is **process-global and get-or-create**: any module may call
:func:`counter`/:func:`gauge`/:func:`histogram` with the same name and get
the same instrument (a type or label mismatch raises — names are a
contract).  ``bench.py`` and ``stats()`` read the same histograms the
runtime writes, and :func:`tpumetrics.telemetry.export.prometheus_text`
exposes the whole registry in Prometheus text format.

Label cardinality is the caller's budget (see ``docs/observability.md``):
every distinct label tuple materializes one series.  The runtime labels by
stream/tenant id — thousands are fine (a histogram series is ~20 numbers);
never label by batch content or shape.

Instruments default **enabled** (unlike spans, they are cheap enough to
leave on); :func:`disable` turns every ``inc``/``set``/``observe`` into a
single flag test for processes that want literally zero accounting.

Histogram quantiles are estimated from the fixed buckets (linear
interpolation inside the covering bucket; the overflow bucket reports the
exact tracked ``max``), so a ``p99`` is only as fine as the bucket grid —
the default millisecond grid resolves sub-millisecond latencies, which is
what the soak gate needs.  ``sum``/``count``/``max`` are exact.

**Sketch mode** (``histogram(..., sketch=True)``) additionally folds every
observation into a sparse host-side log-linear sketch with EXACTLY the
geometry of :class:`tpumetrics.monitoring.sketch.SketchLayout` (levels ×
capacity linear buckets per magnitude octave, mirrored per sign, exact
min/max envelope — a parity test pins the bin indices against the device
sketch).  Quantile reads then carry the sketch's documented bound —
**relative error ≤ 1/capacity** inside the covered magnitude range —
instead of fixed-grid interpolation, and because the sketch is a sparse
count map its merge is a plain key-wise sum: serialized series from N
processes federate into one exact-bound distribution
(:mod:`tpumetrics.telemetry.federate`).  The Prometheus exposition is
unchanged (the fixed ``le`` buckets still export); only ``quantile()``/
``summary()`` and the federation payload see the sketch.  Cost per
``observe``: one log2, two clips, one dict bump — the runtime's shared
submit/dispatch/restore histograms run in this mode.
"""

from __future__ import annotations

import math
import threading
from bisect import bisect_left
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Instrument",
    "SKETCH_CAPACITY",
    "SKETCH_LEVELS",
    "counter",
    "disable",
    "enable",
    "enabled",
    "gauge",
    "get_instrument",
    "histogram",
    "latency_section",
    "registry",
    "reset",
    "sketch_index",
    "sketch_quantile",
]

_ENABLED = True
_LOCK = threading.Lock()
_REGISTRY: "Dict[str, Instrument]" = {}

#: default latency grid (milliseconds): resolves the sub-ms enqueue-shaped
#: submit path and still covers multi-second stalls
DEFAULT_MS_BUCKETS: Tuple[float, ...] = (
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
    25.0, 50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0, 10000.0,
)
#: default duration grid (seconds): XLA compile times
DEFAULT_S_BUCKETS: Tuple[float, ...] = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

# -------------------------------------------------------- sketch geometry
#
# The host-side mirror of monitoring/sketch.py's SketchLayout index math —
# pure python so the submit path never touches jax.  Parameters default to
# the device sketch's defaults; a parity test pins the two bucket_index
# implementations against each other, so the geometry cannot drift.

#: default sketch geometry for sketch-mode histograms (matches
#: monitoring.sketch.SketchLayout defaults: relative error <= 1/capacity)
SKETCH_LEVELS = 44
SKETCH_CAPACITY = 64


def _sketch_unit(levels: int) -> float:
    return 2.0 ** (24 - levels)


def sketch_index(value: float, levels: int = SKETCH_LEVELS,
                 capacity: int = SKETCH_CAPACITY) -> int:
    """Flat sketch-slot index of one value (sign-mirrored, level-major) —
    bit-identical to ``SketchLayout.bucket_index`` on the same geometry."""
    unit = _sketch_unit(levels)
    a = abs(value)
    if a != a:  # NaN: bin like the device sketch's masked zero
        a = 0.0
    safe = max(a, unit * 2.0 ** -40)
    if math.isinf(safe):  # the device sketch's float-space clip to the top level
        lvl = levels - 1
    else:
        lvl = min(max(int(math.floor(math.log2(safe / unit))) + 1, 0), levels - 1)
    if lvl == 0:
        lo, width = 0.0, unit
    else:
        lo = width = unit * 2.0 ** (lvl - 1)
    if math.isinf(a):
        j = capacity - 1  # inf outliers clip into the top bucket, not wrap
    else:
        j = min(max(int((a - lo) * capacity / width), 0), capacity - 1)
    flat = lvl * capacity + j
    side = levels * capacity
    return flat + side if value < 0 else flat


def _sketch_rep(index: int, levels: int, capacity: int) -> float:
    """Signed bucket-midpoint representative value of one sketch slot."""
    unit = _sketch_unit(levels)
    side = levels * capacity
    sign = -1.0 if index >= side else 1.0
    flat = index - side if index >= side else index
    lvl, j = divmod(flat, capacity)
    if lvl == 0:
        lo, width = 0.0, unit
    else:
        lo = width = unit * 2.0 ** (lvl - 1)
    return sign * (lo + (j + 0.5) * (width / capacity))


def sketch_quantile(
    counts: Dict[int, float],
    q: float,
    *,
    minimum: float,
    maximum: float,
    levels: int = SKETCH_LEVELS,
    capacity: int = SKETCH_CAPACITY,
) -> Optional[float]:
    """q-quantile of a sparse sketch count map: midpoint lookup on the
    cumulative counts in ascending value order, clamped into the exact
    ``[minimum, maximum]`` envelope (``SketchLayout.quantile`` semantics).
    ``None`` on an empty sketch.  THE one copy of the read — live
    summaries and the federated merged view both call it."""
    total = sum(counts.values())
    if total <= 0:
        return None
    reps = sorted(
        (_sketch_rep(i, levels, capacity), c) for i, c in counts.items() if c > 0
    )
    rank = q * total
    cum = 0.0
    est = reps[-1][0]
    for rep, c in reps:
        cum += c
        if cum >= rank:
            est = rep
            break
    return min(max(est, minimum), maximum)

# shared instrument names the runtime registers (stats()/bench read these)
SUBMIT_LATENCY_MS = "tpumetrics_submit_latency_ms"
DISPATCH_LATENCY_MS = "tpumetrics_dispatch_latency_ms"
QUEUE_DEPTH = "tpumetrics_queue_depth"
TENANTS_LIVE = "tpumetrics_tenants_live"
JOURNAL_LEN = "tpumetrics_journal_len"
XLA_COMPILE_SECONDS = "tpumetrics_xla_compile_seconds"
RECOMPILES_TOTAL = "tpumetrics_recompiles_total"
DRIFT_SCORE = "tpumetrics_drift_score"
DRIFT_ALERTS = "tpumetrics_drift_alerts_total"
RESTORE_LATENCY_MS = "tpumetrics_restore_latency_ms"
DRAIN_LATENCY_MS = "tpumetrics_drain_latency_ms"
# device-side observability (telemetry/device.py + telemetry/health.py)
PROGRAM_FLOPS = "tpumetrics_program_flops"
PROGRAM_HBM_BYTES = "tpumetrics_program_hbm_bytes"
STATE_HBM_BYTES = "tpumetrics_state_hbm_bytes"
STATE_NONFINITE = "tpumetrics_state_nonfinite_total"
# SLO engine (telemetry/slo.py)
SLO_BURN_RATE = "tpumetrics_slo_burn_rate"
SLO_VIOLATIONS = "tpumetrics_slo_violations_total"
# tenant lifecycle (lifecycle/manager.py)
RESIDENT_TENANTS = "tpumetrics_resident_tenants"
HIBERNATED_BYTES = "tpumetrics_hibernated_bytes"
REVIVAL_LATENCY_MS = "tpumetrics_revival_latency_ms"
# fleet placement + migration (fleet/)
FLEET_RANKS = "tpumetrics_fleet_ranks"
ROUTING_EPOCH = "tpumetrics_routing_epoch"
MIGRATION_LATENCY_MS = "tpumetrics_migration_latency_ms"
MIGRATIONS_TOTAL = "tpumetrics_migrations_total"
AUTOSCALE_DECISIONS = "tpumetrics_autoscale_decisions_total"
# storage fault tolerance (resilience/storage.py + the evaluator's
# durability-degradation latch)
IO_RETRIES_TOTAL = "tpumetrics_io_retries_total"
DURABILITY_DEGRADED = "tpumetrics_durability_degraded"


def enabled() -> bool:
    return _ENABLED


def enable() -> None:
    global _ENABLED
    _ENABLED = True


def disable() -> None:
    global _ENABLED
    _ENABLED = False


class Instrument:
    """Base: a named family of label-keyed series."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "", labels: Sequence[str] = ()) -> None:
        self.name = name
        self.help = help
        self.labelnames: Tuple[str, ...] = tuple(labels)
        self._lock = threading.Lock()

    def _check_labels(self, labels: Tuple[Any, ...]) -> None:
        if len(labels) != len(self.labelnames):
            raise ValueError(
                f"{self.kind} {self.name!r} takes {len(self.labelnames)} label "
                f"value(s) {self.labelnames}, got {len(labels)}"
            )

    def clear(self) -> None:
        raise NotImplementedError

    def collect(self) -> Iterator[Tuple[Tuple[str, ...], Any]]:
        """Yield ``(label_values, value)`` per series (export format)."""
        raise NotImplementedError

    def to_dict(self) -> Dict[str, Any]:
        return {
            "type": self.kind,
            "name": self.name,
            "help": self.help,
            "labels": list(self.labelnames),
            "series": [
                {"label_values": list(lv), "value": v} for lv, v in self.collect()
            ],
        }


class Counter(Instrument):
    """Monotonically increasing count per label tuple."""

    kind = "counter"

    def __init__(self, name: str, help: str = "", labels: Sequence[str] = ()) -> None:
        super().__init__(name, help, labels)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, n: float = 1.0, *labels: str) -> None:
        if not _ENABLED:
            return
        self._check_labels(labels)
        with self._lock:
            self._values[labels] = self._values.get(labels, 0.0) + n

    def value(self, *labels: str) -> float:
        with self._lock:
            if not self.labelnames:
                return self._values.get((), 0.0)
            if labels:
                return self._values.get(labels, 0.0)
            return sum(self._values.values())  # aggregate across label sets

    def remove(self, *labels: str) -> None:
        with self._lock:
            self._values.pop(labels, None)

    def clear(self) -> None:
        with self._lock:
            self._values.clear()

    def collect(self) -> Iterator[Tuple[Tuple[str, ...], float]]:
        with self._lock:
            items = list(self._values.items())
        yield from items


class Gauge(Instrument):
    """Last-set value per label tuple (queue depth, live tenants, …)."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "", labels: Sequence[str] = ()) -> None:
        super().__init__(name, help, labels)
        self._values: Dict[Tuple[str, ...], float] = {}

    def set(self, value: float, *labels: str) -> None:
        if not _ENABLED:
            return
        self._check_labels(labels)
        with self._lock:
            self._values[labels] = float(value)

    def inc(self, n: float = 1.0, *labels: str) -> None:
        if not _ENABLED:
            return
        self._check_labels(labels)
        with self._lock:
            self._values[labels] = self._values.get(labels, 0.0) + n

    def dec(self, n: float = 1.0, *labels: str) -> None:
        self.inc(-n, *labels)

    def value(self, *labels: str) -> float:
        with self._lock:
            return self._values.get(labels, 0.0)

    def remove(self, *labels: str) -> None:
        with self._lock:
            self._values.pop(labels, None)

    def clear(self) -> None:
        with self._lock:
            self._values.clear()

    def collect(self) -> Iterator[Tuple[Tuple[str, ...], float]]:
        with self._lock:
            items = list(self._values.items())
        yield from items


class _Series:
    __slots__ = ("counts", "sum", "count", "max", "min", "sketch")

    def __init__(self, n_buckets: int, sketch: bool = False) -> None:
        self.counts = [0] * n_buckets
        self.sum = 0.0
        self.count = 0
        self.max = 0.0
        self.min = float("inf")  # exact envelope (sketch-mode clamp)
        # sparse sketch-slot counts ({flat index: count}); None in plain mode
        self.sketch: Optional[Dict[int, float]] = {} if sketch else None


class Histogram(Instrument):
    """Fixed-bucket latency/duration distribution per label tuple.

    ``buckets`` are finite upper edges (an overflow ``+Inf`` bucket is
    implicit); ``sum``/``count``/``max`` are tracked exactly per series.
    """

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str = "",
        labels: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_MS_BUCKETS,
        sketch: bool = False,
    ) -> None:
        super().__init__(name, help, labels)
        edges = tuple(sorted(float(b) for b in buckets))
        if not edges:
            raise ValueError("histogram needs at least one bucket edge")
        self.edges = edges
        #: sketch mode (module docstring): quantiles carry the sketch's
        #: <= 1/capacity relative-error bound and series become federatable
        self.sketch = bool(sketch)
        self.sketch_levels = SKETCH_LEVELS
        self.sketch_capacity = SKETCH_CAPACITY
        self._series: Dict[Tuple[str, ...], _Series] = {}

    def observe(self, value: float, *labels: str) -> None:
        if not _ENABLED:
            return
        self._check_labels(labels)
        i = bisect_left(self.edges, value)
        si = (
            sketch_index(value, self.sketch_levels, self.sketch_capacity)
            if self.sketch
            else -1
        )
        with self._lock:
            row = self._series.get(labels)
            if row is None:
                row = self._series[labels] = _Series(len(self.edges) + 1, self.sketch)
            row.counts[i] += 1
            row.sum += value
            row.count += 1
            if value > row.max:
                row.max = value
            if value < row.min:
                row.min = value
            if row.sketch is not None:
                row.sketch[si] = row.sketch.get(si, 0.0) + 1.0

    # ------------------------------------------------------------- reading

    def _aggregate(self, labels: Optional[Tuple[str, ...]]) -> _Series:
        agg = _Series(len(self.edges) + 1, self.sketch)
        with self._lock:
            rows = (
                [self._series[labels]]
                if labels is not None and labels in self._series
                else ([] if labels is not None else list(self._series.values()))
            )
            for row in rows:
                for i, c in enumerate(row.counts):
                    agg.counts[i] += c
                agg.sum += row.sum
                agg.count += row.count
                agg.max = max(agg.max, row.max)
                agg.min = min(agg.min, row.min)
                if agg.sketch is not None and row.sketch is not None:
                    for si, c in row.sketch.items():
                        agg.sketch[si] = agg.sketch.get(si, 0.0) + c
        return agg

    def _quantile_of(self, agg: _Series, q: float) -> Optional[float]:
        if agg.count == 0:
            return None
        if agg.sketch:
            # sketch mode: bucket-midpoint lookup with the documented
            # <= 1/capacity relative-error bound, clamped to the exact
            # [min, max] envelope — SketchLayout.quantile semantics
            return sketch_quantile(
                agg.sketch, q, minimum=agg.min, maximum=agg.max,
                levels=self.sketch_levels, capacity=self.sketch_capacity,
            )
        rank = q * agg.count
        cum = 0.0
        for i, c in enumerate(agg.counts):
            prev = cum
            cum += c
            if cum >= rank and c > 0:
                if i == len(self.edges):  # overflow bucket: exact max
                    return agg.max
                lo = self.edges[i - 1] if i > 0 else 0.0
                hi = self.edges[i]
                frac = (rank - prev) / c
                return min(lo + (hi - lo) * frac, agg.max if agg.max > 0 else hi)
        return agg.max

    def quantile(self, q: float, *labels: str) -> Optional[float]:
        """Bucket-interpolated q-quantile (``labels`` empty = aggregate over
        every series).  ``None`` with no observations.  Values landing in
        the overflow bucket report the exact tracked max."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        return self._quantile_of(self._aggregate(labels if labels else None), q)

    def summary(self, *labels: str) -> Dict[str, Any]:
        """``{"count", "p50", "p90", "p99", "max"}`` for one label tuple (or
        the cross-label aggregate when no labels are given).  One locked
        aggregation serves all three quantiles — at 1000-series scale the
        scan, not the math, is the cost."""
        agg = self._aggregate(labels if labels else None)
        if agg.count == 0:
            return {"count": 0, "p50": None, "p90": None, "p99": None, "max": None}
        return {
            "count": agg.count,
            "p50": self._quantile_of(agg, 0.50),
            "p90": self._quantile_of(agg, 0.90),
            "p99": self._quantile_of(agg, 0.99),
            "max": agg.max,
        }

    def remove(self, *labels: str) -> None:
        """Drop one label tuple's series (a closed stream releasing its
        auto-minted label from the process-global registry)."""
        with self._lock:
            self._series.pop(labels, None)

    def clear(self) -> None:
        with self._lock:
            self._series.clear()

    def collect(self) -> Iterator[Tuple[Tuple[str, ...], Dict[str, Any]]]:
        with self._lock:
            rows = list(self._series.items())
        for lv, row in rows:
            data = {
                "buckets": list(zip(self.edges, row.counts[:-1])),
                "overflow": row.counts[-1],
                "sum": row.sum,
                "count": row.count,
                "max": row.max,
                "min": row.min if row.count else None,
            }
            if row.sketch is not None:
                # JSON-able sparse sketch state: the federation payload
                # (key-wise sum is the merge; telemetry/federate.py)
                data["sketch"] = {str(i): c for i, c in row.sketch.items()}
            yield lv, data

    def to_dict(self) -> Dict[str, Any]:
        out = super().to_dict()
        if self.sketch:
            out["sketch_params"] = {
                "levels": self.sketch_levels, "capacity": self.sketch_capacity,
            }
        return out


# ------------------------------------------------------------------ registry


def _get_or_create(cls: type, name: str, help: str, labels: Sequence[str], **kwargs: Any):
    with _LOCK:
        got = _REGISTRY.get(name)
        if got is not None:
            if type(got) is not cls or got.labelnames != tuple(labels):
                raise ValueError(
                    f"instrument {name!r} already registered as {got.kind} with "
                    f"labels {got.labelnames}; requested {cls.kind} with "
                    f"labels {tuple(labels)} — instrument names are a contract"
                )
            return got
        inst = cls(name, help=help, labels=labels, **kwargs)
        _REGISTRY[name] = inst
        return inst


def counter(name: str, help: str = "", labels: Sequence[str] = ()) -> Counter:
    """Get-or-create the named :class:`Counter`."""
    return _get_or_create(Counter, name, help, labels)


def gauge(name: str, help: str = "", labels: Sequence[str] = ()) -> Gauge:
    """Get-or-create the named :class:`Gauge`."""
    return _get_or_create(Gauge, name, help, labels)


def histogram(
    name: str,
    help: str = "",
    labels: Sequence[str] = (),
    buckets: Optional[Sequence[float]] = None,
    sketch: bool = False,
) -> Histogram:
    """Get-or-create the named :class:`Histogram` (``buckets`` and
    ``sketch`` only apply at creation; a later mismatched value is ignored
    — like the edges, the quantile mode is part of the first
    registration)."""
    return _get_or_create(
        Histogram, name, help, labels,
        buckets=tuple(buckets) if buckets is not None else DEFAULT_MS_BUCKETS,
        sketch=bool(sketch),
    )


def latency_section(stream: str) -> Dict[str, Any]:
    """The ``stats()["latency"]`` payload for one stream/tenant label:
    submit and device-dispatch latency summaries (p50/p90/p99/max/count)
    read from the shared runtime histograms.  All-``None`` summaries when
    nothing was observed (instruments disabled, or a fresh stream)."""
    return {
        "submit_ms": histogram(
            SUBMIT_LATENCY_MS, help="submit() call latency", labels=("stream",),
            sketch=True,
        ).summary(stream),
        "dispatch_ms": histogram(
            DISPATCH_LATENCY_MS, help="device dispatch latency", labels=("stream",),
            sketch=True,
        ).summary(stream),
    }


def registry() -> List[Instrument]:
    """Snapshot of every registered instrument (export order: by name)."""
    with _LOCK:
        return [_REGISTRY[k] for k in sorted(_REGISTRY)]


def get_instrument(name: str) -> Optional[Instrument]:
    """The registered instrument, or ``None`` — a pure read (no
    get-or-create side effects: SLO signals and federation must observe
    the registry, never mint families)."""
    with _LOCK:
        return _REGISTRY.get(name)


def reset(full: bool = False) -> None:
    """Clear every instrument's series (``full=True`` drops registrations
    too — tests only; long-lived processes keep the families)."""
    with _LOCK:
        if full:
            _REGISTRY.clear()
            return
        insts = list(_REGISTRY.values())
    for inst in insts:
        inst.clear()
