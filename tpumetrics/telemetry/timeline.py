"""Cross-rank timelines: merge per-rank JSONL streams, find the straggler.

Every ledger record (and span) carries a **monotonic + wall clock pair**
stamped at the same instant.  Within one process the monotonic clock orders
events exactly; across processes the monotonic epochs differ, so this
module aligns each per-rank stream onto one global axis using the pair:
``offset = median(wall_ns - mono_ns)`` over the stream (the median rejects
an NTP step mid-stream), and ``t_global_ns = mono_ns + offset`` — wall-
anchored, monotonic-ordered.

The soak workers already flush the global ledger to per-rank JSONL sinks
(``<root>/telemetry/epochNNN-rankNNNNN.jsonl``); :func:`merge_timelines`
turns that directory into one clock-aligned :class:`GlobalTimeline`, and:

- :func:`collective_windows` groups the timeline's sync points — one
  window per ``(kind, epoch, step)`` for step-stamped collectives like the
  ``elastic_barrier`` each coordinated cut runs, k-th-occurrence matching
  otherwise — and computes each window's **entry skew** across ranks;
- :func:`straggler_report` names the slowest rank per window and the rank
  that is slowest most often — "which rank is the straggler" as a first-
  class answer instead of a grep;
- :func:`to_perfetto` renders the merged timeline as Chrome trace-event
  JSON (one process per rank) via
  :func:`tpumetrics.telemetry.export.perfetto_trace`, so a whole soak
  opens in Perfetto.

``python -m tpumetrics.soak report <root>`` drives all three from the CLI,
and the soak supervisor attaches the straggler summary to every incident
line it emits.
"""

from __future__ import annotations

import json
import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple, Union

__all__ = [
    "GlobalTimeline",
    "collective_windows",
    "load_rank_streams",
    "merge_timelines",
    "parse_jsonl",
    "render_report",
    "straggler_report",
    "to_perfetto",
]

#: the soak worker's per-rank sink naming convention
RANK_FILE_RE = re.compile(r"epoch(\d+)-rank(\d+)\.jsonl$")

#: ledger kinds that are cross-rank sync points (every rank emits one per
#: window); used by the default straggler analysis
SYNC_KINDS = ("elastic_barrier",)


@dataclass
class GlobalTimeline:
    """One clock-aligned, cross-rank event sequence.

    ``events`` are the per-rank JSONL dicts, each augmented with ``rank``,
    ``epoch``, and ``t_global_ns`` (wall-anchored global nanoseconds),
    sorted by ``t_global_ns``.  ``offsets`` records the per-(rank, epoch)
    wall−mono offset the alignment used.
    """

    events: List[Dict[str, Any]] = field(default_factory=list)
    ranks: List[int] = field(default_factory=list)
    offsets: Dict[Tuple[int, int], int] = field(default_factory=dict)

    def span_ns(self) -> int:
        if not self.events:
            return 0
        return self.events[-1]["t_global_ns"] - self.events[0]["t_global_ns"]

    def by_rank(self) -> Dict[int, List[Dict[str, Any]]]:
        out: Dict[int, List[Dict[str, Any]]] = {r: [] for r in self.ranks}
        for e in self.events:
            out.setdefault(e["rank"], []).append(e)
        return out


def _median(values: List[int]) -> int:
    vals = sorted(values)
    return vals[len(vals) // 2]


def parse_jsonl(path: str) -> List[Dict[str, Any]]:
    """Parse one JSONL record stream (undecodable lines and non-dict values
    are skipped — a killed worker can leave a torn tail, which is evidence,
    not an error).  THE parse rule for per-rank telemetry: the supervisor's
    incremental per-incident cache and :func:`load_rank_streams` both read
    through here, so the two can never drift."""
    records: List[Dict[str, Any]] = []
    with open(path) as fh:
        for line in fh:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict):
                records.append(rec)
    return records


def load_rank_streams(
    directory: str,
) -> Dict[Tuple[int, int], List[Dict[str, Any]]]:
    """Parse every ``epochNNN-rankNNNNN.jsonl`` under ``directory`` into
    ``{(rank, epoch): [record, ...]}``."""
    streams: Dict[Tuple[int, int], List[Dict[str, Any]]] = {}
    if not os.path.isdir(directory):
        return streams
    for name in sorted(os.listdir(directory)):
        m = RANK_FILE_RE.search(name)
        if not m:
            continue
        epoch, rank = int(m.group(1)), int(m.group(2))
        records = parse_jsonl(os.path.join(directory, name))
        if records:
            streams.setdefault((rank, epoch), []).extend(records)
    return streams


def merge_timelines(
    source: Union[str, Dict[Tuple[int, int], List[Dict[str, Any]]]],
) -> GlobalTimeline:
    """Align per-rank streams (a soak telemetry directory, or the mapping
    :func:`load_rank_streams` returns) onto one global wall-anchored axis.

    Records without a clock pair (``mono_ns == 0`` — written before PR 13,
    or synthesized) fall back to their ``wall_ns`` (or 0) so old soak
    output still merges, just with wall-clock precision only."""
    streams = load_rank_streams(source) if isinstance(source, str) else source
    timeline = GlobalTimeline()
    for (rank, epoch), records in sorted(streams.items()):
        pairs = [
            (r["wall_ns"] - r["mono_ns"])
            for r in records
            if r.get("mono_ns") and r.get("wall_ns")
        ]
        offset = _median(pairs) if pairs else 0
        timeline.offsets[(rank, epoch)] = offset
        for rec in records:
            rec = dict(rec)
            rec["rank"] = rank
            rec["epoch"] = epoch
            mono = rec.get("mono_ns") or 0
            rec["t_global_ns"] = (
                mono + offset if mono else int(rec.get("wall_ns") or 0)
            )
            timeline.events.append(rec)
    timeline.events.sort(key=lambda e: (e["t_global_ns"], e["rank"]))
    timeline.ranks = sorted({e["rank"] for e in timeline.events})
    return timeline


def collective_windows(
    timeline: GlobalTimeline, kinds: Tuple[str, ...] = SYNC_KINDS
) -> List[Dict[str, Any]]:
    """Group the timeline's sync-point records into cross-rank windows and
    compute each window's entry skew.

    Window identity: ``(kind, epoch, step)`` when the record's ``extra``
    carries a ``step`` (the elastic barrier stamps one — every rank of a
    coordinated cut shares it); otherwise the k-th occurrence of ``kind``
    on each rank within the epoch (the lockstep contract: ranks issue sync
    collectives in identical order, which ``verify_lockstep`` enforces at
    runtime).  Each window reports per-rank entry times, the skew
    (max − min, ms), and the slowest (last-arriving) rank."""
    occurrence: Dict[Tuple[int, int, str], int] = {}
    grouped: Dict[Tuple, Dict[int, int]] = {}
    for e in timeline.events:
        kind = e.get("kind")
        if kind not in kinds:
            continue
        rank, epoch = e["rank"], e["epoch"]
        step = (e.get("extra") or {}).get("step")
        if step is not None:
            key: Tuple = (kind, epoch, "step", step)
        else:
            i = occurrence.get((rank, epoch, kind), 0)
            occurrence[(rank, epoch, kind)] = i + 1
            key = (kind, epoch, "occ", i)
        # first arrival per rank defines the rank's entry into the window
        grouped.setdefault(key, {}).setdefault(rank, e["t_global_ns"])
    windows = []
    for key in sorted(grouped, key=lambda k: min(grouped[k].values())):
        entries = grouped[key]
        if len(entries) < 2:
            continue  # a 1-rank window has no skew to speak of
        t_min = min(entries.values())
        t_max = max(entries.values())
        slowest = max(entries, key=lambda r: (entries[r], r))
        windows.append(
            {
                "kind": key[0],
                "epoch": key[1],
                "window": key[3],
                "keyed_by": key[2],
                "ranks": sorted(entries),
                "entry_ns": {str(r): entries[r] for r in sorted(entries)},
                "skew_ms": (t_max - t_min) / 1e6,
                "slowest_rank": slowest,
            }
        )
    return windows


def straggler_report(
    timeline: GlobalTimeline, kinds: Tuple[str, ...] = SYNC_KINDS
) -> Dict[str, Any]:
    """Who is holding the job back: per-window skew + the rank that arrives
    last most often.  ``straggler`` is ``None`` when no multi-rank window
    exists (a world-1 soak, or telemetry without sync kinds)."""
    windows = collective_windows(timeline, kinds=kinds)
    counts: Dict[int, int] = {}
    for w in windows:
        counts[w["slowest_rank"]] = counts.get(w["slowest_rank"], 0) + 1
    straggler = (
        max(counts, key=lambda r: (counts[r], -r)) if counts else None
    )
    return {
        "windows": windows,
        "n_windows": len(windows),
        "slowest_counts": {str(r): n for r, n in sorted(counts.items())},
        "straggler": straggler,
        "max_skew_ms": max((w["skew_ms"] for w in windows), default=0.0),
        "mean_skew_ms": (
            sum(w["skew_ms"] for w in windows) / len(windows) if windows else 0.0
        ),
    }


def to_perfetto(
    timeline: GlobalTimeline, target: Optional[str] = None
) -> Union[Dict[str, Any], str]:
    """Render the merged timeline as Chrome trace-event JSON — one Perfetto
    process per rank, the ``t_global_ns`` axis, every record exactly once
    (records that are spans — ``type == "span"`` lines from a flight dump —
    render as slices, ledger records as collective slices / instants)."""
    from tpumetrics.telemetry import export as _export

    span_like = [e for e in timeline.events if e.get("type") == "span"]
    ledger_like = [e for e in timeline.events if e.get("type") != "span"]
    return _export.perfetto_trace(
        target,
        span_list=span_like,
        record_list=ledger_like,
        rank_of=lambda d: int(d.get("rank", 0)),
        process_names={r: f"rank {r}" for r in timeline.ranks},
    )


def render_report(
    timeline: GlobalTimeline, report: Dict[str, Any], max_windows: int = 12
) -> str:
    """Human-readable straggler summary (the CLI's output)."""
    lines = []
    by_rank = timeline.by_rank()
    lines.append(
        f"timeline: {len(timeline.events)} events over {len(timeline.ranks)} "
        f"rank(s), {timeline.span_ns() / 1e9:.3f}s span"
    )
    for rank in timeline.ranks:
        lines.append(f"  rank {rank}: {len(by_rank.get(rank, []))} events")
    lines.append(
        f"sync windows: {report['n_windows']} "
        f"(max skew {report['max_skew_ms']:.3f}ms, "
        f"mean {report['mean_skew_ms']:.3f}ms)"
    )
    shown = report["windows"][:max_windows]
    for w in shown:
        lines.append(
            f"  {w['kind']} epoch {w['epoch']} window {w['window']}: "
            f"skew {w['skew_ms']:.3f}ms, slowest rank {w['slowest_rank']}"
        )
    if len(report["windows"]) > len(shown):
        lines.append(f"  … {len(report['windows']) - len(shown)} more window(s)")
    if report["straggler"] is not None:
        lines.append(
            f"straggler: rank {report['straggler']} "
            f"(slowest in {report['slowest_counts'][str(report['straggler'])]}"
            f"/{report['n_windows']} windows)"
        )
    else:
        lines.append("straggler: none (no multi-rank sync window found)")
    return "\n".join(lines)
