"""Sync-schedule fingerprinting and cross-rank lockstep verification.

The eager multi-host sync protocol (``MultiHostBackend`` over DCN) requires
**every rank to issue the same collectives in the same order**: candidate
selection for a sync depends on per-rank flags (``_computed`` cache,
``_is_synced``, ``_to_sync``), so a single rank with, say, a cached compute
value would silently skip its collectives and deadlock every other rank —
ADVICE r5 #3.  This module converts that hang into a diagnosable error:

1. each rank normalizes its *intended* collective schedule — an ordered list
   of ``(tag, op, dtype, shape)`` entries — and hashes it;
2. the digests (plus the schedules themselves, for diagnosis) are exchanged
   over the backend's existing host-object channel
   (:meth:`DistributedBackend.all_gather_object`) **before** any state
   collective is issued;
3. a mismatch raises :class:`LockstepViolation` naming the diverging rank and
   the first differing schedule entry.

In-trace backends (``AxisBackend``) have no host round trip: they skip the
exchange and only record the fingerprint into the collective ledger.  The
exchange itself is one extra small object gather per verified flush; disable
it globally with :func:`configure` (``lockstep_verification=False``) when the
round trip matters more than the diagnosis.
"""

from __future__ import annotations

import hashlib
from typing import Any, List, Optional, Sequence, Tuple

from tpumetrics.telemetry import ledger as _ledger
from tpumetrics.utils.exceptions import TPUMetricsUserError

__all__ = [
    "LockstepViolation",
    "configure",
    "lockstep_verification_enabled",
    "normalize_schedule",
    "schedule_fingerprint",
    "should_verify",
    "verify_lockstep",
]

_VERIFY = True


def configure(lockstep_verification: Optional[bool] = None) -> None:
    """Toggle the digest exchange (the ledger fingerprint is always recorded)."""
    global _VERIFY
    if lockstep_verification is not None:
        _VERIFY = bool(lockstep_verification)


def lockstep_verification_enabled() -> bool:
    return _VERIFY


def should_verify(backend: Any) -> bool:
    """Whether a digest exchange over ``backend`` is possible and enabled:
    eager (not in-trace), object-capable, spanning more than one rank."""
    if (
        not _VERIFY
        or getattr(backend, "in_trace", False)
        or not getattr(backend, "has_object_channel", False)
    ):
        return False
    try:
        return backend.world_size() > 1
    except Exception:
        return False


class LockstepViolation(TPUMetricsUserError):
    """Ranks disagree on the collective schedule of an eager sync.

    Raised on *every* participating rank (the exchanged schedules are
    identical inputs to an identical comparison), so no rank is left blocked
    in a half-issued sync.
    """


ScheduleEntry = Tuple[str, str, str, Tuple[int, ...]]  # (tag, op, dtype, shape)


def normalize_schedule(entries: Sequence[Sequence[Any]]) -> List[ScheduleEntry]:
    """Canonicalize schedule entries to hashable (tag, op, dtype, shape) tuples.

    ``shape`` participates only for reduce-op entries: gather-style states
    legitimately differ in dim-0 across ranks (pad-gather-trim), so their
    shape must not enter the fingerprint.
    """
    out: List[ScheduleEntry] = []
    for entry in entries:
        tag, op, dtype, shape = entry
        op = str(op)
        shape = tuple(int(d) for d in shape) if op in ("sum", "mean", "max", "min") else ()
        out.append((str(tag), op, str(dtype), shape))
    return out


def schedule_fingerprint(entries: Sequence[Sequence[Any]]) -> str:
    """Stable digest of a normalized schedule."""
    norm = normalize_schedule(entries)
    return hashlib.sha1(repr(norm).encode()).hexdigest()


def _rank_of(backend: Any) -> int:
    try:
        import jax

        return int(jax.process_index())
    except Exception:
        return -1


def verify_lockstep(
    backend: Any,
    entries: Sequence[Sequence[Any]],
    context: str = "",
    group: Optional[Any] = None,
) -> Optional[str]:
    """Fingerprint ``entries`` and, on eager multi-rank backends, exchange
    digests and raise :class:`LockstepViolation` on mismatch.

    Returns the local digest (handy for tests/logging).  The exchange is
    skipped — only the ledger fingerprint is recorded — when the backend is
    in-trace, has no host-object channel, spans a single rank, or
    verification is disabled via :func:`configure`.

    The happy path ships only the fixed-size digest; the full schedules are
    exchanged in a second gather ONLY on mismatch, to name the diverging
    rank and the first differing entry.  Blame assignment: with a strict
    majority digest the outlier rank is named; without one (e.g. two ranks)
    the disagreement is reported symmetrically — two ranks cannot tell who
    is "right".
    """
    from tpumetrics.resilience.policy import run_guarded

    norm = normalize_schedule(entries)
    digest = hashlib.sha1(repr(norm).encode()).hexdigest()
    in_trace = bool(getattr(backend, "in_trace", False))
    _ledger.record_event(
        backend, "lockstep", in_trace=in_trace, digest=digest, entries=len(norm), context=context
    )
    if not should_verify(backend):
        return digest

    # the digest exchange runs under the active SyncPolicy deadline: a dead
    # rank here (before any state collective!) becomes a typed
    # SyncTimeoutError instead of deadlocking the verifier itself
    digests = list(
        run_guarded(
            lambda: backend.all_gather_object(digest, group=group),
            op="lockstep_digest_exchange",
            backend=backend,
        )
    )
    lost = [r for r, d in enumerate(digests) if d is None]
    if lost:
        raise LockstepViolation(
            f"Sync-schedule digest exchange{f' in {context}' if context else ''} lost the "
            f"payload of rank(s) {lost} (object channel dropped the message): cannot prove "
            f"lockstep, refusing to issue state collectives (local rank {_rank_of(backend)})."
        )
    if len(set(digests)) == 1:
        return digest

    # mismatch: one more exchange ships the schedules for the diagnosis
    schedules = [
        [tuple(e) if not isinstance(e, tuple) else e for e in (s or ())]
        for s in run_guarded(
            lambda: backend.all_gather_object(norm, group=group),
            op="lockstep_schedule_exchange",
            backend=backend,
        )
    ]
    counts: dict = {}
    for d in digests:
        counts[d] = counts.get(d, 0) + 1
    best = max(counts.values())
    majority = [d for d, c in counts.items() if c == best]
    where = f" in {context}" if context else ""
    hint = (
        " Every rank must enter an eager multi-host sync with the same metric flags"
        " (_computed cache, _is_synced, _to_sync) and the same compute-group merges"
        " (auto-discovered groups merge on value-identical states after the first"
        " rank-local update, so borderline data can group differently per rank) —"
        " see docs/telemetry.md."
    )
    if best > len(digests) // 2 and len(majority) == 1:
        ref_digest = majority[0]
        ref_rank = digests.index(ref_digest)
        bad_rank = next(r for r, d in enumerate(digests) if d != ref_digest)
        idx, ref_entry, bad_entry = _first_difference(schedules[ref_rank], schedules[bad_rank])
        raise LockstepViolation(
            f"Cross-rank sync-schedule mismatch{where}: rank {bad_rank} diverges from the"
            f" majority (rank {ref_rank}'s schedule) at entry {idx}: rank {ref_rank}"
            f" intends {ref_entry}, rank {bad_rank} intends {bad_entry} (local rank"
            f" {_rank_of(backend)}, digests {digests})." + hint
        )
    # no strict majority (e.g. exactly two ranks): symmetric report
    rank_a = 0
    rank_b = next(r for r in range(1, len(digests)) if digests[r] != digests[0])
    idx, entry_a, entry_b = _first_difference(schedules[rank_a], schedules[rank_b])
    raise LockstepViolation(
        f"Cross-rank sync-schedule mismatch{where}: ranks {rank_a} and {rank_b} disagree"
        f" at schedule entry {idx}: rank {rank_a} intends {entry_a}, rank {rank_b} intends"
        f" {entry_b} (local rank {_rank_of(backend)}, digests {digests})." + hint
    )


def _first_difference(sched_a: List[Any], sched_b: List[Any]) -> Tuple[int, str, str]:
    idx = next(
        (i for i, (a, b) in enumerate(zip(sched_a, sched_b)) if _entry(a) != _entry(b)),
        min(len(sched_a), len(sched_b)),
    )
    entry_a = _entry(sched_a[idx]) if idx < len(sched_a) else "<no entry>"
    entry_b = _entry(sched_b[idx]) if idx < len(sched_b) else "<no entry>"
    return idx, entry_a, entry_b


def _entry(e: Any) -> str:
    try:
        tag, op, dtype, shape = e
        return f"(tag={tag!r}, op={op}, dtype={dtype}, shape={tuple(shape)})"
    except Exception:
        return repr(e)
