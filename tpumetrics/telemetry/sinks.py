"""Pluggable telemetry sinks.

A sink receives every :class:`~tpumetrics.telemetry.ledger.CollectiveRecord`
a ledger records (attach with ``CollectiveLedger.add_sink``, the ``sinks=``
argument of :func:`~tpumetrics.telemetry.ledger.capture`, or directly on the
global ledger).  Two stdlib-only implementations ship here:

- :class:`LoggingSink` — one ``logging`` line per record on the
  ``tpumetrics.telemetry`` logger.
- :class:`JsonlSink` — one JSON object per line, machine-readable (the
  format ``telemetry.summary()`` totals are derived from).
"""

from __future__ import annotations

import json
import logging
from typing import IO, Any, Optional, Union

from tpumetrics.telemetry.ledger import CollectiveRecord

__all__ = ["TelemetrySink", "LoggingSink", "JsonlSink"]


class TelemetrySink:
    """Interface: receives records as they are recorded."""

    def emit(self, record: CollectiveRecord) -> None:
        raise NotImplementedError

    def close(self) -> None:  # noqa: B027
        """Release resources (called when a ``capture`` scope exits)."""


class LoggingSink(TelemetrySink):
    """Emit each record through stdlib :mod:`logging`."""

    def __init__(self, logger: Optional[logging.Logger] = None, level: int = logging.INFO) -> None:
        self._logger = logger if logger is not None else logging.getLogger("tpumetrics.telemetry")
        self._level = level

    def emit(self, record: CollectiveRecord) -> None:
        self._logger.log(
            self._level,
            "collective %s op=%s dtype=%s shape=%s elements=%d wire_bytes=%.0f backend=%s tag=%s%s",
            record.kind,
            record.op,
            record.dtype,
            record.shape,
            record.element_count,
            record.wire_bytes,
            record.backend,
            record.tag or "-",
            " (in-trace)" if record.in_trace else "",
        )


class JsonlSink(TelemetrySink):
    """Append each record as one JSON line to a path or open text file."""

    def __init__(self, target: Union[str, IO[str]]) -> None:
        if isinstance(target, str):
            self._fh: IO[str] = open(target, "a")
            self._owns = True
        else:
            self._fh = target
            self._owns = False

    def emit(self, record: CollectiveRecord) -> None:
        self._fh.write(json.dumps(record.to_dict(), sort_keys=True) + "\n")

    def flush(self) -> None:
        self._fh.flush()

    def close(self) -> None:
        self._fh.flush()
        if self._owns:
            self._fh.close()


def _record_from_json(line: str) -> Any:
    """Parse one JSONL line back to a dict (test/analysis helper)."""
    return json.loads(line)
