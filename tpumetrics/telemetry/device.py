"""Device program profiles — what every compiled program costs, attributed.

PR 9 made host-side seams observable (spans, instruments, compile
attribution); this module opens the device side: every compiled XLA program
the runtime dispatches — the :class:`~tpumetrics.parallel.fuse_update.
FusedCollectionStep` programs behind bucketed evaluator steps and megabatch
groups, and the jitted mAP matcher — **registers** itself here once per
(program key, trace signature), under the same attribution identity the
compile attributor uses (tenant / step token / signature).  A registered
program's XLA ``cost_analysis()`` (flops, bytes accessed) and
``memory_analysis()`` (argument/output/temp/generated-code bytes — the HBM
a dispatch holds) are resolved **lazily on first read** and cached, so the
dispatch hot path pays only a seen-set lookup (benched as
``device_observability``'s ``profile_lookup_ns_per_call`` ceiling) and the
compile-twice cost of ``program.lower(...).compile()`` lands on the
*reader* (``stats()["device"]``, the bench, an operator poking
:func:`profiles`), never on a serving step.

Two registration modes:

- **gated** (:func:`note_dispatch`) — the runtime's per-dispatch hook: a
  no-op unless :func:`enable_device_profiles` armed the registry (one
  module-flag test when off, the PR 9 inert-predicate discipline).
- **always** (:func:`register_program`) — for the few programs whose cost
  IS the product (the detection matcher feeding the bench's MFU): one dict
  insert per distinct program key/signature regardless of the flag.  This
  replaces the detection-private ``last_cost_analysis()`` plumbing — one
  code path for program cost.

Resolved profiles feed two Prometheus gauges, both labeled by tenant and
released by the owning stream's ``close()``:

- ``tpumetrics_program_flops{tenant}`` — summed flops of one step through
  every program registered under the tenant (the bench's MFU numerator);
- ``tpumetrics_program_hbm_bytes{tenant}`` — the largest single program's
  total buffer footprint (arguments + outputs + temps), i.e. the peak HBM a
  dispatch for this tenant holds beyond its live state.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from tpumetrics.telemetry import instruments as _instruments

__all__ = [
    "ProgramProfile",
    "ProfileRegistry",
    "abstract_signature",
    "disable_device_profiles",
    "enable_device_profiles",
    "note_dispatch",
    "profile_summary",
    "profiles",
    "profiling_enabled",
    "registry",
    "release_profiles",
    "register_program",
    "reset_device_profiles",
    "tenant_scope",
]

_ENABLED = False

#: registered-program cap: a shape-churning adversarial stream degrades to
#: eviction accounting, never an unbounded registry (the signature-LRU rule)
_DEFAULT_CAPACITY = 1024

_FLOPS_GAUGE = _instruments.gauge(
    _instruments.PROGRAM_FLOPS,
    help="summed per-step flops of the tenant's registered device programs",
    labels=("tenant",),
)
_HBM_GAUGE = _instruments.gauge(
    _instruments.PROGRAM_HBM_BYTES,
    help="largest registered program's total buffer bytes (args+outputs+temps)",
    labels=("tenant",),
)


def profiling_enabled() -> bool:
    return _ENABLED


def enable_device_profiles() -> None:
    """Arm the per-dispatch registration hook (:func:`note_dispatch`)."""
    global _ENABLED
    _ENABLED = True


def disable_device_profiles() -> None:
    global _ENABLED
    _ENABLED = False


def _leaf_sig(leaf: Any) -> Tuple:
    # shape is already a tuple on jax/numpy arrays and dtype objects hash —
    # no re-tupling or str() per leaf: this runs per DISPATCH when profiling
    # is armed, and is what the profile_lookup_ns_per_call ceiling times
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        return (shape if type(shape) is tuple else tuple(shape), dtype)
    return ("py", type(leaf).__name__, repr(leaf)[:32])


_TREE_LEAVES = None  # jax.tree_util.tree_leaves, bound on first use (lazy jax)


def abstract_signature(args: Tuple[Any, ...]) -> Tuple:
    """A hashable (shape, dtype)-tuple signature over a pytree of call
    arguments — the registry's dedupe key (mirrors, but does not have to
    equal, the runtime's trace signatures)."""
    global _TREE_LEAVES
    if _TREE_LEAVES is None:
        import jax

        _TREE_LEAVES = jax.tree_util.tree_leaves
    return tuple(_leaf_sig(l) for l in _TREE_LEAVES(args))


def _abstract_args(args: Tuple[Any, ...]) -> Tuple[Any, ...]:
    """ShapeDtypeStruct pytree snapshot of concrete call args: what the lazy
    ``program.lower(...)`` needs, WITHOUT pinning the concrete buffers (a
    MATCH_BUDGET-scale dense grid held for the process lifetime was exactly
    the bug the detection module's abstract-spec convention avoided)."""
    import jax

    def one(leaf: Any) -> Any:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            return jax.ShapeDtypeStruct(tuple(shape), dtype)
        import jax.numpy as jnp

        arr = jnp.asarray(leaf)
        return jax.ShapeDtypeStruct(arr.shape, arr.dtype)

    return jax.tree_util.tree_map(one, args)


class ProgramProfile:
    """One registered compiled program; cost/memory analyses resolve lazily.

    ``resolve()`` runs ``program.lower(*abstract_args).compile()`` and reads
    XLA's ``cost_analysis``/``memory_analysis`` — real work (an XLA compile,
    typically served by the persistent cache), so it runs at most once per
    profile, on the reader's thread, and failures degrade to an ``error``
    note instead of raising into ``stats()``.
    """

    __slots__ = (
        "label", "tenant", "signature", "registered_mono_ns", "x64",
        "_program", "_abstract", "_resolved", "_lock",
    )

    def __init__(
        self,
        label: str,
        tenant: str,
        signature: Tuple,
        program: Any,
        abstract: Tuple[Any, ...],
        x64: bool = False,
    ) -> None:
        self.label = label
        self.tenant = tenant
        self.signature = signature
        self.registered_mono_ns = time.monotonic_ns()
        self.x64 = bool(x64)
        self._program = program
        self._abstract = abstract
        self._resolved: Optional[Dict[str, Any]] = None
        self._lock = threading.Lock()

    def resolve(self) -> Dict[str, Any]:
        with self._lock:
            if self._resolved is not None:
                return self._resolved
            out: Dict[str, Any] = {
                "label": self.label,
                "tenant": self.tenant,
                "flops": 0.0,
                "bytes_accessed": 0.0,
                "hbm_bytes": 0.0,
                "argument_bytes": 0.0,
                "output_bytes": 0.0,
                "temp_bytes": 0.0,
                "generated_code_bytes": 0.0,
            }
            try:
                from contextlib import nullcontext

                scope: Any = nullcontext()
                if self.x64:
                    from jax.experimental import enable_x64

                    scope = enable_x64()
                with scope:
                    compiled = self._program.lower(*self._abstract).compile()
                cost = compiled.cost_analysis()
                if isinstance(cost, list):  # older jaxlibs return [dict]
                    cost = cost[0] if cost else None
                if cost:
                    out["flops"] = float(cost.get("flops", 0.0))
                    out["bytes_accessed"] = float(cost.get("bytes accessed", 0.0))
                try:
                    mem = compiled.memory_analysis()
                except Exception:
                    mem = None
                if mem is not None:
                    for key, attr in (
                        ("argument_bytes", "argument_size_in_bytes"),
                        ("output_bytes", "output_size_in_bytes"),
                        ("temp_bytes", "temp_size_in_bytes"),
                        ("generated_code_bytes", "generated_code_size_in_bytes"),
                    ):
                        out[key] = float(getattr(mem, attr, 0.0) or 0.0)
                    alias = float(getattr(mem, "alias_size_in_bytes", 0.0) or 0.0)
                    out["hbm_bytes"] = max(
                        0.0,
                        out["argument_bytes"] + out["output_bytes"]
                        + out["temp_bytes"] - alias,
                    )
                elif out["bytes_accessed"]:
                    out["hbm_bytes"] = out["bytes_accessed"]
            except Exception as err:  # noqa: BLE001 — degrade, never raise into stats()
                out["error"] = f"{type(err).__name__}: {err}"
            self._resolved = out
            return out

    @property
    def resolved(self) -> bool:
        return self._resolved is not None


class ProfileRegistry:
    """Bounded process-global registry of :class:`ProgramProfile`\\ s."""

    def __init__(self, capacity: int = _DEFAULT_CAPACITY) -> None:
        if int(capacity) <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self._lock = threading.Lock()
        self._capacity = int(capacity)
        self._records: "OrderedDict[Tuple, ProgramProfile]" = OrderedDict()
        self.registered = 0  # lifetime inserts
        self.evictions = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    def seen(self, key: Tuple) -> bool:
        """The dispatch fast path: has this (label, signature) registered?
        A hit refreshes the entry's recency — the registry is an LRU (the
        bound evicts the LEAST recently dispatched program, and
        :meth:`newest` means most recently dispatched, the semantics the
        detection matcher's bench read relies on)."""
        with self._lock:
            if key in self._records:
                self._records.move_to_end(key)
                return True
            return False

    def register(
        self,
        label: str,
        program: Any,
        args: Tuple[Any, ...],
        *,
        tenant: Optional[str] = None,
        signature: Optional[Tuple] = None,
        x64: bool = False,
    ) -> bool:
        """Register one program dispatch (idempotent per (label, signature));
        returns True when the profile is NEW.  ``args`` may be concrete —
        only their ShapeDtypeStruct snapshot is retained."""
        sig = signature if signature is not None else abstract_signature(args)
        key = (label, sig)
        with self._lock:
            if key in self._records:
                self._records.move_to_end(key)  # re-dispatch refreshes recency
                return False
        abstract = _abstract_args(args)
        prof = ProgramProfile(
            label, tenant if tenant is not None else "<unattributed>",
            sig, program, abstract, x64=x64,
        )
        with self._lock:
            if key in self._records:  # lost a race: first registration wins
                self._records.move_to_end(key)
                return False
            self._records[key] = prof
            self.registered += 1
            while len(self._records) > self._capacity:
                self._records.popitem(last=False)
                self.evictions += 1
        return True

    def profiles(
        self, tenant: Optional[str] = None, label: Optional[str] = None,
        resolve: bool = True,
    ) -> List[Dict[str, Any]]:
        """Registered profiles (optionally filtered), resolved on demand."""
        with self._lock:
            records = list(self._records.values())
        out = []
        for prof in records:
            if tenant is not None and prof.tenant != tenant:
                continue
            if label is not None and prof.label != label:
                continue
            out.append(prof.resolve() if resolve else {
                "label": prof.label, "tenant": prof.tenant, "resolved": prof.resolved,
            })
        return out

    def newest(self, label: str) -> Optional[ProgramProfile]:
        """The most recently DISPATCHED profile under ``label`` (repeat
        registrations refresh recency) — the detection matcher's "cost of
        the program that just ran" read, matching the semantics of the
        ``last_cost_analysis`` plumbing this registry replaced."""
        with self._lock:
            for key in reversed(self._records):
                if key[0] == label:
                    return self._records[key]
        return None

    def summary(self, tenant: str, resolve: bool = False) -> Dict[str, Any]:
        """One tenant's aggregate: registered program count, summed per-step
        flops, and the largest single program's buffer bytes.

        ``resolve=False`` (the ``stats()`` default) aggregates only the
        profiles that already resolved — ``stats()`` is documented
        never-blocking, and resolution is an XLA compile.  ``resolve=True``
        forces resolution of every registered profile first (the bench /
        explicit-reader path).  Resolved numbers update the
        ``tpumetrics_program_flops``/``_hbm_bytes`` gauges for the label."""
        with self._lock:
            records = [p for p in self._records.values() if p.tenant == tenant]
        rows = [p.resolve() for p in records if resolve or p.resolved]
        flops = sum(r["flops"] for r in rows)
        hbm = max((r["hbm_bytes"] for r in rows), default=0.0)
        if rows:
            _FLOPS_GAUGE.set(flops, tenant)
            _HBM_GAUGE.set(hbm, tenant)
        return {
            "registered": len(records),
            "resolved": len(rows),
            "flops_per_step": flops,
            "program_hbm_bytes": hbm,
            "errors": sum(1 for r in rows if "error" in r),
        }

    def release(self, tenant: str) -> None:
        """Drop one tenant's profiles and gauge series (the ``close()``
        contract: auto-minted labels never outlive their stream)."""
        with self._lock:
            for key in [k for k, p in self._records.items() if p.tenant == tenant]:
                del self._records[key]
        _FLOPS_GAUGE.remove(tenant)
        _HBM_GAUGE.remove(tenant)

    def reset(self) -> None:
        with self._lock:
            self._records.clear()
            self.registered = 0
            self.evictions = 0


_REGISTRY = ProfileRegistry()


def registry() -> ProfileRegistry:
    return _REGISTRY


_TENANT_CTX = threading.local()  # .stack: [tenant, ...] innermost last


class _NullScope:
    __slots__ = ()

    def __enter__(self) -> "_NullScope":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False


_NULL_SCOPE = _NullScope()


class _TenantScope:
    """Pushes on ``__enter__`` (not construction) so one scope object can
    guard several dispatches (the megabatch cold-compile + dispatch pair)."""

    __slots__ = ("_tenant",)

    def __init__(self, tenant: str) -> None:
        self._tenant = str(tenant)

    def __enter__(self) -> "_TenantScope":
        st = getattr(_TENANT_CTX, "stack", None)
        if st is None:
            st = _TENANT_CTX.stack = []
        st.append(self._tenant)
        return self

    def __exit__(self, *exc: Any) -> bool:
        _TENANT_CTX.stack.pop()
        return False


def tenant_scope(tenant: str):
    """Name the tenant that owns programs registered on this thread inside
    the ``with`` (the evaluator/service dispatch loops).  A no-op singleton
    when profiling is disabled — the runtime call sites stay one flag test.
    Deliberately independent of the compile-attribution switch: profiles
    must attribute correctly whether or not ``xla`` attribution is armed."""
    if not _ENABLED:
        return _NULL_SCOPE
    return _TenantScope(tenant)


def _current_tenant() -> Optional[str]:
    """The tenant owning this thread's dispatches: the device layer's own
    scope first, then the ambient compile-attribution context (the same
    identity xla.py charges the compile to), when armed."""
    st = getattr(_TENANT_CTX, "stack", None)
    if st:
        return st[-1]
    from tpumetrics.telemetry import xla as _xla

    stack = getattr(_xla._CTX, "stack", None)
    return stack[-1][0] if stack else None


def note_dispatch(label: str, program: Any, args: Tuple[Any, ...]) -> None:
    """The runtime's per-dispatch hook: register (label, signature) once.
    First statement is the module-flag test — disabled, the whole device-
    profile layer is one bool check per dispatch."""
    if not _ENABLED:
        return
    sig = abstract_signature(args)
    if _REGISTRY.seen((label, sig)):
        return
    _REGISTRY.register(
        label, program, args, tenant=_current_tenant(), signature=sig
    )


def register_program(
    label: str, program: Any, args: Tuple[Any, ...], *, x64: bool = False,
    tenant: Optional[str] = None,
) -> None:
    """Ungated registration for programs whose cost IS the product (the
    detection matcher): one dict insert per distinct signature, independent
    of :func:`enable_device_profiles`."""
    _REGISTRY.register(
        label, program, args,
        tenant=tenant if tenant is not None else _current_tenant(),
        x64=x64,
    )


def profiles(
    tenant: Optional[str] = None, label: Optional[str] = None
) -> List[Dict[str, Any]]:
    """Resolved profiles from the process registry (module-level shorthand)."""
    return _REGISTRY.profiles(tenant=tenant, label=label)


def profile_summary(tenant: str, resolve: bool = False) -> Dict[str, Any]:
    """One tenant's aggregate profile (``stats()["device"]["programs"]``
    with ``resolve=False``; pass ``resolve=True`` to force the lazy XLA
    cost/memory analyses first — reader-path cost)."""
    return _REGISTRY.summary(tenant, resolve=resolve)


def release_profiles(tenant: str) -> None:
    """Release one tenant's profiles + gauge series (``close()``)."""
    _REGISTRY.release(tenant)


def reset_device_profiles() -> None:
    """Clear the registry (tests)."""
    _REGISTRY.reset()
