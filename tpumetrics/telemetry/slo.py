"""Declarative SLOs with multi-window burn-rate alerting.

The recording stack (instruments, ledger, health, drift) answers "what is
happening"; this module answers "is it acceptable, and should someone be
paged".  An :class:`SloRule` binds an **objective** — a predicate over one
live signal (a histogram quantile, a gauge, a ledger event count) — to
**fast/slow burn-rate windows** (the SRE multi-window pattern): each
sampler tick classifies the signal as good or bad against the objective,
and the *burn rate* over a window is

    burn = bad_fraction(window) / error_budget

so ``burn == 1`` means "exactly spending the budget", ``burn == 14`` means
"the whole budget gone in 1/14 of the window".  A rule **breaches** when
the fast window burns at ``fast_burn`` or the slow window at ``slow_burn``
(fast catches an outage in minutes, slow catches a simmer that would miss
any single spike threshold).

Breaches **latch with hysteresis**, exactly like
:mod:`tpumetrics.monitoring.drift`: one crossing emits ONE
``slo_violation`` ledger event, bumps
``tpumetrics_slo_violations_total{slo}``, and fans out to every notifier;
the latch re-arms only once the worst normalized burn drops below
``1 - hysteresis``, so a rate jittering around the threshold cannot page
per tick.  ``tpumetrics_slo_burn_rate{slo}`` tracks the worst burn every
tick, breach or not — the series an external alertmanager would page on.

The :class:`SloEngine` samples on a **background daemon thread**
(:meth:`~SloEngine.arm`), entirely host-side: a tick reads instruments
(per-instrument locks), the ledger's aggregate counters, and plain python
callables — never the device (tpulint TPL106 holds the sampler to the same
no-blocking-reads discipline as the admin handlers).  Tests and embedders
may instead drive :meth:`~SloEngine.tick` directly with an explicit clock,
which is how the burn-rate unit tests pin fast-burn/slow-burn/recovery
semantics deterministically.  ``close()`` stops the thread, releases the
engine's minted ``{slo}`` label series, and clears the latches — the same
series-release contract every runtime ``close()`` honors.

Rule builders for the common objectives (:func:`latency_rule`,
:func:`gauge_ceiling_rule`, :func:`event_rule`, :func:`callable_rule`) and
:func:`standard_rules` for a whole evaluator/service are at the bottom;
``docs/observability.md`` has the math walkthrough and a k8s wiring
recipe.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Sequence, Tuple

from tpumetrics.telemetry import instruments as _instruments
from tpumetrics.telemetry import ledger as _ledger

__all__ = [
    "SloEngine",
    "SloRule",
    "callable_rule",
    "event_rule",
    "gauge_ceiling_rule",
    "jsonl_notifier",
    "latency_rule",
    "standard_rules",
]

_BURN_GAUGE = _instruments.gauge(
    _instruments.SLO_BURN_RATE,
    help="worst-window SLO burn rate (1.0 = spending the error budget exactly)",
    labels=("slo",),
)
_VIOLATIONS = _instruments.counter(
    _instruments.SLO_VIOLATIONS,
    help="SLO breach crossings (hysteresis-latched: one per crossing)",
    labels=("slo",),
)


class SloRule:
    """One objective bound to fast/slow burn-rate thresholds.

    Args:
        name: rule label (the ``{slo}`` series label; must be unique per
            engine).
        signal: zero-arg callable returning the current measured value, or
            ``None`` when there is no data yet (no-data ticks are neither
            good nor bad — they leave the windows untouched).
        objective: the bound the signal must honor.
        comparison: ``"le"`` (good while ``signal <= objective``, e.g. a
            p99 ceiling) or ``"ge"`` (good while ``signal >= objective``).
        budget: allowed bad-sample fraction — the error budget the burn
            rate is measured against (default 1e-2: 99% of samples good).
        fast_window_s / fast_burn: the page-fast pair — breach when the
            bad fraction over the last ``fast_window_s`` seconds reaches
            ``fast_burn * budget``.
        slow_window_s / slow_burn: the simmer pair, same shape.
        hysteresis: re-arm margin on the NORMALIZED worst burn (breach at
            1.0, re-arm below ``1 - hysteresis``).
        description: free text carried on the violation event/notification.
    """

    def __init__(
        self,
        name: str,
        signal: Callable[[], Optional[float]],
        objective: float,
        *,
        comparison: str = "le",
        budget: float = 1e-2,
        fast_window_s: float = 60.0,
        fast_burn: float = 14.0,
        slow_window_s: float = 3600.0,
        slow_burn: float = 2.0,
        hysteresis: float = 0.1,
        description: str = "",
    ) -> None:
        if comparison not in ("le", "ge"):
            raise ValueError(f"comparison must be 'le' or 'ge', got {comparison!r}")
        if not 0.0 < budget <= 1.0:
            raise ValueError(f"budget must be in (0, 1], got {budget}")
        if fast_window_s <= 0 or slow_window_s <= 0:
            raise ValueError("burn windows must be positive")
        if fast_window_s > slow_window_s:
            raise ValueError(
                f"fast window ({fast_window_s}s) must not exceed the slow window "
                f"({slow_window_s}s)"
            )
        if fast_burn <= 0 or slow_burn <= 0:
            raise ValueError("burn thresholds must be positive")
        if not 0.0 <= hysteresis < 1.0:
            raise ValueError(f"hysteresis must be in [0, 1), got {hysteresis}")
        self.name = str(name)
        self.signal = signal
        self.objective = float(objective)
        self.comparison = comparison
        self.budget = float(budget)
        self.fast_window_s = float(fast_window_s)
        self.fast_burn = float(fast_burn)
        self.slow_window_s = float(slow_window_s)
        self.slow_burn = float(slow_burn)
        self.hysteresis = float(hysteresis)
        self.description = str(description)
        # sampler-thread-only (or the caller's tick thread): (t, bad) pairs
        # covering the slow window; the fast window is its suffix
        self._samples: Deque[Tuple[float, float]] = deque()

    def is_bad(self, value: float) -> bool:
        if self.comparison == "le":
            return value > self.objective
        return value < self.objective

    # ------------------------------------------------------------- windows

    def _observe(self, now: float, value: Optional[float]) -> None:
        if value is None:
            return
        self._samples.append((now, 1.0 if self.is_bad(float(value)) else 0.0))
        cutoff = now - self.slow_window_s
        while self._samples and self._samples[0][0] < cutoff:
            self._samples.popleft()

    def _burn(self, now: float, window_s: float) -> float:
        cutoff = now - window_s
        n = bad = 0
        for t, b in reversed(self._samples):
            if t < cutoff:
                break
            n += 1
            bad += b
        if n == 0:
            return 0.0
        return (bad / n) / self.budget

    def burn_rates(self, now: float) -> Tuple[float, float]:
        """(fast, slow) burn rates at ``now`` (1.0 = spending the budget)."""
        return self._burn(now, self.fast_window_s), self._burn(now, self.slow_window_s)

    def worst_normalized(self, now: float) -> float:
        """Worst window burn normalized to its threshold (breach at 1.0)."""
        fast, slow = self.burn_rates(now)
        return max(fast / self.fast_burn, slow / self.slow_burn)


def jsonl_notifier(path: str) -> Callable[[Dict[str, Any]], None]:
    """A notifier appending one JSON line per violation to ``path`` —
    the file an on-call pipeline (or the soak supervisor) tails."""

    lock = threading.Lock()

    def notify(payload: Dict[str, Any]) -> None:
        with lock, open(path, "a") as fh:
            fh.write(json.dumps(payload, sort_keys=True, default=repr) + "\n")

    return notify


class SloEngine:
    """Evaluates a ruleset on a background sampler thread.

    Args:
        rules: the :class:`SloRule` set (unique names).
        sample_every_s: sampler cadence while armed.
        notifiers: callables invoked once per breach crossing with the
            violation payload dict; a raising notifier is swallowed (paging
            plumbing must never take down the evaluator) and counted in
            :meth:`status`.
        clock: monotonic-clock override (tests inject a manual clock).
    """

    def __init__(
        self,
        rules: Sequence[SloRule],
        *,
        sample_every_s: float = 1.0,
        notifiers: Sequence[Callable[[Dict[str, Any]], None]] = (),
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        names = [r.name for r in rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO rule names: {names}")
        if sample_every_s <= 0:
            raise ValueError(f"sample_every_s must be positive, got {sample_every_s}")
        self.rules: List[SloRule] = list(rules)
        self.sample_every_s = float(sample_every_s)
        self._notifiers = list(notifiers)
        self._clock = clock
        self._lock = threading.Lock()  # latches + published status
        self._active: Dict[str, bool] = {r.name: False for r in self.rules}
        self._violations: Dict[str, int] = {r.name: 0 for r in self.rules}
        self._last: Dict[str, Dict[str, Any]] = {}
        self._notify_errors = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._closed = False

    # ---------------------------------------------------------------- tick

    def tick(self, now: Optional[float] = None) -> None:
        """One sampling pass over every rule: read the signal, update the
        windows, refresh the burn gauge, latch/re-arm breaches.  The armed
        sampler thread calls this on its cadence; tests call it directly
        with an explicit ``now``."""
        with self._lock:
            if self._closed:
                # a tick after close() must not re-mint the released {slo}
                # series (or re-page a still-bad signal): close is final
                return
        t = self._clock() if now is None else float(now)
        for rule in self.rules:
            try:
                value = rule.signal()
            except Exception as err:  # noqa: BLE001 — a broken signal must
                # not kill the sampler; surface it through status() instead
                value, err_text = None, f"{type(err).__name__}: {err}"
            else:
                err_text = None
            rule._observe(t, value)
            fast, slow = rule.burn_rates(t)
            worst = max(fast / rule.fast_burn, slow / rule.slow_burn)
            breach = fast >= rule.fast_burn or slow >= rule.slow_burn
            with self._lock:
                if not self._closed and _instruments.enabled():
                    _BURN_GAUGE.set(max(fast, slow), rule.name)
                entry = {
                    "value": value,
                    "objective": rule.objective,
                    "comparison": rule.comparison,
                    "burn_fast": fast,
                    "burn_slow": slow,
                    "active": self._active[rule.name],
                    "violations": self._violations[rule.name],
                    "error": err_text,
                }
                if breach and not self._active[rule.name]:
                    # exactly-once per crossing: the latch flips under the
                    # lock, so a racing manual tick cannot double-page
                    self._active[rule.name] = True
                    self._violations[rule.name] += 1
                    entry["active"] = True
                    entry["violations"] = self._violations[rule.name]
                    payload = self._violation_payload(rule, value, fast, slow)
                elif self._active[rule.name] and worst < 1.0 - rule.hysteresis:
                    self._active[rule.name] = False
                    entry["active"] = False
                    payload = None
                else:
                    payload = None
                self._last[rule.name] = entry
            if payload is not None:
                self._page(rule, payload)

    def _violation_payload(
        self, rule: SloRule, value: Optional[float], fast: float, slow: float
    ) -> Dict[str, Any]:
        return {
            "type": "slo_violation",
            "slo": rule.name,
            "description": rule.description,
            "value": value,
            "objective": rule.objective,
            "comparison": rule.comparison,
            "burn_fast": round(fast, 4),
            "burn_slow": round(slow, 4),
            "fast_burn_threshold": rule.fast_burn,
            "slow_burn_threshold": rule.slow_burn,
            "budget": rule.budget,
        }

    def _page(self, rule: SloRule, payload: Dict[str, Any]) -> None:
        if _instruments.enabled():
            _VIOLATIONS.inc(1, rule.name)
        _ledger.record_event(
            None, "slo_violation",
            **{k: v for k, v in payload.items() if k != "type"},
        )
        for notify in self._notifiers:
            try:
                notify(dict(payload))
            except Exception:  # noqa: BLE001 — paging plumbing never fatal
                with self._lock:
                    self._notify_errors += 1

    # -------------------------------------------------------------- status

    def status(self) -> Dict[str, Any]:
        """The engine's live view (the ``/statusz`` ``"slo"`` section):
        per-rule value/burn/latch state plus breach totals."""
        with self._lock:
            return {
                "armed": self._thread is not None and self._thread.is_alive(),
                "sample_every_s": self.sample_every_s,
                "breached": sorted(n for n, a in self._active.items() if a),
                "violations_total": sum(self._violations.values()),
                "notify_errors": self._notify_errors,
                "rules": {name: dict(entry) for name, entry in self._last.items()},
            }

    def breached(self) -> List[str]:
        """Names of the rules whose breach latch is currently active —
        what flips ``/healthz`` to 503."""
        with self._lock:
            return sorted(n for n, a in self._active.items() if a)

    def violations(self, name: Optional[str] = None) -> int:
        with self._lock:
            if name is not None:
                return self._violations.get(name, 0)
            return sum(self._violations.values())

    # ----------------------------------------------------------- lifecycle

    def arm(self) -> "SloEngine":
        """Start the background sampler (idempotent); returns self."""
        with self._lock:
            if self._closed:
                raise RuntimeError("SloEngine is closed")
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="tpumetrics-slo-sampler", daemon=True
            )
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.sample_every_s):
            self.tick()

    def close(self) -> None:
        """Stop the sampler, release the engine's minted ``{slo}`` series,
        and clear the latches.  Idempotent."""
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=max(5.0, 2 * self.sample_every_s))
        with self._lock:
            self._closed = True
            self._thread = None
            for rule in self.rules:
                _BURN_GAUGE.remove(rule.name)
                _VIOLATIONS.remove(rule.name)
                self._active[rule.name] = False

    def __enter__(self) -> "SloEngine":
        return self.arm()

    def __exit__(self, *exc: Any) -> None:
        self.close()


# ------------------------------------------------------------ rule builders


def latency_rule(
    name: str,
    histogram_name: str,
    objective_ms: float,
    *,
    labels: Sequence[str] = (),
    q: float = 0.99,
    **kwargs: Any,
) -> SloRule:
    """Objective: the named latency histogram's q-quantile stays at or
    under ``objective_ms`` (labels empty = cross-label aggregate).  With
    the runtime's sketch-backed histograms the quantile carries the
    sketch's relative-error bound, so the objective compares against a
    number, not a bucket-grid artifact."""
    label_values = tuple(str(v) for v in labels)

    def signal() -> Optional[float]:
        inst = _instruments.get_instrument(histogram_name)
        if not isinstance(inst, _instruments.Histogram):
            return None
        return inst.quantile(q, *label_values)

    return SloRule(
        name, signal, objective_ms,
        description=f"{histogram_name} p{int(q * 100)} <= {objective_ms}ms",
        **kwargs,
    )


def gauge_ceiling_rule(
    name: str,
    gauge_name: str,
    objective: float,
    *,
    labels: Sequence[str] = (),
    **kwargs: Any,
) -> SloRule:
    """Objective: the named gauge stays at or under ``objective`` (queue
    depth saturation, live-state HBM, …)."""
    label_values = tuple(str(v) for v in labels)

    def signal() -> Optional[float]:
        inst = _instruments.get_instrument(gauge_name)
        if not isinstance(inst, _instruments.Gauge):
            return None
        return inst.value(*label_values)

    return SloRule(
        name, signal, objective,
        description=f"{gauge_name} <= {objective}", **kwargs,
    )


def event_rule(name: str, kind: str, **kwargs: Any) -> SloRule:
    """Objective: ZERO new ledger events of ``kind`` (``state_health``,
    ``drift_alert``, ``tenant_quarantined``, …) per sampling interval.  The
    signal is the per-tick DELTA of the ledger's cumulative per-kind
    counter — a one-off burst recovers once the window drains, which is
    what lets the latch re-arm."""
    last: List[Optional[int]] = [None]

    def signal() -> Optional[float]:
        count = int(_ledger.summary()["counts_by_kind"].get(kind, 0))
        prev, last[0] = last[0], count
        if prev is None:
            return 0.0  # the pre-existing history is not this window's fault
        return float(count - prev)

    kwargs.setdefault("budget", 1e-3)
    return SloRule(
        name, signal, 0.0,
        description=f"zero {kind} ledger events", **kwargs,
    )


def callable_rule(
    name: str,
    signal: Callable[[], Optional[float]],
    objective: float,
    **kwargs: Any,
) -> SloRule:
    """Objective over any zero-arg callable (a ``stats()`` field, a custom
    probe) — the escape hatch the declarative builders sit on."""
    return SloRule(name, signal, objective, **kwargs)


def standard_rules(
    target: Any,
    *,
    submit_p99_ms: Optional[float] = None,
    restore_p99_ms: Optional[float] = None,
    queue_depth_max: Optional[float] = None,
    quarantined_max: float = 0.0,
    page_on_state_health: bool = True,
    page_on_drift: bool = True,
    **kwargs: Any,
) -> List[SloRule]:
    """The standing ruleset for one evaluator/service ``target``: latency
    ceilings over the shared sketch histograms, queue-depth saturation and
    quarantine count over ``target.stats()``, and zero
    ``state_health``/``drift_alert`` events.  Pass the ceilings you want;
    ``None`` skips that rule."""
    rules: List[SloRule] = []
    if submit_p99_ms is not None:
        rules.append(latency_rule(
            "submit_p99", _instruments.SUBMIT_LATENCY_MS, submit_p99_ms, **kwargs
        ))
    if restore_p99_ms is not None:
        rules.append(latency_rule(
            "restore_p99", _instruments.RESTORE_LATENCY_MS, restore_p99_ms, **kwargs
        ))
    if queue_depth_max is not None:
        rules.append(callable_rule(
            "queue_depth", lambda: float(target.stats().get("depth", 0)),
            queue_depth_max,
            description=f"dispatch queue depth <= {queue_depth_max}", **kwargs,
        ))
    rules.append(callable_rule(
        "quarantined_tenants",
        lambda: float(target.stats().get("quarantined_tenants", 0)),
        quarantined_max,
        description=f"quarantined tenants <= {quarantined_max}", **kwargs,
    ))
    if page_on_state_health:
        rules.append(event_rule("state_health", "state_health", **kwargs))
    if page_on_drift:
        rules.append(event_rule("drift_alert", "drift_alert", **kwargs))
    return rules
