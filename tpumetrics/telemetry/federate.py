"""Cross-rank federation: merge N processes' telemetry into one live view.

:mod:`tpumetrics.telemetry.timeline` merges per-rank JSONL *after the
fact*; this module does the live equivalent for the aggregate layers.  A
rank serializes its whole instruments registry + ledger counters with
:func:`local_snapshot` (plain JSON — it travels over the soak's stdio
wire, a file, or HTTP), and rank 0 / a supervisor merges any number of
snapshots into a :class:`FederatedView` that renders one ``/metrics``
exposition and one ``/statusz`` summary for the whole pool.

Merge semantics per instrument kind — chosen so the merged family means
the same thing the per-rank family does:

- **counter**: key-wise sum over identical label tuples (counts add).
- **gauge**: key-wise sum (queue depth, live tenants, state HBM — the
  pool total; per-rank values stay distinguishable only when the label
  carries the rank/stream, which the runtime's auto-minted stream labels
  do).
- **histogram**: bucket-wise sum of the cumulative grid, sum/count add,
  max/min fold — and when the series carry **sketch state**
  (:mod:`~tpumetrics.telemetry.instruments` sketch mode), the sparse
  sketches merge by key-wise sum, so a federated ``p99`` carries the SAME
  ≤ 1/capacity relative-error bound as a local one.  This is the
  dogfooded :mod:`tpumetrics.monitoring.sketch` mergeability argument,
  applied to the telemetry plane itself.
- **ledger**: ``counts_by_kind`` and the scalar aggregates sum.

Families that disagree on kind/labels/bucket edges across snapshots are
refused loudly (a federated view silently mixing two different grids
would render meaningless buckets).  Snapshots are versioned; unknown
future fields are ignored.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Iterator, List, Optional, Tuple

from tpumetrics.telemetry import instruments as _instruments
from tpumetrics.telemetry import ledger as _ledger
from tpumetrics.telemetry.export import _fmt_labels, _fmt_value

__all__ = ["FederatedView", "local_snapshot", "merge_snapshots"]

SNAPSHOT_VERSION = 1


def local_snapshot(
    rank: Optional[int] = None,
    include_ledger: bool = True,
    fleet: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """This process's aggregate telemetry as one JSON-able dict: every
    registered instrument (:meth:`~tpumetrics.telemetry.instruments.
    Instrument.to_dict`, sketch state included) plus the global ledger's
    counters.  A pure read — nothing is minted, reset, or synced.
    ``fleet`` (optional) attaches the placement layer's routing census —
    ``{"routing_epoch": int, "tenants": {tid: {"owner_rank", "routing_epoch",
    "migrating"}}, ...}`` — so any rank holding the merged view can answer
    "who owns tenant T"."""
    out = {
        "v": SNAPSHOT_VERSION,
        "rank": rank if rank is not None else os.getpid(),
        "instruments": [inst.to_dict() for inst in _instruments.registry()],
        "ledger": _ledger.summary() if include_ledger else None,
    }
    if fleet is not None:
        out["fleet"] = fleet
    return out


class FederationError(ValueError):
    """Snapshots disagree on a family's shape (kind / labels / edges)."""


def _merge_histogram_series(
    into: Dict[str, Any], series: Dict[str, Any]
) -> None:
    a, b = into, series
    a["overflow"] = a.get("overflow", 0) + b.get("overflow", 0)
    a["sum"] = a.get("sum", 0.0) + b.get("sum", 0.0)
    a["count"] = a.get("count", 0) + b.get("count", 0)
    a["max"] = max(a.get("max", 0.0), b.get("max", 0.0))
    mins = [m for m in (a.get("min"), b.get("min")) if m is not None]
    a["min"] = min(mins) if mins else None
    edges_a = [e for e, _ in a["buckets"]]
    edges_b = [e for e, _ in b["buckets"]]
    if edges_a != edges_b:
        raise FederationError(
            f"histogram bucket edges differ across snapshots: {edges_a} vs {edges_b}"
        )
    a["buckets"] = [
        (e, ca + cb) for (e, ca), (_e, cb) in zip(a["buckets"], b["buckets"])
    ]
    if "sketch" in a or "sketch" in b:
        merged = dict(a.get("sketch") or {})
        for k, c in (b.get("sketch") or {}).items():
            merged[k] = merged.get(k, 0.0) + c
        a["sketch"] = merged


class FederatedView:
    """N merged snapshots, rendered as one exposition / one status dict."""

    def __init__(self, families: Dict[str, Dict[str, Any]],
                 ledger: Dict[str, Any], ranks: List[Any],
                 fleet: Optional[Dict[str, Any]] = None) -> None:
        self._families = families
        self._ledger = ledger
        self.ranks = ranks
        self._fleet = fleet

    # ------------------------------------------------------------ renderers

    def _family_lines(self, fam: Dict[str, Any]) -> Iterator[str]:
        name, kind = fam["name"], fam["type"]
        labelnames = tuple(fam["labels"])
        if fam.get("help"):
            yield f"# HELP {name} {fam['help']}"
        yield f"# TYPE {name} {kind}"
        for lv_key in sorted(fam["series"]):
            data = fam["series"][lv_key]
            lv = tuple(lv_key)
            if kind == "histogram":
                cum = 0
                for edge, c in data["buckets"]:
                    cum += c
                    yield (
                        f"{name}_bucket"
                        f"{_fmt_labels(labelnames, lv, {'le': _fmt_value(edge)})} {cum}"
                    )
                cum += data["overflow"]
                yield f"{name}_bucket{_fmt_labels(labelnames, lv, {'le': '+Inf'})} {cum}"
                yield f"{name}_sum{_fmt_labels(labelnames, lv)} {_fmt_value(data['sum'])}"
                yield f"{name}_count{_fmt_labels(labelnames, lv)} {data['count']}"
            else:
                yield f"{name}{_fmt_labels(labelnames, lv)} {_fmt_value(data)}"

    def prometheus_text(self) -> str:
        """The merged registries in Prometheus text exposition format —
        the same grammar :func:`~tpumetrics.telemetry.export.
        prometheus_text` emits (the round-trip validator parses both), plus
        the merged ledger families."""
        lines: List[str] = []
        for name in sorted(self._families):
            lines.extend(self._family_lines(self._families[name]))
        if self._ledger:
            lines.append("# TYPE tpumetrics_ledger_events_total counter")
            for kind in sorted(self._ledger.get("counts_by_kind", {})):
                lines.append(
                    f"tpumetrics_ledger_events_total{_fmt_labels(('kind',), (kind,))} "
                    f"{self._ledger['counts_by_kind'][kind]}"
                )
            lines.append("# TYPE tpumetrics_ledger_collectives_total counter")
            lines.append(
                f"tpumetrics_ledger_collectives_total {self._ledger.get('collectives_issued', 0)}"
            )
            lines.append("# TYPE tpumetrics_ledger_wire_bytes_total counter")
            lines.append(
                "tpumetrics_ledger_wire_bytes_total "
                f"{_fmt_value(self._ledger.get('wire_bytes_total', 0.0))}"
            )
        return "\n".join(lines) + "\n"

    def quantile(self, name: str, q: float, *labels: str) -> Optional[float]:
        """Federated q-quantile of a merged histogram family: read from the
        merged sketch when the series carry one (the exact-bound path),
        else bucket interpolation over the merged grid."""
        fam = self._families.get(name)
        if fam is None or fam["type"] != "histogram":
            return None
        rows = (
            [fam["series"].get(tuple(labels))]
            if labels
            else list(fam["series"].values())
        )
        rows = [r for r in rows if r]
        if not rows:
            return None
        agg: Dict[str, Any] = {
            "buckets": [(e, 0) for e, _ in rows[0]["buckets"]],
            "overflow": 0, "sum": 0.0, "count": 0, "max": 0.0, "min": None,
        }
        for row in rows:
            _merge_histogram_series(agg, row)
        if agg["count"] == 0:
            return None
        sketch = agg.get("sketch")
        if sketch:
            params = fam.get("sketch_params") or {}
            return _instruments.sketch_quantile(
                {int(k): v for k, v in sketch.items()}, q,
                minimum=agg["min"] if agg["min"] is not None else 0.0,
                maximum=agg["max"],
                levels=int(params.get("levels", _instruments.SKETCH_LEVELS)),
                capacity=int(params.get("capacity", _instruments.SKETCH_CAPACITY)),
            )
        # fixed-grid fallback: linear interpolation like Histogram._quantile_of
        rank = q * agg["count"]
        cum = 0.0
        prev_edge = 0.0
        for edge, c in agg["buckets"]:
            prev = cum
            cum += c
            if cum >= rank and c > 0:
                frac = (rank - prev) / c
                return min(prev_edge + (edge - prev_edge) * frac, agg["max"])
            prev_edge = edge
        return agg["max"]

    def statusz(self) -> Dict[str, Any]:
        """The merged ``/statusz`` section: pool membership, headline
        latency quantiles from the merged sketches, and the summed ledger
        counters."""
        out: Dict[str, Any] = {
            "ranks": list(self.ranks),
            "world": len(self.ranks),
            "ledger": dict(self._ledger) if self._ledger else {},
            "latency": {},
            "families": sorted(self._families),
        }
        for key, name in (
            ("submit_ms", _instruments.SUBMIT_LATENCY_MS),
            ("dispatch_ms", _instruments.DISPATCH_LATENCY_MS),
            ("restore_ms", _instruments.RESTORE_LATENCY_MS),
        ):
            out["latency"][key] = {
                "p50": self.quantile(name, 0.50),
                "p99": self.quantile(name, 0.99),
            }
        if self._fleet is not None:
            out["fleet"] = self._fleet
        return out


def merge_snapshots(snapshots: List[Dict[str, Any]]) -> FederatedView:
    """Fold N :func:`local_snapshot` payloads into one
    :class:`FederatedView` (module docstring has the per-kind semantics).
    Order-independent: counter/bucket/sketch sums and min/max folds are the
    associative merges the sketch state kind was designed around."""
    families: Dict[str, Dict[str, Any]] = {}
    ledger_merged: Dict[str, Any] = {}
    ranks: List[Any] = []
    fleet_merged: Optional[Dict[str, Any]] = None
    for snap in snapshots:
        ranks.append(snap.get("rank"))
        fleet = snap.get("fleet")
        if fleet is not None:
            if fleet_merged is None:
                fleet_merged = {
                    k: (dict(v) if isinstance(v, dict) else v)
                    for k, v in fleet.items()
                }
            else:
                # epochs are totally ordered: the freshest census wins per
                # tenant (a stale rank's routing row must not mask a newer
                # placement), scalar fields follow the max epoch
                a, b = fleet_merged, fleet
                newest = b if b.get("routing_epoch", 0) >= a.get("routing_epoch", 0) else a
                tenants = dict(a.get("tenants", {}))
                for tid, row in b.get("tenants", {}).items():
                    have = tenants.get(tid)
                    if have is None or row.get("routing_epoch", 0) >= have.get(
                        "routing_epoch", 0
                    ):
                        tenants[tid] = dict(row)
                fleet_merged = {
                    k: (v if k != "tenants" else tenants)
                    for k, v in newest.items()
                }
                fleet_merged["tenants"] = tenants
        for fam in snap.get("instruments", []):
            name = fam["name"]
            got = families.get(name)
            if got is None:
                got = families[name] = {
                    "name": name,
                    "type": fam["type"],
                    "help": fam.get("help", ""),
                    "labels": list(fam.get("labels", [])),
                    "series": {},
                }
                if fam.get("sketch_params"):
                    got["sketch_params"] = dict(fam["sketch_params"])
            if got["type"] != fam["type"] or got["labels"] != list(fam.get("labels", [])):
                raise FederationError(
                    f"family {name!r} disagrees across snapshots: "
                    f"{got['type']}/{got['labels']} vs "
                    f"{fam['type']}/{fam.get('labels')}"
                )
            for series in fam.get("series", []):
                lv = tuple(series["label_values"])
                value = series["value"]
                if fam["type"] == "histogram":
                    # normalize the JSON round-trip's list-pairs to tuples
                    value = dict(value)
                    value["buckets"] = [tuple(p) for p in value["buckets"]]
                    if lv not in got["series"]:
                        base = dict(value)
                        base["buckets"] = [(e, 0) for e, _ in value["buckets"]]
                        base.update(overflow=0, sum=0.0, count=0, max=0.0, min=None)
                        if "sketch" in value:
                            base["sketch"] = {}
                        got["series"][lv] = base
                    _merge_histogram_series(got["series"][lv], value)
                else:
                    got["series"][lv] = got["series"].get(lv, 0.0) + float(value)
        led = snap.get("ledger")
        if led:
            for key, val in led.items():
                if key == "counts_by_kind":
                    bucket = ledger_merged.setdefault("counts_by_kind", {})
                    for kind, n in val.items():
                        bucket[kind] = bucket.get(kind, 0) + n
                elif key == "bytes_by_op":
                    bucket = ledger_merged.setdefault("bytes_by_op", {})
                    for op, n in val.items():
                        bucket[op] = bucket.get(op, 0.0) + n
                elif isinstance(val, (int, float)):
                    ledger_merged[key] = ledger_merged.get(key, 0) + val
    return FederatedView(families, ledger_merged, ranks, fleet=fleet_merged)
