"""XLA compile attribution — who paid for every compile, and retraces.

``jax.monitoring`` reports each backend compile (and persistent-cache
retrieval) as anonymous process-global events; this module — grown from the
listener machinery ``tpumetrics/runtime/compile_cache.py`` introduced for
cache-hit accounting, which now lives here — turns them into *attributed*
records: every XLA compile is charged to the ``(tenant, step token,
trace signature)`` that triggered it.

How attribution works: the runtime knows exactly when it is about to
dispatch a **cold** trace signature (the evaluator/service pre-compile path,
``SignatureRegistry.observe`` returning True); it pushes an attribution
context for the dispatch, and the duration listener charges any compile
event that fires on that thread to the context.  Compiles with no context
(a user's own jit, a warm-up ``jnp`` op) are attributed to
``"<unattributed>"`` — visible, never silently dropped.
:class:`~tpumetrics.parallel.fuse_update.FusedCollectionStep` additionally
installs a *fallback* context naming the step and program key, so the OO
fused path (no evaluator involved) still attributes its compiles.

**Retrace detection**: a ``(token, signature)`` pair that compiles a second
time in one process is a retrace — the jit executable cache should have
served it, so something invalidated it (a new program object per call, a
donation-mode flip, an unhashable-kwarg fallback rebuilding steps).  Each
retrace warns once per key, emits an ``xla_retrace`` ledger event, and
bumps the ``tpumetrics_recompiles_total{tenant}`` counter that
``stats()["recompiles"]`` reads.  Note the persistent compile cache
(``compile_cache.py``) makes a *cold process's* compile cheap but still
fires the compile event — a cache-served compile is attributed like any
other (its near-zero ``seconds`` tells them apart).

Everything here is host-side and off by default:
:func:`enable_compile_attribution` registers the (single, module-lifetime)
listener pair and arms the context checks; disabled, an attribution context
manager is the shared no-op singleton.
"""

from __future__ import annotations

import itertools
import threading
from collections import deque
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import jax

from tpumetrics.telemetry import instruments as _instruments
from tpumetrics.telemetry import ledger as _ledger

__all__ = [
    "attribute_compiles",
    "attribution_enabled",
    "compile_records",
    "count_cache_hits",
    "disable_compile_attribution",
    "enable_compile_attribution",
    "fallback_attribution",
    "recompile_count",
    "release_attribution",
    "reset_compile_attribution",
]

# jax wraps compile-OR-cache-load in this one duration event; the hit path
# additionally reports its retrieval time separately, so true compile
# seconds = backend_compile - cache_retrieval
_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_CACHE_RETRIEVAL_EVENT = "/jax/compilation_cache/cache_retrieval_time_sec"

# jax.monitoring has no unregister API, so exactly ONE listener pair is ever
# registered (lazily, at the first count_cache_hits/attribution use); the
# hit-counting context manager pushes its counter dict here and pops it on
# exit, so repeated/nested use adds nothing to jax's global listener list
_active_counters: List[Dict[str, Any]] = []
_listeners_registered = False
_REG_LOCK = threading.Lock()

_ATTRIB_ENABLED = False
_CTX = threading.local()  # .stack: [(tenant, token, signature, activation), ...]
_LOCK = threading.Lock()
#: (token, signature) -> id of the ACTIVATION whose dispatch first compiled
#: it.  One activation (one `with attribute_compiles(...)` entry) may fire
#: several backend-compile events — the jitted program plus the small eager
#: helper ops (state copies, casts) XLA also compiles the first time a shape
#: appears — and none of those are retraces; a compile event for a known key
#: in a LATER activation is (the jit executable cache should have served it).
_seen_keys: Dict[Tuple[Any, Any], int] = {}
_warned_keys: set = set()
_records: deque = deque(maxlen=4096)
_ACTIVATIONS = itertools.count(1)

# ONE registration site for the attribution instruments (the name/help/
# labels/buckets tuple is a registry contract — duplicating it at call
# sites invites silent drift or a runtime mismatch error)
_COMPILE_HIST = _instruments.histogram(
    _instruments.XLA_COMPILE_SECONDS,
    help="attributed XLA backend-compile seconds",
    labels=("tenant",),
    buckets=_instruments.DEFAULT_S_BUCKETS,
)
_RECOMPILES = _instruments.counter(
    _instruments.RECOMPILES_TOTAL,
    help="compiles of a previously-seen trace signature",
    labels=("tenant",),
)


def _ensure_listeners() -> None:
    global _listeners_registered
    with _REG_LOCK:
        if _listeners_registered:
            return
        jax.monitoring.register_event_listener(_event_listener)
        jax.monitoring.register_event_duration_secs_listener(_duration_listener)
        _listeners_registered = True


def _event_listener(event: str, **_kwargs: Any) -> None:
    for counter in _active_counters:
        if event == "/jax/compilation_cache/cache_hits":
            counter["hits"] += 1
        elif event == "/jax/compilation_cache/cache_misses":
            counter["misses"] += 1


def _duration_listener(event: str, duration: float, **_kwargs: Any) -> None:
    for counter in _active_counters:
        if event == _BACKEND_COMPILE_EVENT:
            counter["backend_compile_secs"] += float(duration)
        elif event == _CACHE_RETRIEVAL_EVENT:
            counter["cache_retrieval_secs"] += float(duration)
    if _ATTRIB_ENABLED and event == _BACKEND_COMPILE_EVENT:
        _attribute(float(duration))


# ------------------------------------------------------------- attribution


def attribution_enabled() -> bool:
    return _ATTRIB_ENABLED


def enable_compile_attribution() -> None:
    """Arm compile attribution (registers the listener pair on first use)."""
    global _ATTRIB_ENABLED
    _ensure_listeners()
    _ATTRIB_ENABLED = True


def disable_compile_attribution() -> None:
    global _ATTRIB_ENABLED
    _ATTRIB_ENABLED = False


def reset_compile_attribution() -> None:
    """Clear the attribution records and the seen/warned signature sets."""
    with _LOCK:
        _seen_keys.clear()
        _warned_keys.clear()
        _records.clear()


def _ctx_stack() -> List[Tuple[str, Any, Any]]:
    st = getattr(_CTX, "stack", None)
    if st is None:
        st = _CTX.stack = []
    return st


class _NullCtx:
    __slots__ = ()

    def __enter__(self) -> "_NullCtx":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False


_NULL = _NullCtx()


class _AttribCtx:
    """Pushes on ``__enter__`` (not construction) so one context object can
    guard several dispatches of the same attributed program; each entry is
    a fresh *activation* (the retrace detector's unit of innocence)."""

    __slots__ = ("_entry",)

    def __init__(self, entry: Tuple[str, Any, Any]) -> None:
        self._entry = entry

    def __enter__(self) -> "_AttribCtx":
        _ctx_stack().append(self._entry + (next(_ACTIVATIONS),))
        return self

    def __exit__(self, *exc: Any) -> bool:
        _ctx_stack().pop()
        return False


def attribute_compiles(tenant: str, signature: Any, token: Any = None):
    """Context manager charging any XLA compile fired on this thread inside
    the ``with`` to ``(tenant, token, signature)``.  ``signature`` must be
    hashable (the runtime's trace signatures are); ``token`` namespaces it
    (the service's step token, the evaluator's stream label).  No-op
    singleton when attribution is disabled."""
    if not _ATTRIB_ENABLED:
        return _NULL
    return _AttribCtx((str(tenant), token, signature))


def fallback_attribution(signature: Any, label: str = "") -> Any:
    """Like :func:`attribute_compiles` but only engages when NO context is
    already active — :class:`FusedCollectionStep` wraps its program
    dispatches with this so OO-path compiles are attributed to the step
    without overriding the runtime's richer (tenant, signature) context."""
    if not _ATTRIB_ENABLED:
        return _NULL
    if _ctx_stack():
        return _NULL
    return _AttribCtx((label or "<step>", None, signature))


def _attribute(seconds: float) -> None:
    stack = getattr(_CTX, "stack", None)
    tenant, token, sig, activation = (
        stack[-1] if stack else ("<unattributed>", None, None, 0)
    )
    key = (token, sig)
    with _LOCK:
        first_act = _seen_keys.get(key) if sig is not None else None
        retrace = first_act is not None and first_act != activation
        if sig is not None and first_act is None:
            _seen_keys[key] = activation
        warn = retrace and key not in _warned_keys
        if warn:
            _warned_keys.add(key)
        _records.append(
            {
                "tenant": tenant,
                "token": repr(token) if token is not None else None,
                "signature": repr(sig) if sig is not None else None,
                "seconds": seconds,
                "retrace": retrace,
            }
        )
    _COMPILE_HIST.observe(seconds, tenant)
    _ledger.record_event(
        None, "xla_compile", tenant=tenant, seconds=round(seconds, 6), retrace=retrace
    )
    if retrace:
        _RECOMPILES.inc(1, tenant)
        _ledger.record_event(None, "xla_retrace", tenant=tenant, seconds=round(seconds, 6))
        if warn:
            from tpumetrics.utils.prints import rank_zero_warn

            rank_zero_warn(
                f"XLA recompiled a previously-seen trace signature for tenant "
                f"{tenant!r} (signature {sig!r}): the jit executable cache should "
                "have served it. Common causes: a fused step rebuilt per call, a "
                "donation-mode flip, or per-batch-varying static kwargs."
            )


def release_attribution(tenant: str, tokens: Sequence[Any] = ()) -> None:
    """Drop one stream/tenant's attribution state: its label series from
    the XLA instruments and the retrace-detector keys under its ``tokens``
    (a closed stream's auto-minted labels must not live in the process
    registry forever — the ``close()`` contract)."""
    tenant = str(tenant)
    _COMPILE_HIST.remove(tenant)
    _RECOMPILES.remove(tenant)
    if tokens:
        token_set = set(tokens)
        with _LOCK:
            for key in [k for k in _seen_keys if k[0] in token_set]:
                del _seen_keys[key]
            _warned_keys.difference_update(
                k for k in list(_warned_keys) if k[0] in token_set
            )


def compile_records() -> List[Dict[str, Any]]:
    """Snapshot of the attributed-compile ring (oldest first): one dict per
    backend compile with tenant/token/signature/seconds/retrace."""
    with _LOCK:
        return [dict(r) for r in _records]


def recompile_count(tenant: Optional[str] = None) -> int:
    """Retrace count (for one tenant label, or total)."""
    if tenant is None:
        return int(_RECOMPILES.value())
    return int(_RECOMPILES.value(str(tenant)))


# ------------------------------------------------------- cache-hit counting


from contextlib import contextmanager  # noqa: E402  (single consumer below)


@contextmanager
def count_cache_hits() -> Iterator[Dict[str, Any]]:
    """Count persistent-cache hits/misses and accumulate backend compile
    seconds inside the ``with`` block via JAX's monitoring events — the
    observable proof that a restarted or elastically resized process REUSED
    executables instead of recompiling::

        with count_cache_hits() as hits:
            evaluator.restore_elastic()
            ... resume streaming ...
        assert hits["hits"] > 0 and hits["misses"] == 0

    ``hits["backend_compile_secs"]`` sums jax's backend-compile duration
    event.  That event times compile-OR-cache-load, so a cache hit still
    contributes its (much cheaper) executable deserialization;
    ``hits["cache_retrieval_secs"]`` sums exactly that part, making
    ``backend_compile_secs - cache_retrieval_secs`` the true XLA compile
    seconds paid — near zero for a fully warm process, while tracing and
    dispatch time (which no cache can remove) still show up in wall time.

    Safe to use repeatedly (or nested) in a long-lived process: one module
    listener pair is registered once and dispatches to the counters of the
    currently active ``with`` blocks only.
    """
    counter: Dict[str, Any] = {
        "hits": 0,
        "misses": 0,
        "backend_compile_secs": 0.0,
        "cache_retrieval_secs": 0.0,
    }
    _ensure_listeners()
    _active_counters.append(counter)
    try:
        yield counter
    finally:
        _active_counters.remove(counter)
