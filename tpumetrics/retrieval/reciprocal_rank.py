"""RetrievalMRR (counterpart of reference ``retrieval/reciprocal_rank.py``)."""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax

from tpumetrics.functional.retrieval._grouped import SortedQueries, grouped_reciprocal_rank
from tpumetrics.retrieval.base import RetrievalMetric

Array = jax.Array


class RetrievalMRR(RetrievalMetric):
    """Mean Reciprocal Rank over queries.

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.retrieval import RetrievalMRR
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.asarray([False, False, True, False, True, False, True])
        >>> mrr = RetrievalMRR()
        >>> float(mrr(preds, target, indexes=indexes))
        0.75
    """

    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(self, top_k: Optional[int] = None, empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        num_queries: Optional[int] = None,
        **kwargs: Any) -> None:
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index,
                         num_queries=num_queries, **kwargs)
        if top_k is not None and not (isinstance(top_k, int) and top_k > 0):
            raise ValueError("`top_k` has to be a positive integer or None")
        self.top_k = top_k

    def _grouped_metric(self, sq: SortedQueries) -> Tuple[Array, Array]:
        return grouped_reciprocal_rank(sq, self.top_k)
