"""RetrievalNormalizedDCG (counterpart of reference ``retrieval/ndcg.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from tpumetrics.functional.retrieval._grouped import grouped_ndcg, reduce_queries, sort_queries
from tpumetrics.retrieval.base import RetrievalMetric

Array = jax.Array


class RetrievalNormalizedDCG(RetrievalMetric):
    """Mean (tie-averaged) nDCG@k over queries; targets may be graded.

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.retrieval import RetrievalNormalizedDCG
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.asarray([False, False, True, False, True, False, True])
        >>> ndcg = RetrievalNormalizedDCG()
        >>> round(float(ndcg(preds, target, indexes=indexes)), 4)
        0.8467
    """

    allow_non_binary_target: bool = True
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(self, top_k: Optional[int] = None, empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        num_queries: Optional[int] = None,
        **kwargs: Any) -> None:
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index,
                         num_queries=num_queries, **kwargs)
        if top_k is not None and not (isinstance(top_k, int) and top_k > 0):
            raise ValueError("`top_k` has to be a positive integer or None")
        self.top_k = top_k

    def _grouped_metric(self, sq):  # pragma: no cover - unused, compute overridden
        raise NotImplementedError

    def compute(self) -> Array:
        """nDCG needs a second (ideal) ranking by target; both rankings are
        one lexsort each, then the tie-averaged gains reduce per query."""
        idx, preds, target, mask, num_queries = self._flat_state()
        if idx.shape[0] == 0:
            return jnp.zeros((), jnp.float32)
        sq_pred = sort_queries(idx, preds, target, num_queries, mask)
        sq_tgt = sort_queries(idx, target, target, num_queries, mask)
        values, computable = grouped_ndcg(sq_pred, sq_tgt, self.top_k)
        return reduce_queries(
            values, computable, sq_pred.counts > 0, self.empty_target_action, self._empty_requirement
        )
