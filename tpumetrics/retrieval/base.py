"""RetrievalMetric base (counterpart of reference ``retrieval/base.py:25``).

The reference's ``compute`` sorts on host, splits per query with a
``.cpu().tolist()`` sync (reference retrieval/base.py:125-130), and loops in
Python. Here compute is one :func:`~tpumetrics.functional.retrieval._grouped.sort_queries`
lexsort + segment reductions over **all** queries at once — no host sync, no
dynamic shapes — so with ``num_queries`` declared the whole metric (update,
cross-device sync of the fixed-capacity document buffers, and compute) runs
inside a jitted/shard_map-ed step.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp

from tpumetrics.buffers import _BufferList
from tpumetrics.functional.retrieval._grouped import SortedQueries, reduce_queries, sort_queries
from tpumetrics.metric import Metric
from tpumetrics.utils.checks import _check_retrieval_inputs
from tpumetrics.utils.data import _is_tracer, dim_zero_cat

Array = jax.Array


class RetrievalMetric(Metric, ABC):
    """Base for query-grouped retrieval metrics fed (preds, target, indexes).

    Args:
        empty_target_action: policy for queries without the required target
            (``neg``: count 0.0; ``pos``: count 1.0; ``skip``: exclude;
            ``error``: raise — eager only).
        ignore_index: target value whose rows are dropped (as a validity
            mask, so it stays jit-safe).
        num_queries: static number of queries (TPU extension). Required for
            in-jit compute; inferred from observed indexes eagerly otherwise.
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False

    indexes: List[Array]
    preds: List[Array]
    target: List[Array]

    allow_non_binary_target: bool = False
    _empty_requirement: str = "positive"

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        num_queries: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)

        empty_target_action_options = ("error", "skip", "neg", "pos")
        if empty_target_action not in empty_target_action_options:
            raise ValueError(f"Argument `empty_target_action` received a wrong value `{empty_target_action}`.")
        self.empty_target_action = empty_target_action

        if ignore_index is not None and not isinstance(ignore_index, int):
            raise ValueError("Argument `ignore_index` must be an integer or None.")
        self.ignore_index = ignore_index
        self.num_queries = num_queries

        self.add_state("indexes", default=[], dist_reduce_fx=None, feature_dtype=jnp.int32)
        self.add_state("preds", default=[], dist_reduce_fx=None)
        self.add_state("target", default=[], dist_reduce_fx=None)

    def update(self, preds: Array, target: Array, indexes: Array) -> None:
        """Validate, flatten, and append; ``ignore_index`` rows are masked
        out rather than dropped (static shapes)."""
        if indexes is None:
            raise ValueError("Argument `indexes` cannot be None")
        indexes, preds, target, keep = _check_retrieval_inputs(
            indexes, preds, target, allow_non_binary_target=self.allow_non_binary_target,
            ignore_index=self.ignore_index,
        )
        self._append_state("indexes", indexes, valid=keep)
        self._append_state("preds", preds, valid=keep)
        self._append_state("target", target, valid=keep)

    def _flat_state(self) -> Tuple[Array, Array, Array, Optional[Array], int]:
        """(indexes, preds, target, valid_mask, num_queries) from the state."""
        if isinstance(self.indexes, _BufferList):
            idx = self.indexes.buffer.values
            preds = self.preds.buffer.values
            target = self.target.buffer.values
            mask = self.indexes.buffer.valid_mask()
        else:
            idx = dim_zero_cat(self.indexes) if self.indexes else jnp.zeros((0,), jnp.int32)
            preds = dim_zero_cat(self.preds) if self.preds else jnp.zeros((0,), jnp.float32)
            target = dim_zero_cat(self.target) if self.target else jnp.zeros((0,), jnp.float32)
            mask = None

        num_queries = self.num_queries
        if num_queries is None:
            if _is_tracer(idx):
                raise ValueError(
                    "Retrieval metrics need a static `num_queries` to compute under jit;"
                    " pass num_queries= at construction or compute eagerly."
                )
            valid_idx = idx if mask is None else idx[jnp.asarray(mask)]
            num_queries = int(valid_idx.max()) + 1 if valid_idx.size else 1
        return idx, preds, target, mask, num_queries

    def compute(self) -> Array:
        """Rank every query and reduce per-query scores with the
        empty-target policy (reference retrieval/base.py:116-147)."""
        idx, preds, target, mask, num_queries = self._flat_state()
        if idx.shape[0] == 0:
            return jnp.zeros((), jnp.float32)
        sq = sort_queries(idx, preds, target, num_queries, mask)
        values, computable = self._grouped_metric(sq)
        return reduce_queries(
            values, computable, sq.counts > 0, self.empty_target_action, self._empty_requirement
        )

    @abstractmethod
    def _grouped_metric(self, sq: SortedQueries) -> Tuple[Array, Array]:
        """Per-query (values, computable) for all queries at once."""
