"""RetrievalRPrecision (counterpart of reference ``retrieval/r_precision.py``)."""

from __future__ import annotations

from typing import Tuple

import jax

from tpumetrics.functional.retrieval._grouped import SortedQueries, grouped_r_precision
from tpumetrics.retrieval.base import RetrievalMetric

Array = jax.Array


class RetrievalRPrecision(RetrievalMetric):
    """Mean R-precision over queries.

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.retrieval import RetrievalRPrecision
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.asarray([False, False, True, False, True, False, True])
        >>> p2 = RetrievalRPrecision()
        >>> round(float(p2(preds, target, indexes=indexes)), 4)
        0.75
    """

    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def _grouped_metric(self, sq: SortedQueries) -> Tuple[Array, Array]:
        return grouped_r_precision(sq)
