"""RetrievalMAP (counterpart of reference ``retrieval/average_precision.py``)."""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax

from tpumetrics.functional.retrieval._grouped import SortedQueries, grouped_average_precision
from tpumetrics.retrieval.base import RetrievalMetric

Array = jax.Array


class RetrievalMAP(RetrievalMetric):
    """Mean Average Precision over queries.

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.retrieval import RetrievalMAP
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.asarray([False, False, True, False, True, False, True])
        >>> rmap = RetrievalMAP()
        >>> round(float(rmap(preds, target, indexes=indexes)), 4)
        0.7917
    """

    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(self, top_k: Optional[int] = None, empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        num_queries: Optional[int] = None,
        **kwargs: Any) -> None:
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index,
                         num_queries=num_queries, **kwargs)
        if top_k is not None and not (isinstance(top_k, int) and top_k > 0):
            raise ValueError("`top_k` has to be a positive integer or None")
        self.top_k = top_k

    def _grouped_metric(self, sq: SortedQueries) -> Tuple[Array, Array]:
        return grouped_average_precision(sq, self.top_k)
