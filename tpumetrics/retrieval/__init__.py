"""Retrieval metric domain (counterpart of reference ``retrieval/__init__.py``)."""

from tpumetrics.retrieval.average_precision import RetrievalMAP
from tpumetrics.retrieval.base import RetrievalMetric
from tpumetrics.retrieval.fall_out import RetrievalFallOut
from tpumetrics.retrieval.hit_rate import RetrievalHitRate
from tpumetrics.retrieval.ndcg import RetrievalNormalizedDCG
from tpumetrics.retrieval.precision import RetrievalPrecision
from tpumetrics.retrieval.precision_recall_curve import (
    RetrievalPrecisionRecallCurve,
    RetrievalRecallAtFixedPrecision,
)
from tpumetrics.retrieval.r_precision import RetrievalRPrecision
from tpumetrics.retrieval.recall import RetrievalRecall
from tpumetrics.retrieval.reciprocal_rank import RetrievalMRR

__all__ = [
    "RetrievalFallOut",
    "RetrievalHitRate",
    "RetrievalMAP",
    "RetrievalMRR",
    "RetrievalMetric",
    "RetrievalNormalizedDCG",
    "RetrievalPrecision",
    "RetrievalPrecisionRecallCurve",
    "RetrievalRecall",
    "RetrievalRecallAtFixedPrecision",
    "RetrievalRPrecision",
]
