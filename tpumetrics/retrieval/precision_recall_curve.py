"""RetrievalPrecisionRecallCurve + RetrievalRecallAtFixedPrecision
(counterpart of reference ``retrieval/precision_recall_curve.py``)."""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from tpumetrics.functional.retrieval._grouped import grouped_precision_recall_curve, sort_queries
from tpumetrics.functional.retrieval.precision_recall_curve import _retrieval_recall_at_fixed_precision
from tpumetrics.classification.precision_recall_curve import _AtFixedValuePlotMixin
from tpumetrics.retrieval.base import RetrievalMetric
from tpumetrics.utils.data import _is_tracer

Array = jax.Array


class RetrievalPrecisionRecallCurve(RetrievalMetric):
    """Average precision/recall at every k in ``1..max_k`` over queries
    (reference precision_recall_curve.py:61-219).

    The reference loops queries and stacks per-query curves; here the whole
    (num_queries, max_k) grid is built with one scatter + cumsum, and the
    empty-target policy is a row mask.

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.retrieval import RetrievalPrecisionRecallCurve
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.asarray([True, False, True, False, True, False, True])
        >>> curve = RetrievalPrecisionRecallCurve(max_k=2)
        >>> precisions, recalls, top_k = curve(preds, target, indexes=indexes)
        >>> precisions.tolist()
        [0.5, 0.5]
        >>> recalls.tolist()
        [0.25, 0.5]
        >>> top_k.tolist()
        [1, 2]
    """

    higher_is_better: bool = True

    def __init__(
        self,
        max_k: Optional[int] = None,
        adaptive_k: bool = False,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        num_queries: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index,
                         num_queries=num_queries, **kwargs)
        if max_k is not None and not (isinstance(max_k, int) and max_k > 0):
            raise ValueError("`max_k` has to be a positive integer or None")
        if not isinstance(adaptive_k, bool):
            raise ValueError("`adaptive_k` has to be a boolean")
        self.max_k = max_k
        self.adaptive_k = adaptive_k

    def compute(self) -> Tuple[Array, Array, Array]:
        idx, preds, target, mask, num_queries = self._flat_state()
        sq = sort_queries(idx, preds, target, num_queries, mask)
        max_k = self.max_k
        if max_k is None:
            if _is_tracer(idx):
                raise ValueError("Pass a static `max_k` to compute the retrieval PR curve under jit.")
            max_k = max(int(sq.counts.max()), 1)
        precision_qk, recall_qk, computable = grouped_precision_recall_curve(sq, max_k, self.adaptive_k)
        observed = sq.counts > 0

        if self.empty_target_action == "error":
            bad = observed & ~computable
            if _is_tracer(bad):
                raise NotImplementedError(
                    "empty_target_action='error' cannot run under jit; use 'skip'/'neg'/'pos'."
                )
            if bool(jnp.any(bad)):
                raise ValueError("`compute` method was provided with a query with no positive target.")

        if self.empty_target_action == "skip":
            used = observed & computable
            fill_p = fill_r = jnp.zeros_like(precision_qk)
        elif self.empty_target_action == "pos":
            used = observed
            fill_p = fill_r = jnp.ones_like(precision_qk)
        else:
            used = observed
            fill_p = fill_r = jnp.zeros_like(precision_qk)

        precision_qk = jnp.where(computable[:, None], precision_qk, fill_p)
        recall_qk = jnp.where(computable[:, None], recall_qk, fill_r)
        denom = jnp.maximum(jnp.sum(used), 1)
        any_used = jnp.sum(used) > 0
        precision = jnp.where(any_used, jnp.sum(jnp.where(used[:, None], precision_qk, 0.0), axis=0) / denom, 0.0)
        recall = jnp.where(any_used, jnp.sum(jnp.where(used[:, None], recall_qk, 0.0), axis=0) / denom, 0.0)
        top_k = jnp.arange(1, max_k + 1, dtype=jnp.int32)
        return precision, recall, top_k

    def _grouped_metric(self, sq):  # pragma: no cover - unused, compute overridden
        raise NotImplementedError


class RetrievalRecallAtFixedPrecision(_AtFixedValuePlotMixin, RetrievalPrecisionRecallCurve):
    """Highest recall whose averaged precision@k clears ``min_precision``,
    plus the k achieving it (reference precision_recall_curve.py:222-312).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.retrieval import RetrievalRecallAtFixedPrecision
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.asarray([True, False, True, False, True, False, True])
        >>> metric = RetrievalRecallAtFixedPrecision(min_precision=0.5)
        >>> max_recall, best_k = metric(preds, target, indexes=indexes)
        >>> (round(float(max_recall), 4), int(best_k))
        (1.0, 4)
    """

    def __init__(
        self,
        min_precision: float = 0.0,
        max_k: Optional[int] = None,
        adaptive_k: bool = False,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        num_queries: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(max_k=max_k, adaptive_k=adaptive_k, empty_target_action=empty_target_action,
                         ignore_index=ignore_index, num_queries=num_queries, **kwargs)
        if not (isinstance(min_precision, float) and 0.0 <= min_precision <= 1.0):
            raise ValueError("`min_precision` has to be a positive float between 0 and 1")
        self.min_precision = min_precision

    def compute(self) -> Tuple[Array, Array]:  # type: ignore[override]
        precisions, recalls, top_k = super().compute()
        return _retrieval_recall_at_fixed_precision(precisions, recalls, top_k, self.min_precision)
