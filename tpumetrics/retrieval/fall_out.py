"""RetrievalFallOut (counterpart of reference ``retrieval/fall_out.py``)."""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax

from tpumetrics.functional.retrieval._grouped import SortedQueries, grouped_fall_out
from tpumetrics.retrieval.base import RetrievalMetric

Array = jax.Array


class RetrievalFallOut(RetrievalMetric):
    """Mean fall-out@k over queries; the empty-target policy keys on queries
    with no *negative* target (reference fall_out.py compute override).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.retrieval import RetrievalFallOut
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.asarray([False, False, True, False, True, False, True])
        >>> fo2 = RetrievalFallOut(top_k=2)
        >>> round(float(fo2(preds, target, indexes=indexes)), 4)
        0.5
    """

    higher_is_better: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    _empty_requirement: str = "negative"

    def __init__(self, top_k: Optional[int] = None, empty_target_action: str = "pos",
                 ignore_index: Optional[int] = None, num_queries: Optional[int] = None,
                 **kwargs: Any) -> None:
        # default differs from the base: a query with no negatives counts as
        # worst-case 1.0 fall-out (reference fall_out.py:89)
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index,
                         num_queries=num_queries, **kwargs)
        if top_k is not None and not (isinstance(top_k, int) and top_k > 0):
            raise ValueError("`top_k` has to be a positive integer or None")
        self.top_k = top_k

    def _grouped_metric(self, sq: SortedQueries) -> Tuple[Array, Array]:
        return grouped_fall_out(sq, self.top_k)
