"""The Metric base class — TPU-native core engine.

Counterpart of the reference's ``src/torchmetrics/metric.py`` (Metric :50,
add_state :194, forward :273, sync machinery :423-587, operator overloads
:925-1060, CompositionalMetric :1075), redesigned for JAX/XLA rather than
translated:

- Metric state is a flat pytree of immutable ``jax.Array`` leaves (plus
  Python lists of arrays for "cat"-style list states). Because arrays are
  immutable, caching/restoring state for sync/unsync and forward's
  double-compute is alias-free by construction — no defensive deep copies.
- The stateful OO API (``m.update(...)``, ``m.compute()``, ``m(...)``)
  matches the reference's ergonomics for eager/host-driven use.
- A **functional bridge** (:meth:`Metric.init_state`,
  :meth:`Metric.functional_update`, :meth:`Metric.functional_compute`)
  exposes the same metric as pure functions over an explicit state pytree so
  updates can live *inside* a jitted/`shard_map`-ed step function, with
  cross-device sync lowered to single XLA collectives (psum/pmax/all_gather)
  over a named mesh axis — the reference's eager
  ``torch.distributed.all_gather`` + local reduce (metric.py:423-453) becomes
  one fused ICI collective.
"""

from __future__ import annotations

import functools
import inspect
from abc import ABC, abstractmethod
from contextlib import contextmanager
from copy import deepcopy
from typing import Any, Callable, Dict, Generator, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from tpumetrics.parallel.backend import (
    AxisBackend,
    DistributedBackend,
    distributed_available as _default_distributed_available,
    get_default_backend,
)
from tpumetrics.telemetry import ledger as _telemetry
from tpumetrics.utils.data import (
    _flatten,
    dim_zero_cat,
    dim_zero_max,
    dim_zero_mean,
    dim_zero_min,
    dim_zero_sum,
)
from tpumetrics.utils.exceptions import TPUMetricsUserError
from tpumetrics.utils.prints import rank_zero_warn

Array = jax.Array
StateType = Union[Array, List[Array]]


def jit_distributed_available() -> bool:
    """Reference parity shim (reference metric.py:45-47)."""
    return _default_distributed_available()


def _squeeze_if_scalar(value: Any) -> Any:
    """Collapse single-element arrays to 0-d arrays (reference utilities/data `_squeeze_if_scalar`)."""
    def _sq(x: Any) -> Any:
        if isinstance(x, jax.Array) and x.ndim > 0 and x.size == 1:
            return jnp.reshape(x, ())
        return x

    return jax.tree_util.tree_map(_sq, value)


_CONST_ATTRS = (
    "higher_is_better",
    "is_differentiable",
    "full_state_update",
    "plot_lower_bound",
    "plot_upper_bound",
    "plot_legend_name",
)

_REDUCE_FNS = {
    "sum": dim_zero_sum,
    "mean": dim_zero_mean,
    "cat": dim_zero_cat,
    "min": dim_zero_min,
    "max": dim_zero_max,
}


class Metric(ABC):
    """Base class for all metrics (reference metric.py:50).

    Subclasses implement :meth:`update` and :meth:`compute`; states are
    declared with :meth:`add_state` and accumulated across batches (and, at
    sync points, across devices/hosts).

    Args (all keyword-only, mirroring reference metric.py:112-147):
        compute_on_cpu: move list states to host memory after each update.
        dist_sync_on_step: synchronize state every ``forward`` call.
        process_group: backend-specific group (mesh-axis name for AxisBackend).
        dist_sync_fn: custom gather function ``(array, group) -> list[array]``.
        distributed_available_fn: predicate deciding whether to sync.
        sync_on_compute: synchronize automatically in ``compute`` (default True).
        compute_with_cache: cache the ``compute`` result until next update.
        sync_backend: explicit :class:`DistributedBackend` strategy; defaults
            to the ambient backend (multi-host over DCN when running under
            ``jax.distributed``, no-op single process). Pass
            ``AxisBackend("dp")`` for in-trace ICI sync.
    """

    __jit_ignored_attributes__ = ["device", "dtype"]

    # every kwarg Metric.__init__ itself consumes — wrappers that split
    # base kwargs from passthrough kwargs must filter against this set
    _BASE_KWARGS = frozenset(
        (
            "compute_on_cpu",
            "dist_sync_on_step",
            "process_group",
            "dist_sync_fn",
            "distributed_available_fn",
            "sync_on_compute",
            "compute_with_cache",
            "sync_backend",
        )
    )

    is_differentiable: Optional[bool] = None
    higher_is_better: Optional[bool] = None
    full_state_update: Optional[bool] = None

    plot_lower_bound: Optional[float] = None
    plot_upper_bound: Optional[float] = None
    plot_legend_name: Optional[str] = None

    def __init__(self, **kwargs: Any) -> None:
        self._dtype = jnp.float32

        self.compute_on_cpu = kwargs.pop("compute_on_cpu", False)
        if not isinstance(self.compute_on_cpu, bool):
            raise ValueError(f"Expected keyword argument `compute_on_cpu` to be a `bool` but got {self.compute_on_cpu}")

        self.dist_sync_on_step = kwargs.pop("dist_sync_on_step", False)
        if not isinstance(self.dist_sync_on_step, bool):
            raise ValueError(
                f"Expected keyword argument `dist_sync_on_step` to be a `bool` but got {self.dist_sync_on_step}"
            )

        self.process_group = kwargs.pop("process_group", None)

        self.dist_sync_fn = kwargs.pop("dist_sync_fn", None)
        if self.dist_sync_fn is not None and not callable(self.dist_sync_fn):
            raise ValueError(
                f"Expected keyword argument `dist_sync_fn` to be a callable or None but got {self.dist_sync_fn}"
            )

        self.distributed_available_fn = kwargs.pop("distributed_available_fn", None) or _default_distributed_available

        self.sync_on_compute = kwargs.pop("sync_on_compute", True)
        if not isinstance(self.sync_on_compute, bool):
            raise ValueError(
                f"Expected keyword argument `sync_on_compute` to be a `bool` but got {self.sync_on_compute}"
            )
        self.compute_with_cache = kwargs.pop("compute_with_cache", True)
        if not isinstance(self.compute_with_cache, bool):
            raise ValueError(
                f"Expected keyword argument `compute_with_cache` to be a `bool` but got {self.compute_with_cache}"
            )

        self.sync_backend: Optional[DistributedBackend] = kwargs.pop("sync_backend", None)

        if kwargs:
            kwargs_ = [f"`{a}`" for a in sorted(kwargs)]
            raise ValueError(f"Unexpected keyword arguments: {', '.join(kwargs_)}")

        # state management
        self._defaults: Dict[str, StateType] = {}
        self._persistent: Dict[str, bool] = {}
        self._reductions: Dict[str, Union[str, Callable, None]] = {}
        self._buffer_specs: Dict[str, tuple] = {}  # name -> (capacity, feature_shape, dtype)
        self._state_spec_hints: Dict[str, tuple] = {}  # name -> (feature_shape, dtype) for list states

        self._update_signature = inspect.signature(self.update)
        self.update: Callable = self._wrap_update(self.update)  # type: ignore[method-assign]
        self.compute: Callable = self._wrap_compute(self.compute)  # type: ignore[method-assign]
        self._computed: Any = None
        self._forward_cache: Any = None
        self._update_count = 0
        self._to_sync = self.sync_on_compute
        self._should_unsync = True

        self._cache: Optional[Dict[str, StateType]] = None
        self._is_synced = False

        # degraded-mode bookkeeping (tpumetrics.resilience): a sync failure
        # pending for the next compute, how that compute was served, and the
        # last successfully *synced* result (the "last_good" fallback)
        self._sync_failure: Optional[Exception] = None
        self._degraded: Optional[str] = None
        self._last_good: Any = None

    # ------------------------------------------------------------------ state

    def add_state(
        self,
        name: str,
        default: Union[Array, list, int, float],
        dist_reduce_fx: Optional[Union[str, Callable]] = None,
        persistent: bool = False,
        capacity: Optional[int] = None,
        feature_shape: tuple = (),
        feature_dtype: Optional[Any] = None,
    ) -> None:
        """Register an accumulator state (reference metric.py:194-271).

        ``default`` is either an array (scalar allowed) for tensor states or
        an empty list for "cat"-style list states. ``dist_reduce_fx`` is one
        of ``"sum" | "mean" | "max" | "min" | "cat" | None`` or a custom
        callable operating on a rank-stacked array.

        For list states, ``capacity`` (+ ``feature_shape``/``feature_dtype``)
        declares a **fixed-capacity masked buffer** used on the functional/
        jit path: the state becomes a :class:`~tpumetrics.buffers.MaskedBuffer`
        with static shapes, in-trace appends, and one all_gather+mask sync
        even when ranks contribute uneven row counts (the static-shape
        replacement for the reference's pad-gather-trim,
        utilities/distributed.py:135-147). The eager OO path keeps exact
        Python-list behavior.

        **Declaration contract** (checked statically by tpulint): the
        default must be the reduce identity — zero for ``"sum"``, ``+inf``
        for ``"min"``, ``-inf`` for ``"max"``, an empty list for ``"cat"``
        (TPL301) — otherwise a rank that never updated contributes a wrong
        value to the cross-rank fold.  Array states with
        ``dist_reduce_fx=None`` gather into per-rank stacks that
        ``parallel/merge.py`` can neither fold nor elastically reshard
        (TPL303).  Update states by **reassignment** (jax arrays are
        immutable; a discarded ``.at[...]`` result silently no-ops, TPL302).

        **Callable merges** (the "sketch" state kind): a callable
        ``dist_reduce_fx`` must be associative and commutative over its
        rank-stacked input, and its default must be the merge *identity*
        (TPL301 applies to callable merges too — e.g. an empty sketch, never
        a pre-seeded one).  Wrap the callable in
        :class:`~tpumetrics.parallel.merge.AssociativeMerge` to declare that
        identity explicitly: only then can elastic restore reshard the state
        (folded value on rank 0, identity elsewhere) and snapshot spec
        errors name the declaration parameters (capacity/levels).
        """
        if not name.isidentifier():
            raise ValueError(f"Argument `name` must be a valid python identifier, got {name!r}")
        if not isinstance(default, list):
            default = jnp.asarray(default)
            if jnp.issubdtype(default.dtype, jnp.floating):
                default = default.astype(self._dtype)
        elif default:
            raise ValueError("state variable must be an array or an *empty* list (where you can append arrays)")

        if isinstance(default, list):
            # remember the declared row spec so a later set_state_capacity
            # builds a buffer of the right dtype/shape without re-declaring
            self._state_spec_hints[name] = (tuple(feature_shape), feature_dtype)
        if capacity is not None:
            if not isinstance(default, list):
                raise ValueError("`capacity` is only valid for list ('cat'-style) states")
            # dtype=None resolves to self._dtype lazily at init_state so a
            # later set_dtype()/half() affects buffers like other states
            self._buffer_specs[name] = (int(capacity), tuple(feature_shape), feature_dtype)

        if dist_reduce_fx is not None and not (dist_reduce_fx in _REDUCE_FNS or callable(dist_reduce_fx)):
            raise ValueError(
                "`dist_reduce_fx` must be callable or one of ['mean', 'sum', 'cat', 'min', 'max', None]"
            )
        reduce_fn = _REDUCE_FNS.get(dist_reduce_fx, dist_reduce_fx) if isinstance(dist_reduce_fx, str) else dist_reduce_fx

        self._defaults[name] = default
        self._persistent[name] = persistent
        self._reductions[name] = reduce_fn
        object.__setattr__(self, name, [] if isinstance(default, list) else default)

    def set_state_capacity(
        self,
        name: str,
        capacity: int,
        feature_shape: tuple = (),
        feature_dtype: Optional[Any] = None,
    ) -> None:
        """Declare (or change) the fixed capacity of an existing list state so
        the functional/jit path uses a static-shape MaskedBuffer for it.

        ``feature_shape``/``feature_dtype`` default to what ``add_state``
        declared for this state (so e.g. integer label states get integer
        buffers without repeating the spec here)."""
        if name not in self._defaults or not isinstance(self._defaults[name], list):
            raise ValueError(f"State {name!r} is not a registered list state")
        hint_shape, hint_dtype = self._state_spec_hints.get(name, ((), None))
        if feature_shape == () and hint_shape != ():
            feature_shape = hint_shape
        if feature_dtype is None:
            feature_dtype = hint_dtype
        self._buffer_specs[name] = (int(capacity), tuple(feature_shape), feature_dtype)

    def _append_state(self, name: str, x: Array, valid: Optional[Array] = None) -> None:
        """Append a batch to a list state, optionally masked.

        On the eager path (Python-list state) invalid rows are dropped
        exactly; on the functional/jit path (MaskedBuffer state) the mask
        routes them to the dump slot with static shapes — this is how a
        metric contributes an uneven, data-dependent number of rows per
        device without breaking the compiled program.
        """
        from tpumetrics.buffers import _BufferList

        val = getattr(self, name)
        if isinstance(val, _BufferList):
            val.append(x, valid=valid)
        else:
            if valid is not None:
                x = x[valid]
            val.append(x)

    @property
    def _state_names(self) -> List[str]:
        return list(self._defaults)

    def metric_state(self) -> Dict[str, StateType]:
        """Current state values as a dict pytree."""
        return {attr: getattr(self, attr) for attr in self._defaults}

    @property
    def update_called(self) -> bool:
        """Whether ``update``/``forward`` has been called since init/reset (reference metric.py)."""
        return self._update_count > 0

    @property
    def update_count(self) -> int:
        return self._update_count

    @property
    def degraded(self) -> bool:
        """Whether the most recent ``compute`` was served degraded — from
        unsynced local state (``"local"``) or a previous synced result
        (``"last_good"``) after a swallowed sync failure (see
        :mod:`tpumetrics.resilience`).  Cleared by a successful synced
        compute, ``reset``, or the next update's cache invalidation."""
        return self._degraded is not None

    @property
    def degraded_mode(self) -> Optional[str]:
        """``"local"`` / ``"last_good"`` when :attr:`degraded`, else ``None``."""
        return self._degraded

    def _copy_state_dict(self) -> Dict[str, StateType]:
        """Snapshot of states. Arrays are immutable so aliasing is safe; lists are
        shallow-copied; buffer adapters unwrap to their MaskedBuffer pytree."""
        from tpumetrics.buffers import _BufferList

        out: Dict[str, StateType] = {}
        for attr, val in self.metric_state().items():
            if isinstance(val, _BufferList):
                out[attr] = val.buffer
            elif isinstance(val, list):
                out[attr] = list(val)
            else:
                out[attr] = val
        return out

    # ---------------------------------------------------------------- forward

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self.forward(*args, **kwargs)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Accumulate into the global state AND return the batch-local value
        (reference metric.py:273-305)."""
        if self._is_synced:
            raise TPUMetricsUserError(
                "The Metric shouldn't be synced when performing ``forward``. "
                "HINT: Did you forget to call ``unsync``?"
            )
        if self.full_state_update or self.full_state_update is None or self.dist_sync_on_step:
            self._forward_cache = self._forward_full_state_update(*args, **kwargs)
        else:
            self._forward_cache = self._forward_reduce_state_update(*args, **kwargs)
        return self._forward_cache

    def _forward_full_state_update(self, *args: Any, **kwargs: Any) -> Any:
        """Two-pass forward: global update + fresh single-batch compute
        (reference metric.py:307-350)."""
        self.update(*args, **kwargs)
        _update_count = self._update_count
        _last_good = self._last_good  # survive the temp reset below
        self._to_sync = self.dist_sync_on_step
        self._should_unsync = False
        _temp_compute_on_cpu = self.compute_on_cpu
        self.compute_on_cpu = False

        cache = self._copy_state_dict()

        self.reset()
        self.update(*args, **kwargs)
        batch_val = self.compute()

        for attr, val in cache.items():
            object.__setattr__(self, attr, val)
        self._update_count = _update_count
        self._last_good = _last_good
        self._is_synced = False
        self._should_unsync = True
        self._to_sync = self.sync_on_compute
        self._computed = None
        self.compute_on_cpu = _temp_compute_on_cpu
        if self.compute_on_cpu:
            self._move_list_states_to_cpu()
        return batch_val

    def _forward_reduce_state_update(self, *args: Any, **kwargs: Any) -> Any:
        """Single-pass forward: batch update on empty state then merge into the
        global state (reference metric.py:352-390)."""
        global_state = self._copy_state_dict()
        _update_count = self._update_count
        _last_good = self._last_good  # survive the temp reset below
        self.reset()

        self._to_sync = self.dist_sync_on_step
        self._should_unsync = False
        _temp_compute_on_cpu = self.compute_on_cpu
        self.compute_on_cpu = False

        self.update(*args, **kwargs)
        batch_val = self.compute()

        self._update_count = _update_count + 1
        self._last_good = _last_good
        self._reduce_states(global_state)

        self._is_synced = False
        self._should_unsync = True
        self._to_sync = self.sync_on_compute
        self._computed = None
        self.compute_on_cpu = _temp_compute_on_cpu
        if self.compute_on_cpu:
            self._move_list_states_to_cpu()
        return batch_val

    def _reduce_states(self, incoming_state: Dict[str, StateType]) -> None:
        """Merge an incoming (global) state into the current (batch) state
        per each state's reduction (reference metric.py:392-421)."""
        for attr, reduction_fn in self._reductions.items():
            local_state = getattr(self, attr)
            global_state = incoming_state[attr]
            if reduction_fn == dim_zero_sum:
                reduced = global_state + local_state
            elif reduction_fn == dim_zero_mean:
                reduced = ((self._update_count - 1) * global_state + local_state) / self._update_count
            elif reduction_fn == dim_zero_max:
                reduced = jnp.maximum(global_state, local_state)
            elif reduction_fn == dim_zero_min:
                reduced = jnp.minimum(global_state, local_state)
            elif reduction_fn == dim_zero_cat:
                if isinstance(global_state, jax.Array):
                    reduced = jnp.concatenate([jnp.atleast_1d(global_state), jnp.atleast_1d(local_state)])
                else:
                    reduced = global_state + local_state
            elif reduction_fn is None and isinstance(global_state, jax.Array):
                reduced = jnp.stack([global_state, local_state])
            elif reduction_fn is None and isinstance(global_state, list):
                reduced = _flatten([global_state, local_state])
            else:
                reduced = reduction_fn(jnp.stack([jnp.asarray(global_state), jnp.asarray(local_state)]))  # type: ignore[misc]
            object.__setattr__(self, attr, reduced)

    # ------------------------------------------------------------------- sync

    def _active_backend(self) -> DistributedBackend:
        return self.sync_backend if self.sync_backend is not None else get_default_backend()

    def _sync_dist(
        self,
        dist_sync_fn: Optional[Callable] = None,
        process_group: Optional[Any] = None,
        _reducer: Optional[Any] = None,
    ) -> Optional[Callable]:
        """Gather+reduce every state across ranks (reference metric.py:423-453).

        When no custom ``dist_sync_fn`` is given, "sum"/"mean"/"max"/"min"
        tensor states take the fused ``all_reduce`` path (one psum-style
        collective) instead of gather + local reduce — the key ICI
        optimization over the reference's always-gather wire protocol.

        With an externally shared ``_reducer`` (a MetricCollection fusing its
        whole eager sync into one flush), the reduce-op collectives are
        DEFERRED: this returns a finalize callback to run after the shared
        reducer's flush; gather-style states still sync immediately.
        """
        group = process_group or self.process_group
        backend = self._active_backend()

        if dist_sync_fn is None:
            # fused backend path: reduce-op states share ONE collective per
            # (op, dtype) class via the FusedReducer — one branch ladder for
            # both the stateful (here) and pure (sync_state) paths
            from tpumetrics.parallel.fuse import FusedReducer

            if _reducer is None:
                # standalone eager sync: verify the cross-rank lockstep
                # contract (same collectives, same order) BEFORE any wire op
                # so a divergent rank raises instead of deadlocking; the
                # reducer then skips its own (redundant) flush verification.
                # A collection-shared _reducer is pre-verified by the caller.
                from tpumetrics.telemetry import lockstep as _lockstep

                if _lockstep.should_verify(backend) or _telemetry.recording():
                    _lockstep.verify_lockstep(
                        backend,
                        self._sync_schedule(tag=type(self).__name__),
                        context=f"{type(self).__name__}._sync_dist",
                        group=group,
                    )
                reducer: Any = FusedReducer(backend, group=group, lockstep=False)
            else:
                reducer = _reducer
            current = {attr: getattr(self, attr) for attr in self._reductions}
            # explicitly the BASE collect: eager sync moves this metric's
            # REGISTERED attribute states; wrapper overrides of
            # _sync_state_collect describe their functional (child-state
            # pytree) shape, which does not apply to the attribute wire
            state_finalize = Metric._sync_state_collect(self, current, backend, reducer, group=group)

            def finalize() -> None:
                for attr, val in state_finalize().items():
                    object.__setattr__(self, attr, val)

            if _reducer is None:
                finalize()
                return None
            return finalize

        # reference-faithful custom-gather path
        input_dict = {attr: getattr(self, attr) for attr in self._reductions}
        for attr, reduction_fn in self._reductions.items():
            if reduction_fn == dim_zero_cat and isinstance(input_dict[attr], list) and len(input_dict[attr]) > 1:
                input_dict[attr] = [dim_zero_cat(input_dict[attr])]

        output_dict: Dict[str, Any] = {}
        for attr, val in input_dict.items():
            if isinstance(val, list):
                output_dict[attr] = [dist_sync_fn(v, group) for v in val]
            else:
                output_dict[attr] = dist_sync_fn(val, group)

        for attr, reduction_fn in self._reductions.items():
            if isinstance(output_dict[attr], list) and len(output_dict[attr]) == 0:
                object.__setattr__(self, attr, [])
                continue
            out = output_dict[attr]
            if isinstance(out[0], list):
                out = _flatten(out)
            if not (callable(reduction_fn) or reduction_fn is None):
                raise TypeError("reduction_fn must be callable or None")
            if reduction_fn is None:
                reduced: Any = out
            elif reduction_fn == dim_zero_cat:
                reduced = dim_zero_cat(out)
            else:
                reduced = reduction_fn(jnp.stack(out))
            object.__setattr__(self, attr, reduced)

    def sync(
        self,
        dist_sync_fn: Optional[Callable] = None,
        process_group: Optional[Any] = None,
        should_sync: bool = True,
        distributed_available: Optional[Callable] = None,
        _reducer: Optional[Any] = None,
    ) -> Optional[Callable]:
        """Synchronize state across ranks, caching the local state for
        :meth:`unsync` (reference metric.py:486-528).

        ``_reducer`` (internal): a shared FusedReducer from a collection-wide
        eager sync; when given and the fused path applies, the reduce-op
        collectives defer to the reducer's flush and the returned finalize
        callback applies the results (the caller runs it after flushing).
        Returns ``None`` when the sync was skipped or applied immediately.
        """
        if self._is_synced and should_sync:
            raise TPUMetricsUserError("The Metric has already been synced.")

        if distributed_available is None and self.distributed_available_fn is not None:
            distributed_available = self.distributed_available_fn
        is_distributed = distributed_available() if callable(distributed_available) else None
        if not should_sync or not is_distributed:
            return None

        if dist_sync_fn is None:
            dist_sync_fn = self.dist_sync_fn  # may remain None → fused backend path

        # cache prior to syncing
        self._cache = self._copy_state_dict()
        self._sync_failure = None  # fresh attempt supersedes any earlier failure
        try:
            finalize = self._sync_dist(dist_sync_fn, process_group=process_group, _reducer=_reducer)
        except Exception as err:
            from tpumetrics.resilience.policy import SyncError, get_sync_policy

            # the fused path applies results only after every collective
            # succeeded (finalize), so attrs are untouched on its failures;
            # the custom dist_sync_fn path mutates attrs incrementally, so
            # restore the pre-sync cache either way before unwinding
            for attr, val in self._cache.items():
                object.__setattr__(self, attr, val)
            self._cache = None
            if not isinstance(err, SyncError) or get_sync_policy().on_failure == "raise":
                raise
            self._sync_failure = err
            return None
        self._is_synced = True
        return finalize

    def unsync(self, should_unsync: bool = True) -> None:
        """Restore the cached pre-sync local state (reference metric.py:530-550)."""
        if not should_unsync:
            return
        if not self._is_synced:
            raise TPUMetricsUserError("The Metric has already been un-synced.")
        if self._cache is None:
            raise TPUMetricsUserError("The internal cache should exist to unsync the Metric.")
        for attr, val in self._cache.items():
            object.__setattr__(self, attr, val)
        self._is_synced = False
        self._cache = None

    @contextmanager
    def sync_context(
        self,
        dist_sync_fn: Optional[Callable] = None,
        process_group: Optional[Any] = None,
        should_sync: bool = True,
        should_unsync: bool = True,
        distributed_available: Optional[Callable] = None,
    ) -> Generator[None, None, None]:
        """Sync on entry, restore on exit (reference metric.py:552-587)."""
        self.sync(
            dist_sync_fn=dist_sync_fn,
            process_group=process_group,
            should_sync=should_sync,
            distributed_available=distributed_available,
        )
        yield
        self.unsync(should_unsync=self._is_synced and should_unsync)

    # ------------------------------------------------------------ wrap update

    def _wrap_update(self, update: Callable) -> Callable:
        @functools.wraps(update)
        def wrapped_func(*args: Any, **kwargs: Any) -> None:
            self._computed = None
            self._update_count += 1
            update(*args, **kwargs)
            if self.compute_on_cpu:
                self._move_list_states_to_cpu()

        return wrapped_func

    def _move_list_states_to_cpu(self) -> None:
        """Move list states to host memory (reference metric.py:481-484)."""
        for key in self._defaults:
            current_val = getattr(self, key)
            if isinstance(current_val, Sequence):
                object.__setattr__(self, key, [jax.device_get(cur_v) for cur_v in current_val])

    def _wrap_compute(self, compute: Callable) -> Callable:
        @functools.wraps(compute)
        def wrapped_func(*args: Any, **kwargs: Any) -> Any:
            if self._update_count == 0:
                rank_zero_warn(
                    f"The ``compute`` method of metric {self.__class__.__name__}"
                    " was called before the ``update`` method which may lead to errors,"
                    " as metric states have not yet been updated.",
                    UserWarning,
                )
            if self._computed is not None:
                return self._computed
            with self.sync_context(
                dist_sync_fn=self.dist_sync_fn,
                should_sync=self._to_sync,
                should_unsync=self._should_unsync,
            ):
                # a SyncError swallowed per SyncPolicy.on_failure (by sync()
                # above, or by a collection-wide fused flush that parked this
                # metric) leaves _sync_failure set: serve degraded
                failure = self._sync_failure
                mode: Optional[str] = None
                if failure is not None:
                    from tpumetrics.resilience.policy import get_sync_policy

                    mode = get_sync_policy().on_failure
                    if mode == "last_good" and self._last_good is None:
                        mode = "local"  # nothing good to serve yet
                if mode == "last_good":
                    value = self._last_good
                else:
                    value = _squeeze_if_scalar(compute(*args, **kwargs))
                if failure is not None:
                    self._degraded = mode
                    _telemetry.record_event(
                        self._active_backend(),
                        "degraded_compute",
                        metric=type(self).__name__,
                        mode=mode,
                        error=type(failure).__name__,
                    )
                else:
                    self._degraded = None
                    if self._is_synced:
                        self._last_good = value
            self._sync_failure = None
            if self.compute_with_cache:
                self._computed = value
            return value

        return wrapped_func

    # --------------------------------------------------------------- abstract

    @abstractmethod
    def update(self, *_: Any, **__: Any) -> None:
        """Override to update the metric state (reference metric.py:621).

        **Trace-safety contract** (checked statically by
        ``python -m tpumetrics.analysis`` — "tpulint"): code reachable from
        ``update()`` must not force a host sync before :meth:`compute` —
        no ``.item()``/``.tolist()``/``float()``/``int()``/``bool()``/
        ``len()``/``np.asarray`` on traced values (TPL101) and no Python
        ``if``/``while``/``assert`` branching on them (TPL102); use
        ``jnp.where``/``lax.cond``/masking instead.  Every accumulator
        assigned here must be declared via :meth:`add_state` — an
        undeclared ``self.<attr>`` (TPL401) is invisible to :meth:`reset`,
        snapshots, cross-rank sync, and elastic fold/reshard.  Collectives
        must not be reachable on only one branch of a rank- or
        data-dependent conditional (TPL201).  Deliberately eager code is
        exempt behind the recognized guard idiom
        (``if isinstance(x, jax.core.Tracer): return`` or an
        ``is_traced``-named predicate) or an inline
        ``# tpulint: disable=CODE -- why`` suppression."""

    @abstractmethod
    def compute(self) -> Any:
        """Override to compute the final value from state (reference metric.py:628)."""

    # ------------------------------------------------------- functional bridge

    def init_state(self) -> Dict[str, StateType]:
        """Fresh default state pytree (pure; for the functional/jit path).

        List states declared with a ``capacity`` become fixed-capacity
        :class:`~tpumetrics.buffers.MaskedBuffer` leaves so the whole state is
        a static-shape pytree usable inside jit/shard_map.
        """
        from tpumetrics.buffers import create_buffer

        out: Dict[str, StateType] = {}
        for attr, default in self._defaults.items():
            if attr in self._buffer_specs:
                cap, fshape, fdtype = self._buffer_specs[attr]
                out[attr] = create_buffer(cap, fshape, fdtype if fdtype is not None else self._dtype)
            elif isinstance(default, list):
                out[attr] = []
            else:
                # fresh buffer, not the stored default itself: callers may
                # donate the returned state to jit (donation deletes the
                # buffer, which would poison every later init_state/reset)
                out[attr] = jnp.copy(default)
        return out

    @contextmanager
    def _borrowed_state(self, state: Dict[str, StateType]) -> Generator[None, None, None]:
        """Temporarily swap ``state`` in as the live state.

        List states are shallow-copied on the way in so in-place appends made
        by ``update`` never mutate the caller's pytree (array leaves are
        immutable anyway). MaskedBuffer leaves are wrapped in a list-like
        adapter so subclass ``update`` code can ``.append`` to them.
        """
        from tpumetrics.buffers import MaskedBuffer, _BufferList

        saved = self._copy_state_dict()
        for attr, val in state.items():
            if isinstance(val, MaskedBuffer):
                val = _BufferList(val)
            elif isinstance(val, list):
                val = list(val)
            object.__setattr__(self, attr, val)
        try:
            yield
        finally:
            for attr, val in saved.items():
                object.__setattr__(self, attr, val)

    def functional_update(self, state: Dict[str, StateType], *args: Any, **kwargs: Any) -> Dict[str, StateType]:
        """Pure state transition: ``update(state, batch) -> new_state``.

        Traceable under ``jit`` — usable inside the user's compiled train/eval
        step with the state pytree carried explicitly (donate it for in-place
        buffer reuse on TPU).
        """
        with self._borrowed_state(state):
            self.__wrapped__update_raw(*args, **kwargs)
            new_state = self._copy_state_dict()
        return new_state

    def __wrapped__update_raw(self, *args: Any, **kwargs: Any) -> None:
        # call the subclass update without counters/cache side effects
        type(self).update(self, *args, **kwargs)

    def functional_compute(
        self,
        state: Dict[str, StateType],
        axis_name: Optional[str] = None,
        backend: Optional[DistributedBackend] = None,
    ) -> Any:
        """Pure compute from an explicit state pytree, optionally syncing
        in-trace over ``axis_name`` (ICI collectives) first."""
        if axis_name is not None:
            backend = AxisBackend(axis_name)
        if backend is not None:
            state = self.sync_state(state, backend)
        with self._borrowed_state(state):
            value = _squeeze_if_scalar(type(self).compute(self))
        return value

    def functional_forward(
        self,
        state: Dict[str, StateType],
        *args: Any,
        axis_name: Optional[str] = None,
        backend: Optional[DistributedBackend] = None,
        **kwargs: Any,
    ) -> tuple:
        """Pure ``forward``: accumulate into ``state`` AND return this batch's
        value, optionally synced in-trace over ``axis_name``.

        The TPU-idiomatic ``dist_sync_on_step=True`` path (reference
        metric.py:273-305 + per-step collective): both the state transition
        and the per-step cross-device sync live inside the jitted step, so
        the sync is one fused ICI collective instead of an eager gather.
        Returns ``(new_state, batch_value)``.

        Inside ``shard_map``, the returned state is **per-device** (each
        device accumulates only its own shard) — carry it with the device
        axis explicit (``out_specs=P(axis)`` on a leading device dim), not as
        a falsely-replicated ``P()`` output.
        """
        new_state = self.functional_update(state, *args, **kwargs)
        batch_state = self.functional_update(self.init_state(), *args, **kwargs)
        batch_val = self.functional_compute(batch_state, axis_name=axis_name, backend=backend)
        return new_state, batch_val

    def state_partition_rules(self, data_axis: str = "dp") -> Any:
        """Default :class:`~tpumetrics.parallel.sharding.StatePartitionRules`
        for this metric's registered states: reduce-op states replicated
        (their ``dist_reduce_fx`` lowers to an in-trace all-reduce under
        GSPMD), ``cat``-style and declared-capacity buffer rows sharded
        along ``data_axis``.  Consumed by the sharded
        :class:`~tpumetrics.parallel.fuse_update.FusedCollectionStep` and
        ``StreamingEvaluator(mesh=...)``; override per state by constructing
        :class:`StatePartitionRules` with explicit ``(regex, spec)`` pairs."""
        from tpumetrics.parallel.sharding import StatePartitionRules

        return StatePartitionRules.for_metric(self, data_axis=data_axis)

    def sync_state(
        self, state: Dict[str, StateType], backend: DistributedBackend
    ) -> Dict[str, StateType]:
        """Pure cross-rank merge of a state pytree using each state's reduce op.

        All "sum"/"mean"/"max"/"min" states of one dtype travel as ONE fused
        collective (:class:`tpumetrics.parallel.fuse.FusedReducer`) — the
        collective count is per (op, dtype) class, not per state, unlike the
        reference's one-gather-per-state wire (utilities/distributed.py:97-147).
        """
        from tpumetrics.parallel.fuse import FusedReducer

        reducer = FusedReducer(backend)
        finalize = self._sync_state_collect(state, backend, reducer)
        reducer.flush()
        return finalize()

    def _sync_schedule(self, tag: str = "") -> List[tuple]:
        """The ordered collective schedule this metric's eager sync intends:
        one ``(tag, op, dtype, shape)`` entry per registered state (shape and
        dtype participate only for reduce ops — gather-style states may
        legitimately differ across ranks).  Input to the lockstep verifier."""
        entries = []
        prefix = f"{tag}." if tag else ""
        for attr, reduction_fn in self._reductions.items():
            val = getattr(self, attr)
            op = _reduce_fn_to_op(reduction_fn)
            if (
                op in ("sum", "mean", "max", "min")
                and isinstance(val, jax.Array)
            ):
                entries.append((f"{prefix}{attr}", op, str(val.dtype), tuple(val.shape)))
            else:
                entries.append((f"{prefix}{attr}", "gather", "", ()))
        return entries

    def _sync_state_collect(
        self,
        state: Dict[str, StateType],
        backend: DistributedBackend,
        reducer: Any,
        group: Optional[Any] = None,
    ) -> Callable[[], Dict[str, StateType]]:
        """Phase 1 of a (possibly multi-metric) fused sync: gather-style
        states sync immediately; reduce-style states register with the shared
        ``reducer``. Returns a finalize closure to call after the reducer's
        single ``flush``, producing the synced state. Wrappers with nested
        child states override this (registering children with the SAME
        reducer), which is what lets a whole MetricCollection — wrappers
        included — sync in one flush.

        Collectives issued (or deferred to the reducer) here carry this
        metric's class name as a telemetry attribution tag, nested under any
        enclosing collection/wrapper tag."""
        out: Dict[str, StateType] = {}
        pending: Dict[str, int] = {}
        with _telemetry.attribution(type(self).__name__):
            self._sync_state_collect_inner(state, backend, reducer, group, out, pending)

        def finalize() -> Dict[str, StateType]:
            out.update(reducer.resolve(pending))
            return out

        return finalize

    def _sync_state_collect_inner(
        self,
        state: Dict[str, StateType],
        backend: DistributedBackend,
        reducer: Any,
        group: Optional[Any],
        out: Dict[str, StateType],
        pending: Dict[str, int],
    ) -> None:
        from tpumetrics.buffers import MaskedBuffer, buffer_all_gather
        from tpumetrics.resilience.policy import get_sync_policy, screen_non_finite

        # NaN/Inf screen before states travel (eager only: an in-trace sync
        # has no host value to inspect — see docs/resilience.md)
        guard = get_sync_policy().guard_non_finite
        screen = guard != "off" and not getattr(backend, "in_trace", False)

        for attr, reduction_fn in self._reductions.items():
            val = state[attr]
            if screen:
                where = f"{type(self).__name__}.{attr}"
                if isinstance(val, MaskedBuffer):
                    # only the valid leading rows hold real data; dump-slot
                    # garbage past `count` must not false-positive
                    screen_non_finite(
                        val.values[: int(val.count)], where=where, mode=guard, backend=backend
                    )
                elif isinstance(val, list):
                    for i, item in enumerate(val):
                        screen_non_finite(
                            item, where=f"{where}[{i}]", mode=guard, backend=backend
                        )
                else:
                    screen_non_finite(val, where=where, mode=guard, backend=backend)
            op = _reduce_fn_to_op(reduction_fn)
            if isinstance(val, MaskedBuffer):
                # one all_gather + static-shape compaction; uneven per-rank
                # valid counts are handled by the mask, not by shape surgery
                out[attr] = buffer_all_gather(val, backend, group=group)
            elif isinstance(val, list):
                if reduction_fn is None:
                    # ragged per-item list (e.g. per-image detection states):
                    # item boundaries are part of the state and travel as a
                    # shape matrix beside the flattened data (reference uses
                    # all_gather_object, detection/mean_ap.py:994-1024)
                    out[attr] = _gather_ragged_list(backend, val, group, self._dtype)
                    continue
                # a locally-empty list still participates in the collective
                # (zero-length contribution) so ranks never diverge on the
                # number of collectives issued — a hang otherwise
                catted = dim_zero_cat(val) if val else jnp.zeros((0,), dtype=self._dtype)
                merged = dim_zero_cat(backend.all_gather(catted, group=group))
                out[attr] = [merged] if merged.size else []
            elif op in ("sum", "mean", "max", "min"):
                pending[attr] = reducer.add(val, op)
            elif op == "cat":
                out[attr] = dim_zero_cat(backend.all_gather(val, group=group))
            elif reduction_fn is None:
                out[attr] = jnp.stack(backend.all_gather(val, group=group))
            elif callable(reduction_fn):
                out[attr] = reduction_fn(jnp.stack(backend.all_gather(val, group=group)))
            else:
                raise TypeError("reduction_fn must be callable or None")

    # ------------------------------------------------------------------ reset

    def reset(self) -> None:
        """Reset state to defaults (reference metric.py:669-684)."""
        self._update_count = 0
        self._forward_cache = None
        self._computed = None
        for attr, default in self._defaults.items():
            if isinstance(default, list):
                object.__setattr__(self, attr, [])
            else:
                object.__setattr__(self, attr, default)
        self._cache = None
        self._is_synced = False
        self._sync_failure = None
        self._degraded = None
        self._last_good = None  # a fresh stream must not serve stale results

    def clone(self) -> "Metric":
        """Deep copy of the metric (reference metric.py:686-688)."""
        return deepcopy(self)

    # ------------------------------------------------------- shared backbones

    @property
    def _backbone_share_ids(self) -> tuple:
        """Registry keys of the resident backbones this metric dispatches
        (``tpumetrics.backbones``).  The service folds these into its share
        key so only tenants over the SAME resident weight set megabatch
        together.  Empty for metrics without a pretrained forward."""
        return tuple(h.key for h in getattr(self, "_backbone_handles", ()))

    def release_backbones(self) -> None:
        """Release this metric's references on shared backbone handles.

        Idempotent.  Metrics that acquire a :class:`~tpumetrics.backbones.
        registry.BackboneHandle` in ``__init__`` (LPIPS, the FID family,
        BERTScore/InfoLM when given a ``backbone=``) record it in
        ``self._backbone_handles``; the last release across all instances
        frees the resident weight tree and its program profiles.  The
        evaluation service calls this per tenant on ``close()``."""
        handles, self._backbone_handles = getattr(self, "_backbone_handles", ()), ()
        for h in handles:
            h.close()
        parked, self._parked_backbone_handles = (
            getattr(self, "_parked_backbone_handles", ()), (),
        )
        for h in parked:
            h.discard_parked()

    def hibernate_backbones(self) -> None:
        """Park this metric's backbone references for tenant hibernation.

        The references stay owned (``_parked_backbone_handles``) so a later
        :meth:`release_backbones` still settles them, but they no longer
        pin HBM: when the hibernating tenant was the LAST resident holder
        of a weight set, :meth:`~tpumetrics.backbones.registry.
        BackboneHandle.release_resident` stages the weights to host and
        frees the device tree.  Idempotent; reversed by
        :meth:`revive_backbones`."""
        handles = getattr(self, "_backbone_handles", ())
        if not handles:
            return
        self._parked_backbone_handles = handles
        self._backbone_handles = ()
        for h in handles:
            h.release_resident()

    def revive_backbones(self) -> None:
        """Un-park this metric's backbone references on tenant revival —
        re-placing a weight set only when every holder had hibernated (a
        surviving resident holder means no re-upload happens).  Idempotent;
        the inverse of :meth:`hibernate_backbones`."""
        handles = getattr(self, "_parked_backbone_handles", ())
        if not handles:
            return
        for h in handles:
            h.reacquire()
        self._backbone_handles = handles
        self._parked_backbone_handles = ()

    # ------------------------------------------------------------ persistence

    def persistent(self, mode: bool = False) -> None:
        """Toggle persistence for all states (reference metric.py:823-826)."""
        for key in self._persistent:
            self._persistent[key] = mode

    def state_dict(self, destination: Optional[Dict] = None, prefix: str = "") -> Dict[str, Any]:
        """States marked persistent, as plain host arrays (reference metric.py:828-858)."""
        destination = {} if destination is None else destination
        for key in self._defaults:
            if not self._persistent[key]:
                continue
            current_val = getattr(self, key)
            if isinstance(current_val, list):
                destination[prefix + key] = [jax.device_get(v) for v in current_val]
            else:
                destination[prefix + key] = jax.device_get(current_val)
        return destination

    def load_state_dict(self, state_dict: Dict[str, Any], prefix: str = "", strict: bool = True) -> None:
        """Restore persistent states (reference metric.py:860-877)."""
        for key in self._defaults:
            name = prefix + key
            if name in state_dict:
                value = state_dict[name]
                if isinstance(value, list):
                    object.__setattr__(self, key, [jnp.asarray(v) for v in value])
                else:
                    object.__setattr__(self, key, jnp.asarray(value))
            elif strict and self._persistent[key]:
                raise KeyError(f"Missing key {name!r} in state_dict")

    # -------------------------------------------------- snapshot hooks (runtime)

    def state_spec(self) -> Dict[str, Dict[str, Any]]:
        """Static description of every registered state — ``name -> {kind,
        shape, dtype, reduce}`` — the compatibility contract that snapshot
        restore validates against (``tpumetrics/runtime/snapshot.py``).

        ``kind`` is ``"array"`` for tensor states, ``"list"`` for eager list
        states (with the current length), ``"buffer"`` for list states with
        a declared fixed capacity, or ``"merge"`` for tensor states whose
        ``dist_reduce_fx`` is an
        :class:`~tpumetrics.parallel.merge.AssociativeMerge` (mergeable
        sketches) — those entries carry the merge's declared parameters
        (e.g. a sketch's capacity/levels) so spec mismatches can name them.
        """
        from tpumetrics.parallel.merge import AssociativeMerge

        spec: Dict[str, Dict[str, Any]] = {}
        for name, default in self._defaults.items():
            val = getattr(self, name)
            reduction_fn = self._reductions[name]
            op = _reduce_fn_to_op(reduction_fn)
            entry: Dict[str, Any]
            if isinstance(default, list):
                if name in self._buffer_specs:
                    cap, fshape, fdtype = self._buffer_specs[name]
                    entry = {
                        "kind": "buffer",
                        "capacity": cap,
                        "feature_shape": list(fshape),
                        "dtype": str(jnp.dtype(fdtype) if fdtype is not None else self._dtype),
                    }
                else:
                    entry = {"kind": "list", "length": len(val) if isinstance(val, list) else None}
            elif isinstance(reduction_fn, AssociativeMerge):
                entry = {
                    "kind": "merge",
                    "shape": list(jnp.shape(val)),
                    "dtype": str(jnp.asarray(val).dtype),
                    "params": dict(reduction_fn.params),
                }
            else:
                entry = {"kind": "array", "shape": list(jnp.shape(val)), "dtype": str(jnp.asarray(val).dtype)}
            if isinstance(reduction_fn, AssociativeMerge):
                entry["reduce"] = f"merge:{reduction_fn.name}"
            else:
                entry["reduce"] = op if op is not None else ("custom" if callable(reduction_fn) else None)
            spec[name] = entry
        return spec

    @contextmanager
    def _all_persistent(self) -> Generator[None, None, None]:
        """Temporarily mark every state persistent so ``state_dict``/
        ``load_state_dict`` cover the FULL state (snapshots must capture
        non-persistent accumulators too)."""
        saved = dict(self._persistent)
        for key in self._persistent:
            self._persistent[key] = True
        try:
            yield
        finally:
            self._persistent = saved

    def _config_fingerprint(self) -> Dict[str, Any]:
        """JSON-able instance configuration (num_classes, average, thresholds,
        …): every plain-scalar public attribute.  Snapshots carry it so a
        restore into a differently-configured metric fails loudly even when
        every registered state is an eager list (whose shapes alone cannot
        reveal the mismatch — e.g. samplewise statscores).

        Sync wiring (``Metric._BASE_KWARGS``: sync_backend, process_group,
        dist_sync_fn, …) is deployment plumbing, not metric configuration —
        it is excluded, so a snapshot written under one backend restores
        under another (e.g. a fault-injection wrapper in tests, or a
        restarted process that has not re-initialized jax.distributed yet).
        """
        return {
            k: (list(v) if isinstance(v, tuple) else v)
            for k, v in vars(self).items()
            if not k.startswith("_")
            and k not in Metric._BASE_KWARGS
            and (
                v is None
                or isinstance(v, (bool, int, float, str))
                or (isinstance(v, tuple) and all(isinstance(x, (bool, int, float, str)) for x in v))
            )
        }

    def snapshot_state(self) -> Dict[str, Any]:
        """Full runtime snapshot of this metric: every state (persistent or
        not, as host arrays via :meth:`state_dict`) plus the update counter
        and config fingerprint — the payload
        :mod:`tpumetrics.runtime.snapshot` persists atomically."""
        with self._all_persistent():
            states = self.state_dict()
        return {
            "states": states,
            "update_count": int(self._update_count),
            "config": self._config_fingerprint(),
        }

    def _validate_snapshot_payload(self, snap: Dict[str, Any], strict: bool = True) -> None:
        """Shared validation for :meth:`load_snapshot_state` and
        :meth:`fold_snapshot_states`: state spec (names, shapes, dtypes of
        tensor states) AND the config fingerprint, checked before any state
        is touched so a mismatched restore fails atomically."""
        states = snap["states"]
        problems = []
        saved_cfg = snap.get("config")
        if strict and saved_cfg is not None:
            # filter sync wiring from BOTH sides: snapshots written before
            # the fingerprint excluded _BASE_KWARGS still carry those keys,
            # and must stay restorable
            saved_cfg = {k: v for k, v in saved_cfg.items() if k not in Metric._BASE_KWARGS}
            own_cfg = self._config_fingerprint()
            for key in sorted(set(saved_cfg) | set(own_cfg)):
                a, b = saved_cfg.get(key, "<absent>"), own_cfg.get(key, "<absent>")
                # snapshot headers round-trip through JSON: scalar numpy
                # leaves stay python scalars, so plain != is the right test
                if a != b:
                    problems.append(f"config {key}: snapshot {a!r} != this metric {b!r}")
        for name, default in self._defaults.items():
            if name not in states:
                problems.append(f"missing state {name!r}")
                continue
            val = states[name]
            if not isinstance(default, list):
                want_shape, want_dtype = jnp.shape(getattr(self, name)), jnp.asarray(getattr(self, name)).dtype
                got = jnp.asarray(val)
                if tuple(got.shape) != tuple(want_shape) or got.dtype != want_dtype:
                    from tpumetrics.parallel.merge import AssociativeMerge

                    note = ""
                    reduction_fn = self._reductions.get(name)
                    if isinstance(reduction_fn, AssociativeMerge):
                        # a merge-kind (sketch) state's shape IS its declared
                        # parameters: name them, like the config fingerprint
                        # names classification configs
                        note = f" [this metric declares {reduction_fn.describe()}]"
                    problems.append(
                        f"{name}: snapshot {got.dtype}{tuple(got.shape)} != expected {want_dtype}{tuple(want_shape)}{note}"
                    )
        if strict:
            problems.extend(f"unexpected state {k!r}" for k in states if k not in self._defaults)
        if problems:
            raise TPUMetricsUserError(
                f"Snapshot state spec incompatible with {type(self).__name__}: " + "; ".join(problems)
                + ". HINT: the metric configuration must match the one that wrote the snapshot."
            )

    def load_snapshot_state(self, snap: Dict[str, Any], strict: bool = True) -> None:
        """Restore a :meth:`snapshot_state` payload, validating the state
        spec (names, shapes, dtypes of tensor states) AND the config
        fingerprint before touching any state so a mismatched restore fails
        atomically with a clear error."""
        self._validate_snapshot_payload(snap, strict=strict)
        with self._all_persistent():
            self.load_state_dict(snap["states"], strict=strict)
        self._update_count = int(snap.get("update_count", self._update_count))
        self._computed = None
        self._cache = None
        self._is_synced = False

    # ------------------------------------------------ elastic fold / reshard

    def fold_snapshot_states(
        self, payloads: List[Dict[str, Any]], strict: bool = True
    ) -> Dict[str, Any]:
        """Fold per-rank :meth:`snapshot_state` payloads into ONE canonical
        global payload, using each state's registered ``dist_reduce_fx``
        (reduce states fold; cat/list states concatenate in rank order) —
        the merge half of elastic restore
        (:mod:`tpumetrics.resilience.elastic`).

        Every payload is validated against THIS metric's config fingerprint
        first, so a cut written by differently-configured ranks fails loudly
        before any state is merged.  ``update_count`` sums across ranks.
        """
        from tpumetrics.parallel.merge import merge_metric_states

        if not payloads:
            raise TPUMetricsUserError("fold_snapshot_states needs at least one rank payload")
        for snap in payloads:
            self._validate_snapshot_payload(snap, strict=strict)
        merged = merge_metric_states(
            [dict(p["states"]) for p in payloads], self._reductions, owner=type(self).__name__
        )
        return {
            "states": merged,
            "update_count": int(sum(int(p.get("update_count", 0)) for p in payloads)),
            "config": self._config_fingerprint(),
        }

    def reshard_snapshot_state(
        self,
        snap: Dict[str, Any],
        rank: int,
        world_size: int,
        cat_placement: str = "rank0",
    ) -> Dict[str, Any]:
        """Rank ``rank``'s share of a folded global payload for a
        ``world_size``-rank world — the split half of elastic restore.
        Placement semantics per state kind:
        :func:`tpumetrics.parallel.merge.reshard_metric_states`.

        The global ``update_count`` splits near-evenly across ranks
        (additive bookkeeping: a later fold sums back to the global total,
        and every rank that received a share of the data also reads as
        updated — no spurious "compute before update" warnings)."""
        from tpumetrics.parallel.merge import reshard_metric_states

        states = reshard_metric_states(
            dict(snap["states"]), self._reductions, rank, world_size,
            cat_placement=cat_placement, owner=type(self).__name__,
        )
        total = int(snap.get("update_count", 0))
        base, extra = divmod(total, world_size)
        return {
            "states": states,
            "update_count": base + (1 if rank < extra else 0),
            "config": self._config_fingerprint(),
        }

    def fold_state_dicts(self, states: List[Dict[str, Any]]) -> Dict[str, Any]:
        """Fold per-rank functional state pytrees (the :meth:`init_state`
        shape, MaskedBuffer leaves included) into one global state — the
        bucketed-runtime counterpart of :meth:`fold_snapshot_states`."""
        from tpumetrics.parallel.merge import merge_metric_states

        if not states:
            raise TPUMetricsUserError("fold_state_dicts needs at least one rank state")
        return merge_metric_states(list(states), self._reductions, owner=type(self).__name__)

    def reshard_state_dict(
        self,
        state: Dict[str, Any],
        rank: int,
        world_size: int,
        cat_placement: str = "rank0",
    ) -> Dict[str, Any]:
        """Rank ``rank``'s share of a folded functional state for a
        ``world_size``-rank world.  Buffer states reshard against this
        metric's declared per-rank capacities (:meth:`init_state`); overflow
        raises rather than dropping restored rows."""
        from tpumetrics.parallel.merge import reshard_metric_states

        return reshard_metric_states(
            dict(state), self._reductions, rank, world_size,
            templates=self.init_state(), cat_placement=cat_placement,
            owner=type(self).__name__,
        )

    # ------------------------------------------------------------ dev / dtype

    @property
    def device(self) -> Any:
        """Device of the metric states (probe-array derivation, reference metric.py:813)."""
        for attr in self._defaults:
            val = getattr(self, attr)
            if isinstance(val, jax.Array):
                devs = val.devices()
                return next(iter(devs))
            if isinstance(val, list) and val and isinstance(val[0], jax.Array):
                return next(iter(val[0].devices()))
        return jax.devices()[0]

    @property
    def dtype(self) -> Any:
        return self._dtype

    def to(self, device: Any) -> "Metric":
        """Move all states to ``device`` (reference `_apply`, metric.py:773-820)."""
        def _move(val: Any) -> Any:
            if isinstance(val, jax.Array):
                return jax.device_put(val, device)
            return val

        for attr in self._defaults:
            val = getattr(self, attr)
            if isinstance(val, list):
                object.__setattr__(self, attr, [_move(v) for v in val])
            else:
                object.__setattr__(self, attr, _move(val))
        self._defaults = {
            k: ([] if isinstance(v, list) else _move(v)) for k, v in self._defaults.items()
        }
        return self

    def set_dtype(self, dst_type: Any) -> "Metric":
        """Convert floating-point states to ``dst_type`` (reference metric.py:761-771).

        Note: accumulators should generally stay fp32 even under bf16 inputs —
        this mirrors the reference API for explicit opt-in.
        """
        self._dtype = jnp.dtype(dst_type)

        def _convert(val: Any) -> Any:
            if isinstance(val, jax.Array) and jnp.issubdtype(val.dtype, jnp.floating):
                return val.astype(dst_type)
            return val

        for attr in self._defaults:
            val = getattr(self, attr)
            if isinstance(val, list):
                object.__setattr__(self, attr, [_convert(v) for v in val])
            else:
                object.__setattr__(self, attr, _convert(val))
        self._defaults = {
            k: ([] if isinstance(v, list) else _convert(v)) for k, v in self._defaults.items()
        }
        self._computed = jax.tree_util.tree_map(_convert, self._computed)
        return self

    def float(self) -> "Metric":
        return self.set_dtype(jnp.float32)

    def double(self) -> "Metric":
        return self.set_dtype(jnp.float64)

    def half(self) -> "Metric":
        return self.set_dtype(jnp.bfloat16)

    # --------------------------------------------------------------- plumbing

    def _filter_kwargs(self, **kwargs: Any) -> Dict[str, Any]:
        """Filter kwargs to those accepted by this metric's update signature
        (reference metric.py:879-898; used by MetricCollection routing)."""
        _params = (inspect.Parameter.VAR_POSITIONAL, inspect.Parameter.VAR_KEYWORD)
        _sign_params = self._update_signature.parameters
        filtered_kwargs = {
            k: v for k, v in kwargs.items() if (k in _sign_params and _sign_params[k].kind not in _params)
        }
        exists_var_keyword = any(v.kind == inspect.Parameter.VAR_KEYWORD for v in _sign_params.values())
        if not filtered_kwargs and not exists_var_keyword:
            filtered_kwargs = kwargs
        if exists_var_keyword:
            filtered_kwargs = kwargs
        return filtered_kwargs

    def __getstate__(self) -> Dict[str, Any]:
        """Pickle support: drop wrapped bound methods (reference metric.py:690-696)."""
        return {k: v for k, v in self.__dict__.items() if k not in ("update", "compute", "_update_signature")}

    def __setstate__(self, state: Dict[str, Any]) -> None:
        self.__dict__.update(state)
        self._update_signature = inspect.signature(self.update)
        self.update = self._wrap_update(self.update)  # type: ignore[method-assign]
        self.compute = self._wrap_compute(self.compute)  # type: ignore[method-assign]

    def __setattr__(self, name: str, value: Any) -> None:
        """Guard const class attributes against instance mutation (reference metric.py:711-722)."""
        if name in _CONST_ATTRS:
            raise RuntimeError(f"Can't change const `{name}`.")
        object.__setattr__(self, name, value)

    def __hash__(self) -> int:
        """Hash over identity-relevant fields (reference metric.py:900-911)."""
        hash_vals: List[Any] = [self.__class__.__name__]
        for key in self._defaults:
            val = getattr(self, key)
            if isinstance(val, list):
                hash_vals.extend(id(v) for v in val)
            else:
                hash_vals.append(id(val))
        return hash(tuple(hash_vals))

    def __repr__(self) -> str:
        return f"{self.__class__.__name__}()"

    def _repr_kwargs(self) -> str:
        return ""

    # ------------------------------------------------------------------- plot

    def _plot(self, val: Any = None, ax: Any = None) -> Any:
        from tpumetrics.utils.plot import plot_single_or_multi_val

        val = val if val is not None else self.compute()
        fig, ax = plot_single_or_multi_val(
            val,
            ax=ax,
            higher_is_better=self.higher_is_better,
            lower_bound=self.plot_lower_bound,
            upper_bound=self.plot_upper_bound,
            legend_name=self.plot_legend_name,
            name=self.__class__.__name__,
        )
        return fig, ax

    def plot(self, *args: Any, **kwargs: Any) -> Any:
        """Plot the metric value(s); requires matplotlib (reference metric.py:633-667)."""
        return self._plot(*args, **kwargs)

    # ---------------------------------------------------------- compositional
    # operator overloads (reference metric.py:925-1060)

    def __add__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.add, self, other)

    def __and__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_and, self, other)

    def __eq__(self, other: Any) -> "CompositionalMetric":  # type: ignore[override]
        return CompositionalMetric(jnp.equal, self, other)

    def __floordiv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.floor_divide, self, other)

    def __ge__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.greater_equal, self, other)

    def __gt__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.greater, self, other)

    def __le__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.less_equal, self, other)

    def __lt__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.less, self, other)

    def __matmul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.matmul, self, other)

    def __mod__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.mod, self, other)

    def __mul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.multiply, self, other)

    def __ne__(self, other: Any) -> "CompositionalMetric":  # type: ignore[override]
        return CompositionalMetric(jnp.not_equal, self, other)

    def __or__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_or, self, other)

    def __pow__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.power, self, other)

    def __radd__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.add, other, self)

    def __rand__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(lambda x, y: jnp.bitwise_and(y, x), self, other)

    def __rfloordiv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.floor_divide, other, self)

    def __rmatmul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.matmul, other, self)

    def __rmod__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.mod, other, self)

    def __rmul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.multiply, other, self)

    def __ror__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(lambda x, y: jnp.bitwise_or(y, x), self, other)

    def __rpow__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.power, other, self)

    def __rsub__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.subtract, other, self)

    def __rtruediv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.true_divide, other, self)

    def __rxor__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(lambda x, y: jnp.bitwise_xor(y, x), self, other)

    def __sub__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.subtract, self, other)

    def __truediv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.true_divide, self, other)

    def __xor__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_xor, self, other)

    def __abs__(self) -> "CompositionalMetric":
        return CompositionalMetric(jnp.abs, self, None)

    def __inv__(self) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_not, self, None)

    def __invert__(self) -> "CompositionalMetric":
        return self.__inv__()

    def __neg__(self) -> "CompositionalMetric":
        return CompositionalMetric(_neg, self, None)

    def __pos__(self) -> "CompositionalMetric":
        return CompositionalMetric(jnp.abs, self, None)

    def __getitem__(self, idx: Any) -> "CompositionalMetric":
        return CompositionalMetric(lambda x: x[idx], self, None)

    def __getnewargs__(self) -> tuple:
        return tuple()

    __iter__ = None


def _neg(x: Array) -> Array:
    return -jnp.abs(x)


def _gather_ragged_list(
    backend: DistributedBackend, items: List[Array], group: Optional[Any], fallback_dtype: Any
) -> List[Array]:
    """Gather a reduce-None ragged list across ranks, preserving item
    boundaries with two collectives per state: one gather of the per-item
    shape matrix and one of the fully-flattened elements, split + reshaped
    back on receipt. Items may be ragged in every dimension (e.g. per-image
    (D_i, G_i) IoU matrices) and of any rank incl. 0-d. Eager backends
    only — in-trace ragged gathers need the fixed-capacity MaskedBuffer
    states instead."""
    import numpy as np

    from tpumetrics.utils.data import _is_tracer

    if any(_is_tracer(v) for v in items):
        raise TPUMetricsUserError(
            "Ragged (dist_reduce_fx=None) list states cannot be gathered inside jit;"
            " declare a fixed capacity for the state (set_state_capacity) to sync in-trace."
        )
    # each row is [ndim, d0, d1, ...] padded with trailing 1s so mixed-rank
    # items round-trip with their exact rank (a bare shape row cannot tell
    # (3,) from (3, 1))
    rank_ndim = max((v.ndim for v in items), default=1)
    shapes = jnp.asarray(
        [(v.ndim,) + tuple(v.shape) + (1,) * (rank_ndim - v.ndim) for v in items], jnp.int32
    ).reshape(len(items), 1 + rank_ndim)
    if items:
        data = jnp.concatenate([jnp.ravel(v) for v in items])
    else:
        data = jnp.zeros((0,), fallback_dtype)

    gathered_shapes = backend.all_gather(shapes, group=group)
    gathered_data = backend.all_gather(data, group=group)

    out: List[Array] = []
    for rank_shapes, rank_data in zip(gathered_shapes, gathered_data):
        offset = 0
        for shape_row in np.asarray(rank_shapes).reshape(-1, np.asarray(rank_shapes).shape[-1]):
            ndim = int(shape_row[0])
            shape = tuple(int(x) for x in shape_row[1 : 1 + ndim])
            n = int(np.prod(shape))
            out.append(rank_data[offset : offset + n].reshape(shape))
            offset += n
    return out


def _reduce_fn_to_op(reduction_fn: Any) -> Optional[str]:
    """Map a registered reduce function back to its wire-op name."""
    if reduction_fn == dim_zero_sum:
        return "sum"
    if reduction_fn == dim_zero_mean:
        return "mean"
    if reduction_fn == dim_zero_max:
        return "max"
    if reduction_fn == dim_zero_min:
        return "min"
    if reduction_fn == dim_zero_cat:
        return "cat"
    return None


class CompositionalMetric(Metric):
    """Lazy arithmetic composition of two metrics (reference metric.py:1075-1198).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics import SumMetric
        >>> a, b = SumMetric(), SumMetric()
        >>> combined = a + b  # CompositionalMetric(jnp.add, a, b)
        >>> a.update(2.0)
        >>> b.update(3.0)
        >>> float(combined.compute())
        5.0
    """

    def __init__(
        self,
        operator: Callable,
        metric_a: Union[Metric, float, int, Array, None],
        metric_b: Union[Metric, float, int, Array, None],
    ) -> None:
        super().__init__()
        self.op = operator
        self.metric_a = jnp.asarray(metric_a) if isinstance(metric_a, (int, float)) else metric_a
        self.metric_b = jnp.asarray(metric_b) if isinstance(metric_b, (int, float)) else metric_b

    def _sync_dist(
        self,
        dist_sync_fn: Optional[Callable] = None,
        process_group: Optional[Any] = None,
        _reducer: Optional[Any] = None,
    ) -> None:
        pass  # children sync themselves (reference metric.py:1114-1119)

    def update(self, *args: Any, **kwargs: Any) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.update(*args, **self.metric_a._filter_kwargs(**kwargs))
        if isinstance(self.metric_b, Metric):
            self.metric_b.update(*args, **self.metric_b._filter_kwargs(**kwargs))

    def compute(self) -> Any:
        val_a = self.metric_a.compute() if isinstance(self.metric_a, Metric) else self.metric_a
        val_b = self.metric_b.compute() if isinstance(self.metric_b, Metric) else self.metric_b
        if val_b is None:
            return self.op(val_a)
        return self.op(val_a, val_b)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        val_a = (
            self.metric_a(*args, **self.metric_a._filter_kwargs(**kwargs))
            if isinstance(self.metric_a, Metric)
            else self.metric_a
        )
        val_b = (
            self.metric_b(*args, **self.metric_b._filter_kwargs(**kwargs))
            if isinstance(self.metric_b, Metric)
            else self.metric_b
        )
        if val_a is None:
            self._forward_cache = None
            return self._forward_cache
        if val_b is None:
            if isinstance(self.metric_b, Metric):
                self._forward_cache = None
                return self._forward_cache
            self._forward_cache = self.op(val_a)
            return self._forward_cache
        self._forward_cache = self.op(val_a, val_b)
        return self._forward_cache

    def reset(self) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.reset()
        if isinstance(self.metric_b, Metric):
            self.metric_b.reset()

    # ------------------------------------------------------ functional bridge
    # child states as a {"a": ..., "b": ...} pytree (constants carry None)

    def init_state(self) -> Dict[str, Any]:
        return {
            "a": self.metric_a.init_state() if isinstance(self.metric_a, Metric) else None,
            "b": self.metric_b.init_state() if isinstance(self.metric_b, Metric) else None,
        }

    def functional_update(self, state: Dict[str, Any], *args: Any, **kwargs: Any) -> Dict[str, Any]:
        out = dict(state)
        if isinstance(self.metric_a, Metric):
            out["a"] = self.metric_a.functional_update(
                state["a"], *args, **self.metric_a._filter_kwargs(**kwargs)
            )
        if isinstance(self.metric_b, Metric):
            out["b"] = self.metric_b.functional_update(
                state["b"], *args, **self.metric_b._filter_kwargs(**kwargs)
            )
        return out

    def functional_compute(self, state: Dict[str, Any], axis_name: Any = None, backend: Any = None) -> Any:
        val_a = (
            self.metric_a.functional_compute(state["a"], axis_name=axis_name, backend=backend)
            if isinstance(self.metric_a, Metric)
            else self.metric_a
        )
        val_b = (
            self.metric_b.functional_compute(state["b"], axis_name=axis_name, backend=backend)
            if isinstance(self.metric_b, Metric)
            else self.metric_b
        )
        if val_b is None:
            return self.op(val_a)
        return self.op(val_a, val_b)

    def _sync_state_collect(self, state: Dict[str, Any], backend: Any, reducer: Any, group: Any = None) -> Any:
        fin_a = (
            self.metric_a._sync_state_collect(state["a"], backend, reducer, group)
            if isinstance(self.metric_a, Metric)
            else (lambda: state["a"])
        )
        fin_b = (
            self.metric_b._sync_state_collect(state["b"], backend, reducer, group)
            if isinstance(self.metric_b, Metric)
            else (lambda: state["b"])
        )
        return lambda: {"a": fin_a(), "b": fin_b()}

    def persistent(self, mode: bool = False) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.persistent(mode=mode)
        if isinstance(self.metric_b, Metric):
            self.metric_b.persistent(mode=mode)

    def __repr__(self) -> str:
        _op_metrics = f"(\n  {self.op.__name__ if hasattr(self.op, '__name__') else self.op}(\n    {self.metric_a!r},\n    {self.metric_b!r}\n  )\n)"
        return self.__class__.__name__ + _op_metrics
