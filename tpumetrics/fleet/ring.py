"""Epoch-versioned consistent-hash routing ring for tenant placement.

The ring answers exactly one question — "who owns tenant T right now?" —
and stamps every answer with the **routing epoch** under which it was
produced.  An epoch is a monotonically increasing integer bumped on every
topology or placement change (rank added/removed, tenant reassigned).  A
cached ``(tenant -> rank)`` binding is valid only while the epoch it was
read under is still current; readers that hold bindings across a migration
seam must re-read after observing an epoch bump (tpulint TPL109 flags code
that doesn't).

Placement is classic consistent hashing: each rank contributes ``vnodes``
virtual points on a 64-bit SHA-1 ring and a tenant maps to the first point
clockwise of its own hash.  Explicit **pins** overlay the hash placement —
a migration commits by pinning the tenant to its new rank — so the hash
ring only decides *natural* ownership; :meth:`natural_owner` exposes that
undecorated answer for rebalancing (move pinned tenants back toward their
natural rank when the topology changes).

Everything here is process-local, lock-protected, and cheap: O(log V)
lookups, O(V) topology edits.  Cross-process agreement rides the
federation plane — the controller publishes :meth:`census` under
``/statusz`` so any rank can answer ownership questions for the pool.
"""

from __future__ import annotations

import bisect
import hashlib
import threading
from typing import Any, Dict, Iterable, List, Optional, Tuple

from tpumetrics.utils.exceptions import TPUMetricsUserError

__all__ = ["ConsistentHashRing", "RingError"]


class RingError(TPUMetricsUserError):
    """The ring cannot answer (empty ring, unknown rank, bad epoch)."""


def _hash(key: str) -> int:
    """Stable 64-bit ring position (first 8 bytes of SHA-1, big-endian)."""
    return int.from_bytes(hashlib.sha1(key.encode("utf-8")).digest()[:8], "big")


class ConsistentHashRing:
    """Thread-safe consistent-hash ring with pins and a routing epoch."""

    def __init__(self, ranks: Iterable[int] = (), *, vnodes: int = 64) -> None:
        if vnodes < 1:
            raise RingError(f"vnodes must be >= 1, got {vnodes}")
        self._vnodes = int(vnodes)
        self._lock = threading.Lock()
        self._epoch = 0
        self._ranks: List[int] = []
        self._points: List[Tuple[int, int]] = []  # (position, rank), sorted
        self._pins: Dict[str, int] = {}  # tenant id -> pinned rank
        for rank in ranks:
            self.add_rank(rank)

    # ------------------------------------------------------------ topology

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    @property
    def ranks(self) -> Tuple[int, ...]:
        with self._lock:
            return tuple(self._ranks)

    @property
    def vnodes(self) -> int:
        return self._vnodes

    def add_rank(self, rank: int) -> int:
        """Add ``rank``'s vnodes; returns the new routing epoch."""
        rank = int(rank)
        with self._lock:
            if rank in self._ranks:
                raise RingError(f"Rank {rank} is already on the ring")
            self._ranks.append(rank)
            self._ranks.sort()
            for v in range(self._vnodes):
                pos = _hash(f"rank:{rank}:vnode:{v}")
                bisect.insort(self._points, (pos, rank))
            self._epoch += 1
            return self._epoch

    def remove_rank(self, rank: int) -> int:
        """Drop ``rank`` (and any pins to it); returns the new epoch."""
        rank = int(rank)
        with self._lock:
            if rank not in self._ranks:
                raise RingError(f"Rank {rank} is not on the ring")
            self._ranks.remove(rank)
            self._points = [(p, r) for (p, r) in self._points if r != rank]
            for tid in [t for t, r in self._pins.items() if r == rank]:
                del self._pins[tid]
            self._epoch += 1
            return self._epoch

    # ------------------------------------------------------------ placement

    def _natural_locked(self, tenant_id: str) -> int:
        if not self._points:
            raise RingError("Ring has no ranks; cannot place a tenant")
        pos = _hash(f"tenant:{tenant_id}")
        i = bisect.bisect_right(self._points, (pos, 1 << 62))
        if i == len(self._points):
            i = 0  # wrap around the ring
        return self._points[i][1]

    def owner(self, tenant_id: str) -> Tuple[int, int]:
        """``(owner_rank, routing_epoch)`` for ``tenant_id`` — pins win."""
        tenant_id = str(tenant_id)
        with self._lock:
            pinned = self._pins.get(tenant_id)
            rank = pinned if pinned is not None else self._natural_locked(tenant_id)
            return rank, self._epoch

    def natural_owner(self, tenant_id: str) -> int:
        """Hash-only placement, ignoring pins (the rebalance target)."""
        with self._lock:
            return self._natural_locked(str(tenant_id))

    def reassign(self, tenant_id: str, rank: int) -> int:
        """Pin ``tenant_id`` to ``rank`` and bump the epoch; returns it."""
        rank = int(rank)
        with self._lock:
            if rank not in self._ranks:
                raise RingError(f"Cannot pin {tenant_id!r} to rank {rank}: not on the ring")
            self._pins[str(tenant_id)] = rank
            self._epoch += 1
            return self._epoch

    def unpin(self, tenant_id: str) -> int:
        """Drop an explicit pin (tenant reverts to natural placement)."""
        with self._lock:
            self._pins.pop(str(tenant_id), None)
            self._epoch += 1
            return self._epoch

    def pins(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._pins)

    # ------------------------------------------------------------ census

    def census(
        self, tenant_ids: Iterable[str], migrating: Iterable[str] = ()
    ) -> Dict[str, Dict[str, Any]]:
        """Per-tenant routing rows for ``/statusz``: ``owner_rank``,
        ``routing_epoch``, ``migrating``."""
        moving = {str(t) for t in migrating}
        out: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            for tid in tenant_ids:
                tid = str(tid)
                pinned = self._pins.get(tid)
                rank = pinned if pinned is not None else self._natural_locked(tid)
                out[tid] = {
                    "owner_rank": rank,
                    "routing_epoch": self._epoch,
                    "migrating": tid in moving,
                }
        return out

    # ------------------------------------------------------------ round trip

    def to_dict(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "epoch": self._epoch,
                "vnodes": self._vnodes,
                "ranks": list(self._ranks),
                "pins": dict(self._pins),
            }

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "ConsistentHashRing":
        ring = cls(data.get("ranks", ()), vnodes=int(data.get("vnodes", 64)))
        with ring._lock:
            ring._pins = {str(k): int(v) for k, v in dict(data.get("pins", {})).items()}
            ring._epoch = int(data.get("epoch", ring._epoch))
        return ring

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        with self._lock:
            return (
                f"ConsistentHashRing(ranks={self._ranks}, epoch={self._epoch}, "
                f"pins={len(self._pins)}, vnodes={self._vnodes})"
            )
