"""Self-scaling fleet: consistent-hash tenant placement, zero-loss live
migration, and SLO-driven pool resize.

- :mod:`~tpumetrics.fleet.ring` — the epoch-versioned consistent-hash
  routing ring (placement + census).
- :mod:`~tpumetrics.fleet.migrate` — the two-phase zero-loss tenant
  handoff and its crash recovery.
- :mod:`~tpumetrics.fleet.autoscaler` — burn-rate signal -> grow/shrink
  decisions with hysteresis.
- :mod:`~tpumetrics.fleet.controller` — the :class:`FleetController`
  tying them together over N evaluation services.
"""

from tpumetrics.fleet.autoscaler import Autoscaler, AutoscalerPolicy
from tpumetrics.fleet.controller import FleetController
from tpumetrics.fleet.migrate import (
    HandoffStore,
    MigrationError,
    MigrationReport,
    TenantMigratingError,
    migrate_tenant,
    recover_handoffs,
)
from tpumetrics.fleet.ring import ConsistentHashRing, RingError

__all__ = [
    "Autoscaler",
    "AutoscalerPolicy",
    "ConsistentHashRing",
    "FleetController",
    "HandoffStore",
    "MigrationError",
    "MigrationReport",
    "RingError",
    "TenantMigratingError",
    "migrate_tenant",
    "recover_handoffs",
]
