"""SLO-driven pool autoscaler with hysteresis.

The autoscaler consumes the burn-rate signal the SLO engine already
computes (:class:`~tpumetrics.telemetry.slo.SloEngine` latches a breach
when BOTH the fast and slow burn windows exceed the objective's budget)
and turns it into grow/shrink decisions for the fleet controller.  It
deliberately owns NO metric math — the SLO rules define "too slow", the
autoscaler only answers "how many ranks".

Hysteresis is three-fold, so a recovering pool cannot thrash:

- **streaks** — grow only after ``grow_after`` consecutive breached
  observations, shrink only after ``shrink_after`` consecutive calm ones
  (shrink is the slower direction by default: scale up fast, down slow);
- **cooldown** — after any action, hold for ``cooldown_s`` regardless of
  the signal (a fresh rank needs time to absorb rebalanced tenants before
  the burn windows can reflect it);
- **bounds** — the world stays in ``[min_ranks, max_ranks]``.

Clock-injectable (``clock=``) and driven by explicit
:meth:`Autoscaler.observe` calls, so tests and the soak advance it
deterministically.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, Optional, Tuple

from tpumetrics.telemetry import instruments as _instruments

__all__ = ["Autoscaler", "AutoscalerPolicy"]

_DECISIONS_TOTAL = _instruments.counter(
    _instruments.AUTOSCALE_DECISIONS,
    help="autoscaler decisions by kind",
    labels=("decision",),
)


@dataclasses.dataclass(frozen=True)
class AutoscalerPolicy:
    """Declarative autoscaling policy.

    Args:
        min_ranks / max_ranks: inclusive world-size bounds.
        grow_after: consecutive breached observations before growing.
        shrink_after: consecutive calm observations before shrinking
            (larger than ``grow_after`` by default — up fast, down slow).
        cooldown_s: hold time after any resize, regardless of the signal.
        step: ranks added/removed per decision.
    """

    min_ranks: int = 1
    max_ranks: int = 8
    grow_after: int = 2
    shrink_after: int = 6
    cooldown_s: float = 30.0
    step: int = 1

    def __post_init__(self) -> None:
        if not 1 <= int(self.min_ranks) <= int(self.max_ranks):
            raise ValueError(
                f"need 1 <= min_ranks <= max_ranks, got {self.min_ranks}/{self.max_ranks}"
            )
        if int(self.grow_after) < 1 or int(self.shrink_after) < 1:
            raise ValueError(
                f"grow_after/shrink_after must be >= 1, got "
                f"{self.grow_after}/{self.shrink_after}"
            )
        if not self.cooldown_s >= 0:
            raise ValueError(f"cooldown_s must be >= 0, got {self.cooldown_s}")
        if int(self.step) < 1:
            raise ValueError(f"step must be >= 1, got {self.step}")


class Autoscaler:
    """Burn-rate signal -> world-size decisions (module docstring).

    Args:
        engine: the :class:`~tpumetrics.telemetry.slo.SloEngine` whose
            breach latches drive the decisions (``None`` = always calm).
        policy: the :class:`AutoscalerPolicy` hysteresis knobs.
        clock: monotonic-seconds source (injectable for tests).
    """

    def __init__(
        self,
        engine: Any = None,
        policy: AutoscalerPolicy = AutoscalerPolicy(),
        *,
        clock: Any = time.monotonic,
    ) -> None:
        self.engine = engine
        self.policy = policy
        self._clock = clock
        self._breach_streak = 0
        self._calm_streak = 0
        self._last_action_at: Optional[float] = None
        self.decisions: Dict[str, int] = {"grow": 0, "shrink": 0, "hold": 0}

    def observe(
        self, world: int, now: Optional[float] = None
    ) -> Tuple[str, int]:
        """Fold one observation of the SLO signal into the streaks and
        decide: ``("grow" | "shrink" | "hold", target_world)``.  The
        caller (the fleet controller) performs the resize; this only
        decides."""
        now = self._clock() if now is None else now
        breached = bool(self.engine.breached()) if self.engine is not None else False
        if breached:
            self._breach_streak += 1
            self._calm_streak = 0
        else:
            self._calm_streak += 1
            self._breach_streak = 0
        cooling = (
            self._last_action_at is not None
            and now - self._last_action_at < self.policy.cooldown_s
        )
        decision, target = "hold", int(world)
        if not cooling:
            if (
                breached
                and self._breach_streak >= self.policy.grow_after
                and world < self.policy.max_ranks
            ):
                decision = "grow"
                target = min(world + self.policy.step, self.policy.max_ranks)
            elif (
                not breached
                and self._calm_streak >= self.policy.shrink_after
                and world > self.policy.min_ranks
            ):
                decision = "shrink"
                target = max(world - self.policy.step, self.policy.min_ranks)
        if decision != "hold":
            self._last_action_at = now
            self._breach_streak = 0
            self._calm_streak = 0
        self.decisions[decision] += 1
        if _instruments.enabled():
            _DECISIONS_TOTAL.inc(1, decision)
        return decision, target

    def stats(self) -> Dict[str, Any]:
        return {
            "breach_streak": self._breach_streak,
            "calm_streak": self._calm_streak,
            "cooling": (
                self._last_action_at is not None
                and self._clock() - self._last_action_at < self.policy.cooldown_s
            ),
            "decisions": dict(self.decisions),
            "min_ranks": self.policy.min_ranks,
            "max_ranks": self.policy.max_ranks,
        }
