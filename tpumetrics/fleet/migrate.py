"""Zero-loss live migration of one tenant between evaluation services.

The state machine (``docs/fleet.md`` draws it) is a two-phase handoff
whose single durable commit point is the handoff manifest:

1. **window** — ``source.begin_migration`` gates the tenant's intake by
   its own backpressure policy and flushes pending batches; the stream
   position is final from here.
2. **cut** — the final state crosses through the atomic snapshot format
   (write-temp -> fsync -> rename, CRC'd, batch count stamped in the
   header) into the :class:`HandoffStore`; a hibernated tenant ships its
   existing spill file verbatim instead — O(1), no revival.
3. **adopt** — the target registers the tenant fresh and places the cut.
   Registration's duplicate check is the exactly-once guard.
4. **commit** — the manifest flips to ``"committed"`` (atomic rename).
   Everything before this point rolls BACK (abort the window, withdraw
   the adoption — loss-free, since no traffic reached the target yet);
   everything after rolls FORWARD (the tenant's home is the target).
5. **re-place** — the routing ring pins the tenant to the target and
   bumps the epoch; the source deregisters, tombstoning the id so gated
   waiters and late submitters get a typed refusal naming the new owner.

A SIGKILL at ANY point leaves the manifest in exactly one state:
``"cut"`` (recover on the source from the cut — the migration never
happened) or ``"committed"`` (recover on the target — it already did).
:func:`recover_handoffs` adopts accordingly, refuses double residency,
and re-pins the ring — the soak's exactly-once gate.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from tpumetrics.lifecycle.store import SpillStore, _safe_dirname
from tpumetrics.resilience import storage as _storage
from tpumetrics.runtime import snapshot as _snapshot
from tpumetrics.telemetry import instruments as _instruments
from tpumetrics.telemetry import ledger as _telemetry
from tpumetrics.utils.exceptions import TPUMetricsUserError

__all__ = [
    "HandoffStore",
    "MigrationError",
    "MigrationReport",
    "TenantMigratingError",
    "migrate_tenant",
    "recover_handoffs",
]

_MIGRATION_HIST = _instruments.histogram(
    _instruments.MIGRATION_LATENCY_MS,
    help="tenant live-migration latency (window -> cut -> adopt -> commit)",
    labels=("stream",),
    sketch=True,
)
_MIGRATIONS_TOTAL = _instruments.counter(
    _instruments.MIGRATIONS_TOTAL,
    help="tenant migrations by outcome",
    labels=("outcome",),
)


class TenantMigratingError(TPUMetricsUserError):
    """The tenant is inside (or past) a migration's final-cut window under
    backpressure policy ``"error"``: the call is refused rather than
    blocked, exactly like a full queue under the same policy.  A refusal
    issued AFTER the commit carries the new placement — ``target_rank``
    and ``routing_epoch`` — so the caller re-reads the routing ring and
    resubmits to the new owner."""

    def __init__(
        self,
        message: str,
        *,
        target_rank: Any = None,
        routing_epoch: Any = None,
    ) -> None:
        super().__init__(message)
        self.target_rank = target_rank
        self.routing_epoch = routing_epoch


class MigrationError(TPUMetricsUserError):
    """A migration step cannot proceed (double residency discovered during
    recovery, an unreadable manifest, a missing rank)."""


@dataclass
class MigrationReport:
    """One migration's outcome (returned by :func:`migrate_tenant` and
    :func:`recover_handoffs`)."""

    tenant: str
    source_rank: Any
    target_rank: Any
    mode: str  # "live" | "spill" | "pristine"
    batches: int
    items: int
    routing_epoch: Any = None
    latency_ms: float = 0.0
    recovered: bool = False
    extra: Dict[str, Any] = field(default_factory=dict)


class HandoffStore:
    """Durable middle ground of a migration: the final cut plus a tiny
    atomic JSON manifest whose ``state`` field IS the commit point.

    Cuts ride a :class:`~tpumetrics.lifecycle.store.SpillStore` under
    ``root/cuts`` (atomic snapshot format, CRC, retention); manifests are
    written temp-then-rename under ``root/manifests`` so a crash can never
    leave a half-written commit record.  ``root=None`` creates a private
    temporary root removed by :meth:`close` — crash recovery across
    processes needs a real directory."""

    def __init__(self, root: Optional[str] = None) -> None:
        self._owned = root is None
        self.root = (
            root if root is not None else tempfile.mkdtemp(prefix="tpumetrics-handoff-")
        )
        self.cuts = SpillStore(os.path.join(self.root, "cuts"), keep=1, seam="migration")
        self._manifests = os.path.join(self.root, "manifests")
        os.makedirs(self._manifests, exist_ok=True)
        self._lock = threading.Lock()

    def _manifest_path(self, tenant_id: str) -> str:
        return os.path.join(self._manifests, _safe_dirname(tenant_id) + ".json")

    def _write_manifest(self, tenant_id: str, data: Dict[str, Any]) -> None:
        path = self._manifest_path(tenant_id)
        # retain the current manifest as the ".prev" sibling BEFORE the
        # rename: a manifest found torn at recovery (a power loss that tore
        # the rename's data out from under the directory entry) then
        # arbitrates from the atomic-rename predecessor — the state machine's
        # previous durable state — instead of being unrecoverable
        prior = None
        try:
            with open(path, "rb") as fh:
                prior = fh.read()
            json.loads(prior.decode())  # never retain an already-torn file
        except (OSError, ValueError):
            prior = None
        if prior is not None:
            _storage.atomic_write(
                self._manifests, path + ".prev",
                lambda fh: fh.write(prior), seam="manifest",
            )
        payload = json.dumps(data, sort_keys=True).encode()
        _storage.atomic_write(
            self._manifests, path, lambda fh: fh.write(payload), seam="manifest",
        )

    def cut(
        self,
        tenant_id: str,
        payload: Any,
        meta: Dict[str, Any],
        *,
        mode: str = "live",
        source_rank: Any = None,
        target_rank: Any = None,
        guard_non_finite: str = "off",
    ) -> str:
        """Persist a live cut + its ``"cut"``-state manifest; returns the
        cut path."""
        path = self.cuts.spill(
            tenant_id, payload, dict(meta), guard_non_finite=guard_non_finite
        )
        self._write_manifest(
            tenant_id,
            {
                "tenant": tenant_id,
                "state": "cut",
                "mode": mode,
                "source_rank": source_rank,
                "target_rank": target_rank,
                "meta": dict(meta),
            },
        )
        return path

    def cut_file(
        self,
        tenant_id: str,
        src_path: Optional[str],
        meta: Dict[str, Any],
        *,
        source_rank: Any = None,
        target_rank: Any = None,
    ) -> Optional[str]:
        """Adopt a hibernated tenant's spill file verbatim as the cut
        (``None`` = pristine: manifest only) + its manifest."""
        path = None
        mode = "pristine"
        if src_path is not None:
            path = self.cuts.adopt_file(tenant_id, src_path)
            mode = "spill"
        self._write_manifest(
            tenant_id,
            {
                "tenant": tenant_id,
                "state": "cut",
                "mode": mode,
                "source_rank": source_rank,
                "target_rank": target_rank,
                "meta": dict(meta),
            },
        )
        return path

    def load(
        self,
        tenant_id: str,
        *,
        template: Any = None,
        annotations: Optional[Dict[str, str]] = None,
    ):
        """Restore the tenant's cut -> ``(payload, header)`` or ``None``."""
        return self.cuts.load(tenant_id, template=template, annotations=annotations)

    def newest_cut_path(self, tenant_id: str) -> Optional[str]:
        return self.cuts.newest_path(tenant_id)

    def _load_manifest(self, path: str) -> Optional[Dict[str, Any]]:
        """One manifest file -> dict, ``None`` when absent.  A TORN manifest
        (truncated JSON — the rename's data lost under the directory entry)
        arbitrates from the retained atomic-rename predecessor: the previous
        durable state of the state machine.  Torn with no predecessor means
        the FIRST write never durably landed — the migration never reached
        its durable phase, i.e. no manifest at all."""

        def _read(p: str) -> Optional[Dict[str, Any]]:
            try:
                with open(p) as fh:
                    return json.load(fh)
            except FileNotFoundError:
                return None

        try:
            return _storage.read_with_retry(lambda: _read(path), seam="manifest", path=path)
        except json.JSONDecodeError as torn:
            try:
                prev = _storage.read_with_retry(
                    lambda: _read(path + ".prev"), seam="manifest", path=path + ".prev"
                )
            except (OSError, json.JSONDecodeError):
                prev = None
            _telemetry.record_event(
                None, "manifest_torn", path=path, error=str(torn),
                arbitrated="prev" if prev is not None else "absent",
            )
            return prev
        except OSError as err:
            raise MigrationError(
                f"Unreadable handoff manifest at {path!r}: {err}"
            ) from err

    def manifest(self, tenant_id: str) -> Optional[Dict[str, Any]]:
        return self._load_manifest(self._manifest_path(tenant_id))

    def mark_committed(self, tenant_id: str) -> None:
        """Flip the manifest to ``"committed"`` — THE durable commit point
        of the migration (atomic rename)."""
        data = self.manifest(tenant_id)
        if data is None:
            raise MigrationError(
                f"No handoff manifest for tenant {tenant_id!r} to commit."
            )
        data["state"] = "committed"
        self._write_manifest(tenant_id, data)

    def pending(self) -> List[Dict[str, Any]]:
        """Every unresolved manifest (sorted by tenant id) — an interrupted
        migration per entry; feed to :func:`recover_handoffs`."""
        out = []
        for name in sorted(os.listdir(self._manifests)):
            if not name.endswith(".json"):
                continue
            data = self._load_manifest(os.path.join(self._manifests, name))
            if data is not None:
                out.append(data)
        return sorted(out, key=lambda m: m.get("tenant", ""))

    def resolve(self, tenant_id: str) -> None:
        """Drop a finished migration's manifest + cut (idempotent)."""
        for path in (self._manifest_path(tenant_id), self._manifest_path(tenant_id) + ".prev"):
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
        self.cuts.discard(tenant_id)

    def close(self) -> None:
        self.cuts.close()
        if self._owned:
            shutil.rmtree(self.root, ignore_errors=True)


def _record(kind: str, tenant_id: str, **extra: Any) -> None:
    with _telemetry.attribution(tenant_id):
        _telemetry.record_event(None, kind, tenant=tenant_id, **extra)


def _adopt_from_cut(
    service: Any,
    tenant_id: str,
    manifest_mode: str,
    meta: Dict[str, Any],
    metric_factory: Callable[[str], Any],
    handoff: HandoffStore,
    register_kw: Optional[Dict[str, Any]],
) -> None:
    """Place one cut on ``service`` (the shared adopt step of the live path
    and crash recovery).  Live cuts load through the durable file — the
    adopted state is byte-for-byte what recovery would restore."""
    metric = metric_factory(tenant_id)
    kw = dict(register_kw or {})
    if manifest_mode == "live":
        if meta.get("mode") == "bucketed":
            got = handoff.load(
                tenant_id,
                template=metric.init_state(),
                annotations=_snapshot.state_annotations(metric),
            )
        else:
            got = handoff.load(tenant_id)
        if got is None:
            raise MigrationError(
                f"Handoff cut for tenant {tenant_id!r} is missing: the "
                "migration cannot be loss-free."
            )
        payload, header = got
        service.adopt_migrated(tenant_id, metric, payload, header["meta"], **kw)
    else:
        path = handoff.newest_cut_path(tenant_id) if manifest_mode == "spill" else None
        if manifest_mode == "spill" and path is None:
            raise MigrationError(
                f"Handoff spill file for tenant {tenant_id!r} is missing."
            )
        service.adopt_hibernated(tenant_id, metric, meta, spill_path=path, **kw)


def migrate_tenant(
    source: Any,
    target: Any,
    tenant_id: str,
    *,
    metric_factory: Callable[[str], Any],
    handoff: HandoffStore,
    source_rank: Any = None,
    target_rank: Any = None,
    ring: Any = None,
    register_kw: Optional[Dict[str, Any]] = None,
) -> MigrationReport:
    """Move one tenant from ``source`` to ``target`` with zero loss (the
    module docstring's state machine).  ``metric_factory(tenant_id)`` must
    build a config-identical metric for the target registration.  Any
    failure before the manifest commits rolls back to the source — window
    aborted, adoption withdrawn, tenant never double-resident; the
    ``tenant_migrate_started/committed/aborted`` ledger events are
    exactly-once per attempt."""
    t0 = time.perf_counter()
    _record(
        "tenant_migrate_started", tenant_id,
        source_rank=source_rank, target_rank=target_rank,
    )
    adopted = False
    try:
        mode, cut, meta = source.begin_migration(tenant_id)
        if mode == "live":
            handoff.cut(
                tenant_id, cut, meta,
                mode=mode, source_rank=source_rank, target_rank=target_rank,
            )
        else:
            handoff.cut_file(
                tenant_id, cut, meta,
                source_rank=source_rank, target_rank=target_rank,
            )
        _adopt_from_cut(
            target, tenant_id, mode, meta, metric_factory, handoff, register_kw
        )
        adopted = True
        handoff.mark_committed(tenant_id)
    except BaseException as err:
        source.abort_migration(tenant_id)
        if adopted:
            target.withdraw_adoption(tenant_id)
        handoff.resolve(tenant_id)
        _record(
            "tenant_migrate_aborted", tenant_id,
            source_rank=source_rank, target_rank=target_rank, error=repr(err),
        )
        if _instruments.enabled():
            _MIGRATIONS_TOTAL.inc(1, "aborted")
        raise
    # ---- past the commit point: roll forward only
    epoch = ring.reassign(tenant_id, target_rank) if ring is not None else None
    source.commit_migration(
        tenant_id, target_rank=target_rank, routing_epoch=epoch
    )
    handoff.resolve(tenant_id)
    latency_ms = (time.perf_counter() - t0) * 1e3
    _record(
        "tenant_migrate_committed", tenant_id,
        source_rank=source_rank, target_rank=target_rank, mode=mode,
        batches=int(meta.get("batches", 0)), routing_epoch=epoch,
        latency_ms=round(latency_ms, 3),
    )
    if _instruments.enabled():
        _MIGRATION_HIST.observe(latency_ms, tenant_id)
        _MIGRATIONS_TOTAL.inc(1, "committed")
    return MigrationReport(
        tenant=tenant_id, source_rank=source_rank, target_rank=target_rank,
        mode=mode, batches=int(meta.get("batches", 0)),
        items=int(meta.get("items", 0)), routing_epoch=epoch,
        latency_ms=latency_ms,
    )


def recover_handoffs(
    handoff: HandoffStore,
    services_by_rank: Dict[Any, Any],
    metric_factory: Callable[[str], Any],
    *,
    ring: Any = None,
    register_kw: Optional[Dict[str, Any]] = None,
) -> List[MigrationReport]:
    """Resolve every interrupted migration after a crash: a ``"cut"``
    manifest means the migration never committed — the tenant belongs to
    its SOURCE rank, restored from the final cut; a ``"committed"`` one
    means it already moved — adopt on the TARGET.  Either way the tenant
    ends resident on exactly one rank; finding it already resident on two
    raises :class:`MigrationError` (never silently double-count), and a
    tenant already resident on one rank is left alone (the cut is
    superseded).  Returns one recovered :class:`MigrationReport` per
    manifest."""
    reports: List[MigrationReport] = []
    for manifest in handoff.pending():
        tid = manifest["tenant"]
        meta = manifest.get("meta", {})
        committed = manifest.get("state") == "committed"
        owner_rank = manifest["target_rank"] if committed else manifest["source_rank"]
        present = [
            rank
            for rank, svc in sorted(services_by_rank.items(), key=lambda kv: str(kv[0]))
            if tid in set(svc.tenant_ids())
        ]
        if len(present) > 1:
            raise MigrationError(
                f"Tenant {tid!r} is resident on ranks {present} during handoff "
                "recovery: double residency would double-count its stream."
            )
        if present:
            owner_rank = present[0]  # an earlier recovery / re-registration won
        else:
            if owner_rank not in services_by_rank:
                raise MigrationError(
                    f"Tenant {tid!r} recovers on rank {owner_rank}, which is "
                    "not in the fleet."
                )
            _adopt_from_cut(
                services_by_rank[owner_rank], tid, manifest.get("mode", "live"),
                meta, metric_factory, handoff, register_kw,
            )
        epoch = ring.reassign(tid, owner_rank) if ring is not None else None
        handoff.resolve(tid)
        _record(
            "tenant_migrate_committed" if committed else "tenant_migrate_aborted",
            tid,
            source_rank=manifest.get("source_rank"),
            target_rank=manifest.get("target_rank"),
            recovered=True, owner_rank=owner_rank, routing_epoch=epoch,
        )
        if _instruments.enabled():
            _MIGRATIONS_TOTAL.inc(1, "recovered")
        reports.append(
            MigrationReport(
                tenant=tid, source_rank=manifest.get("source_rank"),
                target_rank=manifest.get("target_rank"),
                mode=manifest.get("mode", "live"),
                batches=int(meta.get("batches", 0)),
                items=int(meta.get("items", 0)),
                routing_epoch=epoch, recovered=True,
                extra={"owner_rank": owner_rank, "committed": committed},
            )
        )
    return reports
