"""Self-scaling fleet of evaluation services behind one routing ring.

:class:`FleetController` owns N :class:`~tpumetrics.runtime.service.
EvaluationService` ranks and the :class:`~tpumetrics.fleet.ring.
ConsistentHashRing` that places tenants on them.  It is the one component
that ties the fleet layers together:

- **placement** — every registration pins its tenant on the ring, so
  topology changes never silently move a tenant: the ONLY way a tenant
  changes rank is an explicit zero-loss migration
  (:func:`~tpumetrics.fleet.migrate.migrate_tenant`), which re-pins at
  commit.
- **routing** — :meth:`submit` / :meth:`compute` read the ring lock-free
  and retry on a *moved* refusal (:class:`~tpumetrics.fleet.migrate.
  TenantMigratingError` with ``target_rank`` set): the refusal itself
  names the new owner, so a bounded re-read converges without any global
  pause.
- **resize** — :meth:`resize` grows by adding ranks and rebalancing
  displaced tenants to their natural owners, or shrinks by migrating
  every tenant off the doomed (highest-numbered) ranks using a *survivor
  ring*, so routing stays answerable at every intermediate step.
- **autoscaling** — :meth:`autoscale_tick` folds the SLO engine's
  burn-rate breach latch through the :class:`~tpumetrics.fleet.
  autoscaler.Autoscaler` hysteresis and applies the decision.
- **federation** — with ``admin_port=``, the embedded admin server's
  ``/statusz`` federation section carries the per-tenant routing census
  (``owner_rank`` / ``routing_epoch`` / ``migrating``), so any reader of
  any rank can answer "who owns tenant T".

Structural operations (migrate / resize / recover) serialize on one
re-entrant lock; the data plane (submit / compute / flush) never takes
it — the ring and each service are independently thread-safe, and the
migration seams inside the service provide the per-tenant ordering.
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from tpumetrics.fleet.autoscaler import Autoscaler
from tpumetrics.fleet.migrate import (
    HandoffStore,
    MigrationReport,
    TenantMigratingError,
    migrate_tenant,
    recover_handoffs,
)
from tpumetrics.fleet.ring import ConsistentHashRing, RingError
from tpumetrics.telemetry import instruments as _instruments
from tpumetrics.utils.exceptions import TPUMetricsUserError

__all__ = ["FleetController"]

_RANKS_GAUGE = _instruments.gauge(
    _instruments.FLEET_RANKS,
    help="evaluation-service ranks in the fleet",
    labels=("fleet",),
)
_EPOCH_GAUGE = _instruments.gauge(
    _instruments.ROUTING_EPOCH,
    help="routing-ring epoch (bumps on every placement change)",
    labels=("fleet",),
)

# bounded retry for the moved-refusal loop: each retry follows a refusal
# that NAMED the new owner, so >1 iteration only happens when the tenant
# migrates again mid-call; a handful covers any sane churn without
# masking a routing livelock
_ROUTE_RETRIES = 8


class FleetController:
    """N evaluation services + one routing ring (module docstring).

    Args:
        metric_factory: ``metric_factory(tenant_id)`` builds the tenant's
            metric — used by auto-registration and migration adoption,
            which must construct a config-identical instance on the
            target rank.
        ranks: initial world size (>= 1).
        register_kw: keyword defaults for every ``register`` call (per-call
            kwargs override).
        service_kw: keyword arguments for every
            :class:`~tpumetrics.runtime.service.EvaluationService` built.
        handoff_dir: durable root for the migration
            :class:`~tpumetrics.fleet.migrate.HandoffStore`; ``None`` uses
            a private temp dir (fine in-process, no cross-process crash
            recovery).
        vnodes: virtual nodes per rank on the ring.
        autoscaler: optional :class:`~tpumetrics.fleet.autoscaler.
            Autoscaler`; built automatically from ``slo`` when omitted.
        slo: optional :class:`~tpumetrics.telemetry.slo.SloEngine` whose
            breach latch drives :meth:`autoscale_tick`.
        admin_port: optional port for an embedded admin server carrying
            the federated routing census (0 = ephemeral).
        name: fleet label on the gauges, service names, and admin server.
    """

    def __init__(
        self,
        metric_factory: Callable[[str], Any],
        *,
        ranks: int = 1,
        register_kw: Optional[Dict[str, Any]] = None,
        service_kw: Optional[Dict[str, Any]] = None,
        handoff_dir: Optional[str] = None,
        vnodes: int = 64,
        autoscaler: Optional[Autoscaler] = None,
        slo: Any = None,
        admin_port: Optional[int] = None,
        name: str = "fleet",
    ) -> None:
        if int(ranks) < 1:
            raise ValueError(f"ranks must be >= 1, got {ranks}")
        self._metric_factory = metric_factory
        self._register_kw = dict(register_kw or {})
        self._service_kw = dict(service_kw or {})
        self._name = str(name)
        self._ring = ConsistentHashRing(vnodes=vnodes)
        self._services: Dict[int, Any] = {}
        self._rank_ids = itertools.count()
        self._struct = threading.RLock()  # migrate / resize / recover
        self._migrating: set = set()
        self._mig_lock = threading.Lock()  # the set above (census readers)
        self.handoff = HandoffStore(handoff_dir)
        self.slo = slo
        self.autoscaler = (
            autoscaler
            if autoscaler is not None
            else (Autoscaler(engine=slo) if slo is not None else None)
        )
        self._closed = False
        for _ in range(int(ranks)):
            self._add_rank_locked()
        self._publish()
        self.admin = None
        if admin_port is not None:
            from tpumetrics.telemetry.federate import local_snapshot
            from tpumetrics.telemetry.serve import start_admin_server

            # ONE snapshot: the instruments registry is process-global, so
            # in-process ranks already share it — emitting a snapshot per
            # rank would double-count every family in the merged view.  The
            # fleet census rides along, giving /statusz its federation
            # section with the per-tenant routing rows.
            self.admin = start_admin_server(
                int(admin_port),
                targets={f"{self._name}-r{r}": s for r, s in self._services.items()},
                slo=slo,
                federation=lambda: [
                    local_snapshot(rank=0, fleet=self.fleet_status())
                ],
                name=self._name,
            )

    # ------------------------------------------------------------- topology

    @property
    def ring(self) -> ConsistentHashRing:
        return self._ring

    @property
    def world(self) -> int:
        return len(self._services)

    @property
    def ranks(self) -> List[int]:
        return sorted(self._services)

    def service(self, rank: int) -> Any:
        try:
            return self._services[rank]
        except KeyError:
            raise RingError(
                f"rank {rank!r} is not in the fleet (ranks: {self.ranks})"
            ) from None

    def _add_rank_locked(self) -> int:
        from tpumetrics.runtime.service import EvaluationService

        rank = next(self._rank_ids)
        svc = EvaluationService(
            name=f"{self._name}-r{rank}", **self._service_kw
        )
        self._services[rank] = svc
        self._ring.add_rank(rank)
        return rank

    def _publish(self) -> None:
        if _instruments.enabled():
            _RANKS_GAUGE.set(len(self._services), self._name)
            _EPOCH_GAUGE.set(self._ring.epoch, self._name)

    def _find_rank(self, tenant_id: str) -> Optional[int]:
        for rank in sorted(self._services):
            if tenant_id in set(self._services[rank].tenant_ids()):
                return rank
        return None

    # ------------------------------------------------------------ data plane

    def register(
        self, tenant_id: str, metric: Any = None, *, rank: Optional[int] = None,
        **kwargs: Any,
    ) -> int:
        """Register a tenant on its ring-assigned rank (or an explicit
        ``rank=``) and PIN the placement — the pin is what guarantees the
        routing answer stays stable across resizes until a migration
        deliberately moves it.  Returns the owning rank."""
        with self._struct:
            have = self._find_rank(tenant_id)
            if have is not None:
                raise TPUMetricsUserError(
                    f"Tenant {tenant_id!r} is already registered on rank "
                    f"{have}; deregister or migrate it instead."
                )
            owner = self._ring.owner(tenant_id)[0] if rank is None else int(rank)
            svc = self.service(owner)
            if metric is None:
                metric = self._metric_factory(tenant_id)
            svc.register(tenant_id, metric, **{**self._register_kw, **kwargs})
            self._ring.reassign(tenant_id, owner)
            self._publish()
            return owner

    def _route(self, tenant_id: str, op: Callable[[Any], Any]) -> Any:
        last: Optional[TenantMigratingError] = None
        for _ in range(_ROUTE_RETRIES):
            rank = self._ring.owner(tenant_id)[0]
            svc = self._services.get(rank)
            if svc is None:
                raise RingError(
                    f"Tenant {tenant_id!r} routes to rank {rank}, which has "
                    f"left the fleet (ranks: {self.ranks})."
                )
            try:
                return op(svc)
            except TenantMigratingError as err:
                if err.target_rank is None:
                    raise  # window refusal under policy "error": caller's call
                last = err  # moved: the ring is already bumped — re-read
        raise TenantMigratingError(
            f"Tenant {tenant_id!r} kept moving across {_ROUTE_RETRIES} "
            "routing reads; giving up rather than spinning.",
            target_rank=last.target_rank if last else None,
            routing_epoch=last.routing_epoch if last else None,
        )

    def submit(self, tenant_id: str, *args: Any) -> None:
        """Submit to the tenant's current owner, transparently following a
        committed migration (a *moved* refusal re-reads the ring)."""
        self._route(tenant_id, lambda svc: svc.submit(tenant_id, *args))

    def compute(self, tenant_id: str) -> Any:
        return self._route(tenant_id, lambda svc: svc.compute(tenant_id))

    def flush(self, tenant_id: Optional[str] = None,
              timeout: Optional[float] = None) -> None:
        if tenant_id is not None:
            self._route(tenant_id, lambda svc: svc.flush(tenant_id, timeout))
            return
        for rank in self.ranks:
            svc = self._services.get(rank)
            if svc is not None:
                svc.flush(None, timeout)

    def tenant_ids(self) -> List[str]:
        out: set = set()
        for svc in list(self._services.values()):
            out.update(svc.tenant_ids())
        return sorted(out)

    # ------------------------------------------------------------ migrations

    def migrate(self, tenant_id: str, target_rank: int) -> Optional[MigrationReport]:
        """Zero-loss migrate one tenant to ``target_rank`` (no-op when it
        already lives there)."""
        with self._struct:
            target = self.service(int(target_rank))
            source_rank = self._find_rank(tenant_id)
            if source_rank is None:
                raise TPUMetricsUserError(
                    f"Tenant {tenant_id!r} is not registered on any rank."
                )
            if source_rank == int(target_rank):
                return None
            with self._mig_lock:
                self._migrating.add(tenant_id)
            try:
                report = migrate_tenant(
                    self._services[source_rank], target, tenant_id,
                    metric_factory=self._metric_factory,
                    handoff=self.handoff,
                    source_rank=source_rank, target_rank=int(target_rank),
                    ring=self._ring, register_kw=self._register_kw,
                )
            finally:
                with self._mig_lock:
                    self._migrating.discard(tenant_id)
            self._publish()
            return report

    def resize(self, n: int) -> List[MigrationReport]:
        """Grow or shrink the pool to ``n`` ranks, migrating every
        displaced tenant with the same zero-loss handoff as
        :meth:`migrate`.  Shrink retires the highest-numbered ranks and
        routes their tenants via a *survivor ring*, so the live ring stays
        valid at every intermediate step."""
        if int(n) < 1:
            raise ValueError(f"resize target must be >= 1, got {n}")
        reports: List[MigrationReport] = []
        with self._struct:
            current = self.ranks
            if int(n) == len(current):
                return reports
            if int(n) > len(current):
                for _ in range(int(n) - len(current)):
                    self._add_rank_locked()
                # rebalance: a grown ring changes natural placement; pins
                # keep routing stable, so deliberately move each displaced
                # tenant to its new natural owner
                for tid in self.tenant_ids():
                    natural = self._ring.natural_owner(tid)
                    if natural != self._ring.owner(tid)[0]:
                        report = self.migrate(tid, natural)
                        if report is not None:
                            reports.append(report)
            else:
                survivors = current[: int(n)]
                doomed = current[int(n):]
                placed = ConsistentHashRing(
                    survivors, vnodes=self._ring.vnodes
                )
                for rank in doomed:
                    for tid in sorted(self._services[rank].tenant_ids()):
                        report = self.migrate(tid, placed.owner(tid)[0])
                        if report is not None:
                            reports.append(report)
                for rank in doomed:
                    self._ring.remove_rank(rank)
                    svc = self._services.pop(rank)
                    if self.admin is not None:
                        self.admin.remove_target(f"{self._name}-r{rank}")
                    svc.close(drain=True)
            if self.admin is not None:
                for rank, svc in self._services.items():
                    self.admin.add_target(f"{self._name}-r{rank}", svc)
            self._publish()
        return reports

    def recover(self) -> List[MigrationReport]:
        """Resolve interrupted migrations left in the handoff store by a
        crash (:func:`~tpumetrics.fleet.migrate.recover_handoffs`): each
        tenant ends resident on exactly one rank — the source when the cut
        never committed, the target when it did."""
        with self._struct:
            reports = recover_handoffs(
                self.handoff, dict(self._services), self._metric_factory,
                ring=self._ring, register_kw=self._register_kw,
            )
            self._publish()
            return reports

    # ----------------------------------------------------------- autoscaling

    def autoscale_tick(
        self, now: Optional[float] = None
    ) -> Tuple[str, int, List[MigrationReport]]:
        """One autoscaling observation: tick the SLO engine, fold the
        breach latch through the hysteresis, and apply the decision.
        Returns ``(decision, world_after, migration_reports)``."""
        if self.autoscaler is None:
            raise TPUMetricsUserError(
                "autoscale_tick needs an autoscaler (pass autoscaler= or slo=)."
            )
        if self.slo is not None:
            self.slo.tick(now)
        with self._struct:
            decision, target = self.autoscaler.observe(self.world, now)
            reports = self.resize(target) if decision != "hold" else []
            return decision, self.world, reports

    # ------------------------------------------------------------- federation

    def census(self) -> Dict[str, Dict[str, Any]]:
        """Per-tenant routing rows: ``{tid: {"owner_rank", "routing_epoch",
        "migrating"}}``."""
        with self._mig_lock:
            migrating = set(self._migrating)
        return self._ring.census(self.tenant_ids(), migrating=migrating)

    def fleet_status(self) -> Dict[str, Any]:
        """The fleet section of the federated ``/statusz``: ring epoch,
        membership, the per-tenant census, and the autoscaler's posture."""
        out: Dict[str, Any] = {
            "name": self._name,
            "routing_epoch": self._ring.epoch,
            "world": self.world,
            "ranks": self.ranks,
            "tenants": self.census(),
        }
        if self.autoscaler is not None:
            out["autoscaler"] = self.autoscaler.stats()
        return out

    # -------------------------------------------------------------- shutdown

    def close(self, drain: bool = True) -> None:
        """Stop the admin plane, close every rank (draining by default),
        and release the fleet's gauges (idempotent)."""
        with self._struct:
            if self._closed:
                return
            self._closed = True
            if self.admin is not None:
                self.admin.close()
            for rank in self.ranks:
                self._services.pop(rank).close(drain=drain)
            self.handoff.close()
            if _instruments.enabled():
                _RANKS_GAUGE.remove(self._name)
                _EPOCH_GAUGE.remove(self._name)

    def __enter__(self) -> "FleetController":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close(drain=all(e is None for e in exc))
