"""KernelInceptionDistance (counterpart of reference ``image/kid.py``)."""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from tpumetrics.image.fid import _adopt_backbone, _resolve_feature_extractor
from tpumetrics.metric import Metric
from tpumetrics.utils.data import dim_zero_cat

Array = jax.Array


def poly_kernel(f1: Array, f2: Array, degree: int = 3, gamma: Optional[float] = None, coef: float = 1.0) -> Array:
    """Polynomial kernel (reference kid.py:53-57)."""
    if gamma is None:
        gamma = 1.0 / f1.shape[1]
    return (jnp.matmul(f1, f2.T, precision=jax.lax.Precision.HIGHEST) * gamma + coef) ** degree


def _np_poly_mmd(
    f_real: "np.ndarray", f_fake: "np.ndarray", degree: int = 3, gamma: Optional[float] = None, coef: float = 1.0
) -> float:
    """Host float64 unbiased polynomial-kernel MMD (compute-time path)."""
    if gamma is None:
        gamma = 1.0 / f_real.shape[1]
    k_11 = (f_real @ f_real.T * gamma + coef) ** degree
    k_22 = (f_fake @ f_fake.T * gamma + coef) ** degree
    k_12 = (f_real @ f_fake.T * gamma + coef) ** degree
    m = k_11.shape[0]
    value = ((k_11.sum() - np.trace(k_11)) + (k_22.sum() - np.trace(k_22))) / (m * (m - 1))
    return float(value - 2 * k_12.sum() / (m**2))


def poly_mmd(
    f_real: Array, f_fake: Array, degree: int = 3, gamma: Optional[float] = None, coef: float = 1.0
) -> Array:
    """Unbiased polynomial-kernel MMD (reference kid.py:60-72)."""
    k_11 = poly_kernel(f_real, f_real, degree, gamma, coef)
    k_22 = poly_kernel(f_fake, f_fake, degree, gamma, coef)
    k_12 = poly_kernel(f_real, f_fake, degree, gamma, coef)

    m = k_11.shape[0]
    diag_x = jnp.diagonal(k_11)
    diag_y = jnp.diagonal(k_22)

    kt_xx_sums = k_11.sum(axis=-1) - diag_x
    kt_yy_sums = k_22.sum(axis=-1) - diag_y
    k_xy_sums = k_12.sum(axis=0)

    value = (kt_xx_sums.sum() + kt_yy_sums.sum()) / (m * (m - 1))
    value -= 2 * k_xy_sums.sum() / (m**2)
    return value


class KernelInceptionDistance(Metric):
    """KID: mean/std of unbiased polynomial MMD over random feature subsets
    (reference kid.py:74-280).

    Args:
        feature: callable image→(N, D) extractor, or gated int (see FID).
        subsets / subset_size: subset sampling configuration.
        degree / gamma / coef: polynomial kernel parameters.
        seed: subset-sampling seed (TPU extension; the reference draws from
            the global torch RNG).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> from tpumetrics.image import KernelInceptionDistance
        >>> extract = lambda imgs: imgs.reshape(imgs.shape[0], -1)[:, :8].astype(jnp.float32)
        >>> kid = KernelInceptionDistance(feature=extract, subsets=3, subset_size=8)
        >>> real = jax.random.randint(jax.random.PRNGKey(0), (16, 3, 8, 8), 0, 255)
        >>> fake = jax.random.randint(jax.random.PRNGKey(1), (16, 3, 8, 8), 0, 255)
        >>> kid.update(real, real=True)
        >>> kid.update(fake, real=False)
        >>> kid_mean, kid_std = kid.compute()
        >>> bool(jnp.isfinite(kid_mean))
        True
    """

    is_differentiable: bool = False
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    def __init__(
        self,
        feature: Union[int, str, Callable] = 2048,
        subsets: int = 100,
        subset_size: int = 1000,
        degree: int = 3,
        gamma: Optional[float] = None,
        coef: float = 1.0,
        reset_real_features: bool = True,
        normalize: bool = False,
        seed: Optional[int] = None,
        feature_extractor_weights_path: Optional[str] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.inception, _ = _resolve_feature_extractor(
            feature, type(self).__name__, feature_extractor_weights_path, acquire=True
        )
        _adopt_backbone(self, self.inception)

        if not (isinstance(subsets, int) and subsets > 0):
            raise ValueError("Argument `subsets` expected to be integer larger than 0")
        self.subsets = subsets
        if not (isinstance(subset_size, int) and subset_size > 0):
            raise ValueError("Argument `subset_size` expected to be integer larger than 0")
        self.subset_size = subset_size
        if not (isinstance(degree, int) and degree > 0):
            raise ValueError("Argument `degree` expected to be integer larger than 0")
        self.degree = degree
        if gamma is not None and not (isinstance(gamma, float) and gamma > 0):
            raise ValueError("Argument `gamma` expected to be `None` or float larger than 0")
        self.gamma = gamma
        if not (isinstance(coef, float) and coef > 0):
            raise ValueError("Argument `coef` expected to be float larger than 0")
        self.coef = coef
        if not isinstance(reset_real_features, bool):
            raise ValueError("Argument `reset_real_features` expected to be a bool")
        self.reset_real_features = reset_real_features
        if not isinstance(normalize, bool):
            raise ValueError("Argument `normalize` expected to be a bool")
        self.normalize = normalize
        self._rng = np.random.default_rng(seed)

        self.add_state("real_features", default=[], dist_reduce_fx=None)
        self.add_state("fake_features", default=[], dist_reduce_fx=None)

    def update(self, imgs: Array, real: bool) -> None:
        """Extract and store features (reference kid.py:240-252)."""
        imgs = (imgs * 255).astype(jnp.uint8) if self.normalize else imgs
        features = jnp.asarray(self.inception(imgs), jnp.float32)
        if real:
            self.real_features.append(features)
        else:
            self.fake_features.append(features)

    def compute(self) -> Tuple[Array, Array]:
        """Subset-sampled MMD mean/std (reference kid.py:254-280).

        The cubed polynomial kernel of raw feature magnitudes overflows fp32
        precision, so — like the reference's double-precision states — the
        compute-time MMD runs on host in float64."""
        real_features = np.asarray(dim_zero_cat(self.real_features), np.float64)
        fake_features = np.asarray(dim_zero_cat(self.fake_features), np.float64)
        if real_features.shape[0] < self.subset_size or fake_features.shape[0] < self.subset_size:
            raise ValueError("Argument `subset_size` should be smaller than the number of samples")

        kid_scores = []
        for _ in range(self.subsets):
            perm = self._rng.permutation(real_features.shape[0])[: self.subset_size]
            f_real = real_features[perm]
            perm = self._rng.permutation(fake_features.shape[0])[: self.subset_size]
            f_fake = fake_features[perm]
            kid_scores.append(_np_poly_mmd(f_real, f_fake, self.degree, self.gamma, self.coef))
        kid_scores_arr = np.asarray(kid_scores)
        return jnp.asarray(kid_scores_arr.mean(), jnp.float32), jnp.asarray(kid_scores_arr.std(), jnp.float32)

    def reset(self) -> None:
        if not self.reset_real_features:
            real = self.real_features
            super().reset()
            self.real_features = real
        else:
            super().reset()
