"""PerceptualPathLength (counterpart of reference
``image/perceptual_path_length.py`` / ``functional/image/perceptual_path_length.py``)."""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from tpumetrics.functional.image.lpips import learned_perceptual_image_patch_similarity
from tpumetrics.metric import Metric

Array = jax.Array


def _interpolate(
    latents1: Array, latents2: Array, epsilon: Union[float, Array], interpolation_method: str
) -> Array:
    """Lerp/slerp the fraction-``epsilon`` point on the latents1→latents2 path
    (reference functional/perceptual_path_length.py); ``epsilon`` may be a
    per-sample (B, 1) array."""
    eps = epsilon
    if interpolation_method == "lerp":
        return latents1 + (latents2 - latents1) * eps
    if interpolation_method in ("slerp_any", "slerp_unit"):
        ndims = tuple(range(1, latents1.ndim))
        unit1 = latents1 / jnp.linalg.norm(latents1, axis=ndims, keepdims=True)
        unit2 = latents2 / jnp.linalg.norm(latents2, axis=ndims, keepdims=True)
        cos = jnp.sum(unit1 * unit2, axis=ndims, keepdims=True)
        omega = jnp.arccos(jnp.clip(cos, -1 + 1e-7, 1 - 1e-7))
        so = jnp.sin(omega)
        res = (jnp.sin((1.0 - eps) * omega) / so) * latents1 + (jnp.sin(eps * omega) / so) * latents2
        if interpolation_method == "slerp_unit":
            res = res / jnp.linalg.norm(res, axis=ndims, keepdims=True)
        return res
    raise ValueError(f"Interpolation method {interpolation_method} not supported.")


def perceptual_path_length(
    generator: Callable[[Array], Array],
    num_samples: int = 10_000,
    conditional: bool = False,
    batch_size: int = 64,
    interpolation_method: str = "lerp",
    epsilon: float = 1e-4,
    resize: Optional[int] = 64,
    lower_discard: Optional[float] = 0.01,
    upper_discard: Optional[float] = 0.99,
    sim_net: Optional[Union[str, Callable]] = None,
    latent_dim: int = 128,
    key: Optional[Array] = None,
    backbone_params: Optional[Sequence] = None,
) -> Tuple[Array, Array, Array]:
    """PPL (Karras et al. 2019): LPIPS distance between images generated from
    epsilon-separated latents, scaled by 1/eps², with percentile discarding.

    ``generator`` maps latent batches to image batches; ``sim_net`` is the
    perceptual backbone — a callable feature stack, or one of
    ``"alex"``/``"vgg"``/``"squeeze"`` with the offline-converted conv
    weights passed as ``backbone_params`` (resolved through the shared
    backbone registry, same as LPIPS itself).

    Returns (mean, std, per-pair distances).
    """
    if sim_net is None:
        raise ModuleNotFoundError(
            "perceptual_path_length requires a perceptual backbone: pass `sim_net` (see"
            " LearnedPerceptualImagePatchSimilarity — the pretrained default is unavailable here)."
        )
    layer_weights = None
    if isinstance(sim_net, str):
        from tpumetrics.functional.image.lpips import resolve_lpips_net

        sim_net, layer_weights = resolve_lpips_net(
            sim_net, backbone_params, None, arg_name="sim_net"
        )
    if conditional:
        raise NotImplementedError(
            "Conditional PPL (sampling labels alongside latents) is not implemented;"
            " evaluate with conditional=False or close over fixed labels in `generator`."
        )
    key = key if key is not None else jax.random.PRNGKey(0)
    distances = []
    num_batches = -(-num_samples // batch_size)  # ceil: sample at least num_samples
    for i in range(num_batches):
        key, k1, k2, k3 = jax.random.split(key, 4)
        z1 = jax.random.normal(k1, (batch_size, latent_dim))
        z2 = jax.random.normal(k2, (batch_size, latent_dim))
        # sample t ~ U[0,1) per path and measure the segment t -> t+epsilon
        # ON the z1→z2 path (Karras et al. 2019), so the latent step is
        # always exactly epsilon of the path
        t = jax.random.uniform(k3, (batch_size,) + (1,) * (z1.ndim - 1))
        z_t = _interpolate(z1, z2, t, interpolation_method)
        z_t_eps = _interpolate(z1, z2, t + epsilon, interpolation_method)
        img1 = generator(z_t)
        img2 = generator(z_t_eps)
        if resize is not None:
            img1 = jax.image.resize(img1, (img1.shape[0], img1.shape[1], resize, resize), "bilinear")
            img2 = jax.image.resize(img2, (img2.shape[0], img2.shape[1], resize, resize), "bilinear")
        per_pair = learned_perceptual_image_patch_similarity(
            img1, img2, sim_net, layer_weights, reduction="none"
        )
        distances.append(per_pair / (epsilon**2))
    dist = jnp.concatenate(distances)[:num_samples]

    if lower_discard is not None or upper_discard is not None:
        lo = jnp.quantile(dist, lower_discard) if lower_discard is not None else -jnp.inf
        hi = jnp.quantile(dist, upper_discard) if upper_discard is not None else jnp.inf
        mask = (dist >= lo) & (dist <= hi)
        kept = jnp.where(mask, dist, 0.0)
        n = jnp.maximum(mask.sum(), 1)
        mean = kept.sum() / n
        std = jnp.sqrt(jnp.where(mask, (dist - mean) ** 2, 0.0).sum() / n)
        return mean, std, dist
    return dist.mean(), dist.std(), dist


class PerceptualPathLength(Metric):
    """PPL as a metric object: ``update`` is a no-op (the generator is
    sampled at compute), mirroring the reference's design where the metric
    owns the sampling loop.

    Example:
        >>> import jax, jax.numpy as jnp
        >>> from tpumetrics.image import PerceptualPathLength
        >>> def generator(z):
        ...     img = jnp.tanh(z[:, :48].reshape(z.shape[0], 3, 4, 4))
        ...     return jnp.repeat(jnp.repeat(img, 4, axis=2), 4, axis=3)
        >>> def sim_net(x):  # toy perceptual feature stack
        ...     return [x[:, :, ::2, ::2], jnp.tanh(x).mean(axis=1, keepdims=True)]
        >>> metric = PerceptualPathLength(num_samples=8, batch_size=8, sim_net=sim_net,
        ...                               resize=None, latent_dim=64)
        >>> metric.update(generator)
        >>> mean, std, dist = metric.compute()
        >>> bool(jnp.isfinite(mean)), dist.shape
        (True, (8,))
    """

    is_differentiable: bool = False
    higher_is_better: bool = False
    full_state_update: bool = False

    def __init__(
        self,
        num_samples: int = 10_000,
        conditional: bool = False,
        batch_size: int = 128,
        interpolation_method: str = "lerp",
        epsilon: float = 1e-4,
        resize: Optional[int] = 64,
        lower_discard: Optional[float] = 0.01,
        upper_discard: Optional[float] = 0.99,
        sim_net: Optional[Union[str, Callable]] = None,
        latent_dim: int = 128,
        backbone_params: Optional[Sequence] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_samples = num_samples
        self.conditional = conditional
        self.batch_size = batch_size
        self.interpolation_method = interpolation_method
        self.epsilon = epsilon
        self.resize = resize
        self.lower_discard = lower_discard
        self.upper_discard = upper_discard
        self.sim_net = sim_net
        self.backbone_params = backbone_params
        if isinstance(sim_net, str):
            from tpumetrics.functional.image.lpips import resolve_lpips_net

            # acquire the shared registry handle up front so this instance
            # owns a reference (released by release_backbones()); compute()
            # re-resolves against the same resident handle
            handle, _ = resolve_lpips_net(
                sim_net, backbone_params, None, arg_name="sim_net", acquire=True
            )
            self._backbone_handles = (handle,)
            self.backbone_key = handle.key
        self.latent_dim = latent_dim
        self._generator: Optional[Callable] = None
        self.add_state("dummy", jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, generator: Callable[[Array], Array]) -> None:
        """Register the generator to be path-sampled at compute."""
        self._generator = generator

    def compute(self) -> Tuple[Array, Array, Array]:
        if self._generator is None:
            raise RuntimeError("No generator registered; call update(generator) first.")
        return perceptual_path_length(
            self._generator,
            num_samples=self.num_samples,
            conditional=self.conditional,
            batch_size=self.batch_size,
            interpolation_method=self.interpolation_method,
            epsilon=self.epsilon,
            resize=self.resize,
            lower_discard=self.lower_discard,
            upper_discard=self.upper_discard,
            sim_net=self.sim_net,
            latent_dim=self.latent_dim,
            backbone_params=self.backbone_params,
        )
