"""FrechetInceptionDistance (counterpart of reference ``image/fid.py:182``)."""

from __future__ import annotations

from typing import Any, Callable, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from tpumetrics.metric import Metric
from tpumetrics.utils.data import _is_tracer

Array = jax.Array


def _resolve_feature_extractor(
    feature: Union[int, str, Callable],
    metric_name: str,
    weights_path: Optional[str] = None,
    *,
    dtype_policy: str = "float32",
    acquire: bool = False,
):
    """Resolve the ``feature`` argument: a callable extractor (any function
    mapping an image batch to (N, D) features — e.g. a jitted Flax apply) is
    used directly; an int/str selects a tap of the FID InceptionV3
    (reference fid.py:30-44 → ``_inception.py``), resolved through the
    process-global backbone registry from converted weights (``weights_path``
    / ``TPUMETRICS_INCEPTION_WEIGHTS``) and raising with the conversion
    recipe when none are available.  ``acquire=True`` makes the caller own a
    registry reference (see :func:`_adopt_backbone`)."""
    if callable(feature):
        return feature, None
    if isinstance(feature, (int, str)):
        from tpumetrics.image._inception import inception_feature_extractor

        handle = inception_feature_extractor(
            feature, weights_path, dtype_policy=dtype_policy, acquire=acquire
        )
        return handle, feature
    raise TypeError("Got unknown input to argument `feature`")


def _adopt_backbone(metric: Metric, extractor: Callable) -> None:
    """Record an acquired :class:`~tpumetrics.backbones.registry.
    BackboneHandle` on ``metric``: the handle joins ``_backbone_handles``
    (released by ``Metric.release_backbones()``) and its registry key becomes
    the public ``backbone_key`` attribute, so the config digest — and with it
    the service share key — separates tenants over different weight sets."""
    if hasattr(extractor, "key") and hasattr(extractor, "close"):
        metric._backbone_handles = getattr(metric, "_backbone_handles", ()) + (extractor,)
        metric.backbone_key = extractor.key


def _tap_num_features(tap: Union[int, str, None]) -> Optional[int]:
    """Feature dimensionality of a named InceptionV3 tap (None for callables)."""
    if tap is None:
        return None
    if isinstance(tap, str) and tap.startswith("logits"):
        from tpumetrics.image._inception import NUM_CLASSES

        return NUM_CLASSES
    return int(tap)


def _compute_fid(mu1: Array, sigma1: Array, mu2: Array, sigma2: Array) -> Array:
    """Fréchet distance via the sqrtm-free eigenvalue identity
    (reference fid.py:159-180): d² = |mu1-mu2|² + tr(s1)+tr(s2) - 2·Σ√eig(s1·s2).

    The nonsymmetric eigendecomposition has no TPU kernel, so it runs on host
    float64 at compute time (the reference equally depends on CPU scipy)."""
    a = jnp.sum((mu1 - mu2) ** 2, axis=-1)
    b = jnp.trace(sigma1) + jnp.trace(sigma2)
    if _is_tracer(sigma1):
        raise NotImplementedError(
            "FID's eigenvalue term has no TPU kernel; call compute() eagerly (outside jit)."
        )
    prod = np.asarray(sigma1, np.float64) @ np.asarray(sigma2, np.float64)
    eigvals = np.linalg.eigvals(prod)
    c = np.sqrt(eigvals.astype(np.complex128)).real.sum()
    return (a + b - 2 * jnp.asarray(c, jnp.float32)).astype(jnp.float32)


class FrechetInceptionDistance(Metric):
    """FID with streaming mean/covariance sum states — constant-memory over
    any number of images, synced with six psums (reference fid.py:314-320).

    Args:
        feature: a callable image→(N, D) feature extractor, or one of
            64/192/768/2048 selecting a tap of the FID InceptionV3
            (reference fid.py:30-44; built from converted weights — see
            ``feature_extractor_weights_path``).
        reset_real_features: whether ``reset()`` clears the real statistics.
        normalize: inputs are [0,1] floats instead of [0,255] bytes.
        num_features: feature dimensionality; inferred from the tap or by
            probing the extractor with a tiny batch when not given.
        feature_extractor_weights_path: ``.npz`` produced by
            ``python -m tpumetrics.image._inception_convert`` from the
            reference's ``pt_inception-2015-12-05`` checkpoint; defaults to
            the ``TPUMETRICS_INCEPTION_WEIGHTS`` environment variable.

    Example:
        >>> import jax, jax.numpy as jnp
        >>> from tpumetrics.image import FrechetInceptionDistance
        >>> extract = lambda imgs: imgs.reshape(imgs.shape[0], -1)[:, :16].astype(jnp.float32)
        >>> fid = FrechetInceptionDistance(feature=extract, num_features=16)
        >>> key1, key2 = jax.random.PRNGKey(0), jax.random.PRNGKey(1)
        >>> real = jax.random.randint(key1, (8, 3, 16, 16), 0, 255)
        >>> fake = jax.random.randint(key2, (8, 3, 16, 16), 0, 255)
        >>> fid.update(real, real=True)
        >>> fid.update(fake, real=False)
        >>> float(fid.compute()) >= 0
        True
    """

    is_differentiable: bool = False
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    def __init__(
        self,
        feature: Union[int, str, Callable] = 2048,
        reset_real_features: bool = True,
        normalize: bool = False,
        num_features: Optional[int] = None,
        feature_extractor_weights_path: Optional[str] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.inception, tap = _resolve_feature_extractor(
            feature, type(self).__name__, feature_extractor_weights_path, acquire=True
        )
        _adopt_backbone(self, self.inception)
        if num_features is None:
            num_features = _tap_num_features(tap)
        if num_features is None:
            probe = jnp.zeros((1, 3, 299, 299), jnp.float32)
            num_features = int(np.asarray(self.inception(probe)).shape[-1])
        self.num_features = num_features

        if not isinstance(reset_real_features, bool):
            raise ValueError("Argument `reset_real_features` expected to be a bool")
        self.reset_real_features = reset_real_features
        if not isinstance(normalize, bool):
            raise ValueError("Argument `normalize` expected to be a bool")
        self.normalize = normalize

        self._jit_accum = None  # built lazily; cached across updates
        mx = (num_features, num_features)
        self.add_state("real_features_sum", jnp.zeros(num_features), dist_reduce_fx="sum")
        self.add_state("real_features_cov_sum", jnp.zeros(mx), dist_reduce_fx="sum")
        self.add_state("real_features_num_samples", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("fake_features_sum", jnp.zeros(num_features), dist_reduce_fx="sum")
        self.add_state("fake_features_cov_sum", jnp.zeros(mx), dist_reduce_fx="sum")
        self.add_state("fake_features_num_samples", jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, imgs: Array, real: bool) -> None:
        """Extract features and accumulate first/second moments
        (reference fid.py:322-338).

        Extractor + moment accumulation run as ONE jit call (cached per input
        shape): eagerly each op is a separate dispatch, and on a
        remote-attached accelerator the per-update cost is round trips, not
        FLOPs.  A user extractor that cannot be traced (host/numpy-based)
        falls back to the eager path with a one-time warning."""
        if self._jit_accum is None:
            inception, normalize = self.inception, self.normalize

            def accum(feat_sum, cov_sum, n, imgs):
                x = (imgs * 255).astype(jnp.uint8) if normalize else imgs
                f = jnp.asarray(inception(x), jnp.float32)
                if f.ndim == 1:
                    f = f[None]
                return feat_sum + f.sum(axis=0), cov_sum + f.T @ f, n + imgs.shape[0]

            from tpumetrics.utils.jit_fallback import JitWithEagerFallback

            self._jit_accum = JitWithEagerFallback(
                accum, f"The `feature` extractor of {type(self).__name__}"
            )
        prefix = "real" if real else "fake"
        states = tuple(getattr(self, f"{prefix}_features_{s}") for s in ("sum", "cov_sum", "num_samples"))
        out = self._jit_accum(*states, imgs)
        for s, val in zip(("sum", "cov_sum", "num_samples"), out):
            setattr(self, f"{prefix}_features_{s}", val)

    def __getstate__(self):
        state = super().__getstate__()
        state.pop("_jit_accum", None)  # compiled fn, unpicklable; rebuilt lazily
        return state

    def __setstate__(self, state):
        super().__setstate__(state)
        self._jit_accum = None

    def compute(self) -> Array:
        """FID from the accumulated moments (reference fid.py:340-351)."""
        if bool(self.real_features_num_samples < 2) or bool(self.fake_features_num_samples < 2):
            raise RuntimeError("More than one sample is required for both the real and fake distributed to compute FID")
        mean_real = self.real_features_sum / self.real_features_num_samples
        mean_fake = self.fake_features_sum / self.fake_features_num_samples
        cov_real = (self.real_features_cov_sum - self.real_features_num_samples * jnp.outer(mean_real, mean_real)) / (
            self.real_features_num_samples - 1
        )
        cov_fake = (self.fake_features_cov_sum - self.fake_features_num_samples * jnp.outer(mean_fake, mean_fake)) / (
            self.fake_features_num_samples - 1
        )
        return _compute_fid(mean_real, cov_real, mean_fake, cov_fake)

    def reset(self) -> None:
        """Optionally keep the (expensive) real statistics (reference fid.py:353-366)."""
        if not self.reset_real_features:
            real_sum = self.real_features_sum
            real_cov = self.real_features_cov_sum
            real_n = self.real_features_num_samples
            super().reset()
            self.real_features_sum = real_sum
            self.real_features_cov_sum = real_cov
            self.real_features_num_samples = real_n
        else:
            super().reset()
