"""MemorizationInformedFrechetInceptionDistance (counterpart of reference
``image/mifid.py``)."""

from __future__ import annotations

from typing import Any, Callable, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from tpumetrics.image.fid import _adopt_backbone, _compute_fid, _resolve_feature_extractor
from tpumetrics.metric import Metric
from tpumetrics.utils.data import dim_zero_cat

Array = jax.Array


def _compute_cosine_distance(features1: Array, features2: Array, cosine_distance_eps: float = 0.1) -> Array:
    """Mean minimum cosine distance, thresholded (reference mifid.py:36-47)."""
    features1 = features1[jnp.asarray(np.sum(np.asarray(features1), axis=1) != 0)]
    features2 = features2[jnp.asarray(np.sum(np.asarray(features2), axis=1) != 0)]
    norm_f1 = features1 / jnp.linalg.norm(features1, axis=1, keepdims=True)
    norm_f2 = features2 / jnp.linalg.norm(features2, axis=1, keepdims=True)
    d = 1.0 - jnp.abs(jnp.matmul(norm_f1, norm_f2.T, precision=jax.lax.Precision.HIGHEST))
    mean_min_d = jnp.mean(d.min(axis=1))
    return jnp.where(mean_min_d < cosine_distance_eps, mean_min_d, jnp.ones_like(mean_min_d))


def _mifid_compute(
    mu1: Array,
    sigma1: Array,
    features1: Array,
    mu2: Array,
    sigma2: Array,
    features2: Array,
    cosine_distance_eps: float = 0.1,
) -> Array:
    """FID weighted by the memorization distance (reference mifid.py:50-63)."""
    fid_value = _compute_fid(mu1, sigma1, mu2, sigma2)
    distance = _compute_cosine_distance(features1, features2, cosine_distance_eps)
    return jnp.where(fid_value > 1e-8, fid_value / (distance + 1e-14), jnp.zeros_like(fid_value))


class MemorizationInformedFrechetInceptionDistance(Metric):
    """MiFID = FID / memorization distance: penalizes generators that copy
    the training set (reference mifid.py:66-250).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> from tpumetrics.image import MemorizationInformedFrechetInceptionDistance
        >>> extract = lambda imgs: imgs.reshape(imgs.shape[0], -1)[:, :8].astype(jnp.float32)
        >>> mifid = MemorizationInformedFrechetInceptionDistance(feature=extract)
        >>> real = jax.random.randint(jax.random.PRNGKey(0), (8, 3, 8, 8), 0, 255)
        >>> fake = jax.random.randint(jax.random.PRNGKey(1), (8, 3, 8, 8), 0, 255)
        >>> mifid.update(real, real=True)
        >>> mifid.update(fake, real=False)
        >>> float(mifid.compute()) >= 0
        True
    """

    is_differentiable: bool = False
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    def __init__(
        self,
        feature: Union[int, str, Callable] = 2048,
        reset_real_features: bool = True,
        normalize: bool = False,
        cosine_distance_eps: float = 0.1,
        feature_extractor_weights_path: Optional[str] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.inception, _ = _resolve_feature_extractor(
            feature, type(self).__name__, feature_extractor_weights_path, acquire=True
        )
        _adopt_backbone(self, self.inception)
        if not isinstance(reset_real_features, bool):
            raise ValueError("Argument `reset_real_features` expected to be a bool")
        self.reset_real_features = reset_real_features
        if not isinstance(normalize, bool):
            raise ValueError("Argument `normalize` expected to be a bool")
        self.normalize = normalize
        if not (isinstance(cosine_distance_eps, float) and 1 >= cosine_distance_eps > 0):
            raise ValueError("Argument `cosine_distance_eps` expected to be a float greater than 0 and less than 1")
        self.cosine_distance_eps = cosine_distance_eps

        self.add_state("real_features", default=[], dist_reduce_fx=None)
        self.add_state("fake_features", default=[], dist_reduce_fx=None)

    def update(self, imgs: Array, real: bool) -> None:
        """Extract and store features (reference mifid.py:219-227)."""
        imgs = (imgs * 255).astype(jnp.uint8) if self.normalize else imgs
        features = jnp.asarray(self.inception(imgs), jnp.float32)
        if features.ndim == 1:
            features = features[None]
        if real:
            self.real_features.append(features)
        else:
            self.fake_features.append(features)

    def compute(self) -> Array:
        """MiFID over all stored features (reference mifid.py:229-243)."""
        real_features = dim_zero_cat(self.real_features)
        fake_features = dim_zero_cat(self.fake_features)
        mean_real, mean_fake = real_features.mean(axis=0), fake_features.mean(axis=0)
        cov_real = jnp.cov(real_features.T)
        cov_fake = jnp.cov(fake_features.T)
        return _mifid_compute(
            mean_real, cov_real, real_features, mean_fake, cov_fake, fake_features, self.cosine_distance_eps
        )

    def reset(self) -> None:
        if not self.reset_real_features:
            real = self.real_features
            super().reset()
            self.real_features = real
        else:
            super().reset()
