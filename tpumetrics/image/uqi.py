"""UniversalImageQualityIndex (counterpart of reference ``image/uqi.py``)."""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp

from tpumetrics.functional.image.uqi import _uqi_compute, _uqi_update
from tpumetrics.metric import Metric
from tpumetrics.utils.data import dim_zero_cat

Array = jax.Array


class UniversalImageQualityIndex(Metric):
    """UQI accumulated over batches (reference uqi.py:33-153).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> from tpumetrics.image import UniversalImageQualityIndex
        >>> preds = jax.random.uniform(jax.random.PRNGKey(0), (16, 1, 16, 16))
        >>> target = preds * 0.75
        >>> uqi = UniversalImageQualityIndex()
        >>> round(float(uqi(preds, target)), 2)
        0.92
    """

    is_differentiable: bool = True
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        kernel_size: Sequence[int] = (11, 11),
        sigma: Sequence[float] = (1.5, 1.5),
        reduction: Optional[str] = "elementwise_mean",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if reduction in ("elementwise_mean", "sum"):
            self.add_state("sum_uqi", jnp.zeros(()), dist_reduce_fx="sum")
            self.add_state("numel", jnp.zeros(()), dist_reduce_fx="sum")
        else:
            self.add_state("preds", default=[], dist_reduce_fx="cat")
            self.add_state("target", default=[], dist_reduce_fx="cat")
        self.kernel_size = kernel_size
        self.sigma = sigma
        self.reduction = reduction

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate UQI sums (or raw images for reduction='none')."""
        preds, target = _uqi_update(preds, target)
        if self.reduction in ("elementwise_mean", "sum"):
            uqi_map = _uqi_compute(preds, target, self.kernel_size, self.sigma, reduction="none")
            self.sum_uqi = self.sum_uqi + uqi_map.sum()
            self.numel = self.numel + uqi_map.size
        else:
            self.preds.append(preds)
            self.target.append(target)

    def compute(self) -> Array:
        if self.reduction == "elementwise_mean":
            return self.sum_uqi / self.numel
        if self.reduction == "sum":
            return self.sum_uqi
        return _uqi_compute(
            dim_zero_cat(self.preds), dim_zero_cat(self.target), self.kernel_size, self.sigma, self.reduction
        )
