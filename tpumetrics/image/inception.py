"""InceptionScore (counterpart of reference ``image/inception.py``)."""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from tpumetrics.image.fid import _adopt_backbone, _resolve_feature_extractor
from tpumetrics.metric import Metric
from tpumetrics.utils.data import dim_zero_cat

Array = jax.Array


class InceptionScore(Metric):
    """IS: exp of the mean split-KL between conditional and marginal class
    distributions of a classifier's logits (reference inception.py:36-201).

    Args:
        feature: callable image→(N, num_classes) logits extractor, or a
            gated int for the pretrained InceptionV3 (see FID).
        splits: number of splits for the mean/std estimate.
        seed: feature-shuffling seed (TPU extension).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> from tpumetrics.image import InceptionScore
        >>> logits = lambda imgs: imgs.reshape(imgs.shape[0], -1)[:, :10].astype(jnp.float32)
        >>> inception = InceptionScore(feature=logits, splits=2)
        >>> imgs = jax.random.randint(jax.random.PRNGKey(0), (16, 3, 8, 8), 0, 255)
        >>> inception.update(imgs)
        >>> score_mean, score_std = inception.compute()
        >>> bool(score_mean >= 1.0)
        True
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    def __init__(
        self,
        feature: Union[int, str, Callable] = "logits_unbiased",
        splits: int = 10,
        normalize: bool = False,
        seed: Optional[int] = None,
        feature_extractor_weights_path: Optional[str] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.inception, _ = _resolve_feature_extractor(
            feature, type(self).__name__, feature_extractor_weights_path, acquire=True
        )
        _adopt_backbone(self, self.inception)
        if not isinstance(normalize, bool):
            raise ValueError("Argument `normalize` expected to be a bool")
        self.normalize = normalize
        self.splits = splits
        self._rng = np.random.default_rng(seed)
        self.add_state("features", default=[], dist_reduce_fx=None)

    def update(self, imgs: Array) -> None:
        """Extract and store classifier logits (reference inception.py:144-148)."""
        imgs = (imgs * 255).astype(jnp.uint8) if self.normalize else imgs
        features = jnp.asarray(self.inception(imgs), jnp.float32)
        self.features.append(features)

    def compute(self) -> Tuple[Array, Array]:
        """exp(KL) per split, mean/std over splits (reference inception.py:150-170)."""
        features = dim_zero_cat(self.features)
        idx = jnp.asarray(self._rng.permutation(features.shape[0]))
        features = features[idx]

        prob = jax.nn.softmax(features, axis=1)
        log_prob = jax.nn.log_softmax(features, axis=1)

        # torch.chunk semantics: chunk size ceil(n/splits) yields at most
        # `splits` chunks, all non-empty — array_split would emit empty
        # chunks (and NaN means) when n < splits
        n = int(prob.shape[0])
        chunk = -(-n // self.splits) if n else 1
        bounds = list(range(0, n, chunk)) or [0]
        prob_chunks = [prob[i : i + chunk] for i in bounds]
        log_prob_chunks = [log_prob[i : i + chunk] for i in bounds]

        kl_list = []
        for p, log_p in zip(prob_chunks, log_prob_chunks):
            mean_prob = p.mean(axis=0, keepdims=True)
            # p == 0 contributes 0 to the KL; the raw expression is
            # 0 * log(0) = NaN when a class prob underflows
            kl = jnp.where(p > 0, p * (log_p - jnp.log(mean_prob)), 0.0)
            kl_list.append(jnp.exp(kl.sum(axis=1).mean()))
        kl_arr = jnp.stack(kl_list)
        return kl_arr.mean(), kl_arr.std()
