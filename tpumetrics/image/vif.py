"""VisualInformationFidelity (counterpart of reference ``image/vif.py``)."""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from tpumetrics.functional.image.vif import visual_information_fidelity
from tpumetrics.metric import Metric

Array = jax.Array


class VisualInformationFidelity(Metric):
    """Pixel-based VIF accumulated over batches (reference vif.py:26-86).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> from tpumetrics.image import VisualInformationFidelity
        >>> preds = jax.random.uniform(jax.random.PRNGKey(41), (8, 3, 41, 41))
        >>> target = jax.random.uniform(jax.random.PRNGKey(42), (8, 3, 41, 41))
        >>> vif = VisualInformationFidelity()
        >>> float(vif(preds, target)) > 0
        True
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False
    plot_lower_bound: float = 0.0

    def __init__(self, sigma_n_sq: float = 2.0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(sigma_n_sq, (float, int)) or sigma_n_sq < 0:
            raise ValueError(f"Argument `sigma_n_sq` is expected to be a positive float or int, but got {sigma_n_sq}")
        self.add_state("vif_score", default=jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.zeros(()), dist_reduce_fx="sum")
        self.sigma_n_sq = sigma_n_sq

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate per-batch VIF sums."""
        batch_vif = visual_information_fidelity(preds, target, self.sigma_n_sq)
        self.vif_score = self.vif_score + batch_vif * preds.shape[0]
        self.total = self.total + preds.shape[0]

    def compute(self) -> Array:
        return self.vif_score / self.total
