"""SpectralDistortionIndex (counterpart of reference ``image/d_lambda.py``)."""

from __future__ import annotations

from typing import Any, List, Optional

import jax

from tpumetrics.functional.image.d_lambda import (
    _spectral_distortion_index_compute,
    _spectral_distortion_index_update,
)
from tpumetrics.metric import Metric
from tpumetrics.utils.data import dim_zero_cat

Array = jax.Array


class SpectralDistortionIndex(Metric):
    """D_lambda pan-sharpening distortion, accumulated over batches
    (reference d_lambda.py:33-146).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> from tpumetrics.image import SpectralDistortionIndex
        >>> preds = jax.random.uniform(jax.random.PRNGKey(42), (16, 3, 16, 16))
        >>> target = preds * 0.75
        >>> sdi = SpectralDistortionIndex()
        >>> float(sdi(preds, target)) < 0.2
        True
    """

    higher_is_better: bool = True
    is_differentiable: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    preds: List[Array]
    target: List[Array]

    def __init__(self, p: int = 1, reduction: Optional[str] = "elementwise_mean", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(p, int) or p <= 0:
            raise ValueError(f"Expected `p` to be a positive integer. Got p: {p}.")
        self.p = p
        allowed_reductions = ("elementwise_mean", "sum", "none")
        if reduction not in allowed_reductions:
            raise ValueError(f"Expected argument `reduction` be one of {allowed_reductions} but got {reduction}")
        self.reduction = reduction
        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.add_state("target", default=[], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        """Append image batches."""
        preds, target = _spectral_distortion_index_update(preds, target)
        self.preds.append(preds)
        self.target.append(target)

    def compute(self) -> Array:
        return _spectral_distortion_index_compute(
            dim_zero_cat(self.preds), dim_zero_cat(self.target), self.p, self.reduction
        )
