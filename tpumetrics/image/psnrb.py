"""PeakSignalNoiseRatioWithBlockedEffect (counterpart of reference ``image/psnrb.py``)."""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from tpumetrics.functional.image.psnrb import _psnrb_compute, _psnrb_update
from tpumetrics.metric import Metric

Array = jax.Array


class PeakSignalNoiseRatioWithBlockedEffect(Metric):
    """PSNR with a blockiness penalty, for grayscale images (reference psnrb.py:33-136).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> from tpumetrics.image import PeakSignalNoiseRatioWithBlockedEffect
        >>> metric = PeakSignalNoiseRatioWithBlockedEffect()
        >>> preds = jax.random.uniform(jax.random.PRNGKey(0), (2, 1, 16, 16))
        >>> target = jax.random.uniform(jax.random.PRNGKey(1), (2, 1, 16, 16))
        >>> float(metric(preds, target)) > 0
        True
    """

    is_differentiable: bool = True
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    def __init__(self, block_size: int = 8, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(block_size, int) or block_size < 1:
            raise ValueError("Argument `block_size` should be a positive integer")
        self.block_size = block_size
        self.add_state("sum_squared_error", default=jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("bef", default=jnp.zeros(()), dist_reduce_fx="sum")
        # reduce identity for max (tpulint TPL301); first update overwrites it
        self.add_state("data_range", default=jnp.asarray(-jnp.inf), dist_reduce_fx="max")

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate squared error, blocked effect, and observed range."""
        preds = jnp.asarray(preds, jnp.float32)
        target = jnp.asarray(target, jnp.float32)
        sum_squared_error, bef, num_obs = _psnrb_update(preds, target, block_size=self.block_size)
        self.sum_squared_error = self.sum_squared_error + sum_squared_error
        self.bef = self.bef + bef
        self.total = self.total + num_obs
        self.data_range = jnp.maximum(self.data_range, target.max() - target.min())

    def compute(self) -> Array:
        return _psnrb_compute(self.sum_squared_error, self.bef, self.total, self.data_range)
