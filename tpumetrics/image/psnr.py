"""PeakSignalNoiseRatio (counterpart of reference ``image/psnr.py``)."""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from tpumetrics.functional.image.psnr import _psnr_compute, _psnr_update
from tpumetrics.metric import Metric
from tpumetrics.utils.data import dim_zero_cat

Array = jax.Array


class PeakSignalNoiseRatio(Metric):
    """PSNR accumulated over batches (reference psnr.py:33-154).

    Args:
        data_range: value range of the input; ``None`` tracks the observed
            target min/max (only valid with ``dim=None``), a tuple clamps
            inputs into the range.
        base: logarithm base.
        reduction: reduction over per-``dim`` scores.
        dim: dimensions to compute PSNR over; ``None`` means global.

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.image import PeakSignalNoiseRatio
        >>> psnr = PeakSignalNoiseRatio(data_range=3.0)
        >>> preds = jnp.asarray([[0.0, 1.0], [2.0, 3.0]])
        >>> target = jnp.asarray([[3.0, 2.0], [1.0, 0.0]])
        >>> round(float(psnr(preds, target)), 3)
        2.553
    """

    is_differentiable: bool = True
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    def __init__(
        self,
        data_range: Optional[Union[float, Tuple[float, float]]] = None,
        base: float = 10.0,
        reduction: Optional[str] = "elementwise_mean",
        dim: Optional[Union[int, Tuple[int, ...]]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if dim is None and reduction != "elementwise_mean":
            from tpumetrics.utils.prints import rank_zero_warn

            rank_zero_warn(f"The `reduction={reduction}` will not have any effect when `dim` is None.")

        if dim is None:
            self.add_state("sum_squared_error", default=jnp.zeros(()), dist_reduce_fx="sum")
            self.add_state("total", default=jnp.zeros(()), dist_reduce_fx="sum")
        else:
            self.add_state("sum_squared_error", default=[], dist_reduce_fx="cat")
            self.add_state("total", default=[], dist_reduce_fx="cat")

        self.clamping_fn = None
        if data_range is None:
            if dim is not None:
                raise ValueError("The `data_range` must be given when `dim` is not None.")
            self.data_range = None
            # reduce-identity defaults (tpulint TPL301): a rank that never
            # updated must not drag the tracked range toward 0 in the fold.
            # Deliberate reference divergence: torchmetrics' zero defaults
            # anchor the tracked range at 0, so data not spanning 0 (e.g.
            # targets in [10, 255]) gets range max-0 there and max-min here
            self.add_state("min_target", default=jnp.asarray(jnp.inf), dist_reduce_fx="min")
            self.add_state("max_target", default=jnp.asarray(-jnp.inf), dist_reduce_fx="max")
        elif isinstance(data_range, tuple):
            self.add_state("data_range", default=jnp.asarray(data_range[1] - data_range[0]), dist_reduce_fx="mean")
            self.clamping_fn = lambda x: jnp.clip(x, data_range[0], data_range[1])
        else:
            self.add_state("data_range", default=jnp.asarray(float(data_range)), dist_reduce_fx="mean")
        self.base = base
        self.reduction = reduction
        self.dim = tuple(dim) if isinstance(dim, Sequence) else dim

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate squared error and (when untracked) the data range."""
        preds = jnp.asarray(preds, jnp.float32)
        target = jnp.asarray(target, jnp.float32)
        if self.clamping_fn is not None:
            preds = self.clamping_fn(preds)
            target = self.clamping_fn(target)

        sum_squared_error, num_obs = _psnr_update(preds, target, dim=self.dim)
        if self.dim is None:
            if self.data_range is None:
                self.min_target = jnp.minimum(target.min(), self.min_target)
                self.max_target = jnp.maximum(target.max(), self.max_target)
            self.sum_squared_error = self.sum_squared_error + sum_squared_error
            self.total = self.total + num_obs
        else:
            self.sum_squared_error.append(sum_squared_error)
            self.total.append(num_obs)

    def compute(self) -> Array:
        data_range = self.data_range if self.data_range is not None else (self.max_target - self.min_target)
        if self.dim is None:
            sum_squared_error = self.sum_squared_error
            total = self.total
        else:
            sum_squared_error = dim_zero_cat(self.sum_squared_error)
            total = dim_zero_cat(self.total)
        return _psnr_compute(sum_squared_error, total, data_range, base=self.base, reduction=self.reduction)
