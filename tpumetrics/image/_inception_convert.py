"""Offline converter: FID InceptionV3 torch checkpoint → ``.npz`` params.

The reference downloads torch-fidelity's ``pt_inception-2015-12-05-6726825d.pth``
(reference image/fid.py:30-44 → torch_fidelity feature extractor).  In an
environment that has that file and torch, run::

    python -m tpumetrics.image._inception_convert pt_inception-2015-12-05-6726825d.pth inception.npz

and point ``FrechetInceptionDistance(feature=2048,
feature_extractor_weights_path="inception.npz")`` (or the
``TPUMETRICS_INCEPTION_WEIGHTS`` env var) at the result.  Only the parameter
names the forward needs are kept; aux-classifier entries and BN
``num_batches_tracked`` counters are dropped.
"""

from __future__ import annotations

import sys
from typing import Dict, Mapping

import numpy as np

from tpumetrics.image._inception import check_inception_params, inception_param_spec


def convert_state_dict(state_dict: Mapping[str, "np.ndarray"]) -> Dict[str, np.ndarray]:
    """Select + validate the reference checkpoint entries for our forward.

    Accepts either raw tensors or numpy arrays as values; returns float32
    numpy arrays keyed exactly as ``inception_param_spec()``.
    """
    spec = inception_param_spec()
    out: Dict[str, np.ndarray] = {}
    for key in spec:
        src = key
        if src not in state_dict:
            # torch-fidelity prefixes nothing, but torchvision-style dumps may
            # carry a leading "base." or module prefix — try a dot-boundary
            # suffix match, skipping aux-classifier twins (AuxLogits.fc.*)
            candidates = [
                k for k in state_dict if k.endswith("." + src) and ".AuxLogits." not in "." + k
            ]
            if len(candidates) != 1:
                raise KeyError(
                    f"Checkpoint is missing parameter `{key}` (no unique suffix match);"
                    " expected a torch-fidelity FeatureExtractorInceptionV3 state_dict"
                )
            src = candidates[0]
        val = state_dict[src]
        if hasattr(val, "detach"):  # torch tensor without importing torch here
            val = val.detach().cpu().numpy()
        out[key] = np.asarray(val, np.float32)
    check_inception_params(out)
    return out


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 2:
        print(__doc__)
        return 2
    src, dst = argv
    import torch

    state_dict = torch.load(src, map_location="cpu")
    if isinstance(state_dict, dict) and "state_dict" in state_dict:
        state_dict = state_dict["state_dict"]
    params = convert_state_dict(state_dict)
    np.savez(dst, **params)
    print(f"wrote {len(params)} arrays to {dst}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
