"""Image metric domain (counterpart of reference ``image/__init__.py``)."""

from tpumetrics.image.d_lambda import SpectralDistortionIndex
from tpumetrics.image.fid import FrechetInceptionDistance
from tpumetrics.image.inception import InceptionScore
from tpumetrics.image.kid import KernelInceptionDistance
from tpumetrics.image.lpip import LearnedPerceptualImagePatchSimilarity
from tpumetrics.image.mifid import MemorizationInformedFrechetInceptionDistance
from tpumetrics.image.perceptual_path_length import PerceptualPathLength
from tpumetrics.image.ergas import ErrorRelativeGlobalDimensionlessSynthesis
from tpumetrics.image.psnr import PeakSignalNoiseRatio
from tpumetrics.image.psnrb import PeakSignalNoiseRatioWithBlockedEffect
from tpumetrics.image.rase import RelativeAverageSpectralError
from tpumetrics.image.rmse_sw import RootMeanSquaredErrorUsingSlidingWindow
from tpumetrics.image.sam import SpectralAngleMapper
from tpumetrics.image.ssim import (
    MultiScaleStructuralSimilarityIndexMeasure,
    StructuralSimilarityIndexMeasure,
)
from tpumetrics.image.tv import TotalVariation
from tpumetrics.image.uqi import UniversalImageQualityIndex
from tpumetrics.image.vif import VisualInformationFidelity

__all__ = [
    "ErrorRelativeGlobalDimensionlessSynthesis",
    "FrechetInceptionDistance",
    "InceptionScore",
    "KernelInceptionDistance",
    "LearnedPerceptualImagePatchSimilarity",
    "MemorizationInformedFrechetInceptionDistance",
    "MultiScaleStructuralSimilarityIndexMeasure",
    "PeakSignalNoiseRatio",
    "PeakSignalNoiseRatioWithBlockedEffect",
    "PerceptualPathLength",
    "RelativeAverageSpectralError",
    "RootMeanSquaredErrorUsingSlidingWindow",
    "SpectralAngleMapper",
    "SpectralDistortionIndex",
    "StructuralSimilarityIndexMeasure",
    "TotalVariation",
    "UniversalImageQualityIndex",
    "VisualInformationFidelity",
]
