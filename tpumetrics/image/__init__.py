"""Image metric domain (counterpart of reference ``image/__init__.py``)."""

from tpumetrics.image.d_lambda import SpectralDistortionIndex
from tpumetrics.image.ergas import ErrorRelativeGlobalDimensionlessSynthesis
from tpumetrics.image.psnr import PeakSignalNoiseRatio
from tpumetrics.image.psnrb import PeakSignalNoiseRatioWithBlockedEffect
from tpumetrics.image.rase import RelativeAverageSpectralError
from tpumetrics.image.rmse_sw import RootMeanSquaredErrorUsingSlidingWindow
from tpumetrics.image.sam import SpectralAngleMapper
from tpumetrics.image.ssim import (
    MultiScaleStructuralSimilarityIndexMeasure,
    StructuralSimilarityIndexMeasure,
)
from tpumetrics.image.tv import TotalVariation
from tpumetrics.image.uqi import UniversalImageQualityIndex
from tpumetrics.image.vif import VisualInformationFidelity

__all__ = [
    "ErrorRelativeGlobalDimensionlessSynthesis",
    "MultiScaleStructuralSimilarityIndexMeasure",
    "PeakSignalNoiseRatio",
    "PeakSignalNoiseRatioWithBlockedEffect",
    "RelativeAverageSpectralError",
    "RootMeanSquaredErrorUsingSlidingWindow",
    "SpectralAngleMapper",
    "SpectralDistortionIndex",
    "StructuralSimilarityIndexMeasure",
    "TotalVariation",
    "UniversalImageQualityIndex",
    "VisualInformationFidelity",
]
