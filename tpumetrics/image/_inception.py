"""FID InceptionV3 feature extractor as a pure-jax forward function.

The reference's default feature extractor for FID/KID/IS/MiFID is
``NoTrainInceptionV3`` (reference ``image/fid.py:30-44,45-157``), a wrapper
around torch-fidelity's ``FeatureExtractorInceptionV3`` — the TF-ported
"pt_inception-2015-12-05" network whose exact quirks define the metric:

- the **TF1-compatible bilinear resize** to 299×299 with ``align_corners=False``
  semantics (``src = dst * in/out``, *no* half-pixel offset — reference
  fid.py:32,83-88; FID values are famously sensitive to exactly this resize);
- ``(x - 128) / 128`` input scaling from uint8;
- torchvision's InceptionV3 topology with the FID deviations: the pooling
  branches of the A/C/E blocks use ``count_include_pad=False`` average
  pooling, and ``Mixed_7c`` (E_2) uses a **max** pool branch;
- feature taps at ``64`` / ``192`` / ``768`` / ``2048`` / ``logits_unbiased``
  / ``logits`` (1008 classes), reference fid.py:90-151.

Pretrained weights cannot be downloaded in an offline environment, so the
forward takes its parameters as data (same pattern as the LPIPS backbones in
``_backbones.py``): a flat ``{torch_state_dict_key: array}`` mapping that a
user converts offline from the reference's checkpoint with::

    python -m tpumetrics.image._inception_convert pt_inception-2015-12-05-6726825d.pth inception.npz

Everything is jit-compatible: static conv plans, ``lax`` pooling windows, no
data-dependent control flow.  On TPU the convs land on the MXU.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

Array = jax.Array

INPUT_IMAGE_SIZE = 299
NUM_CLASSES = 1008
VALID_INT_FEATURES = (64, 192, 768, 2048)
VALID_STR_FEATURES = ("logits_unbiased", "logits")
_BN_EPS = 1e-3


# ------------------------------------------------------------ architecture
# every BasicConv2d as (name, in_ch, out_ch, (kh, kw), stride, (ph, pw));
# block topology mirrors torch-fidelity's FeatureExtractorInceptionV3


def _inception_a(name: str, in_ch: int, pool_features: int):
    return [
        (f"{name}.branch1x1", in_ch, 64, (1, 1), 1, (0, 0)),
        (f"{name}.branch5x5_1", in_ch, 48, (1, 1), 1, (0, 0)),
        (f"{name}.branch5x5_2", 48, 64, (5, 5), 1, (2, 2)),
        (f"{name}.branch3x3dbl_1", in_ch, 64, (1, 1), 1, (0, 0)),
        (f"{name}.branch3x3dbl_2", 64, 96, (3, 3), 1, (1, 1)),
        (f"{name}.branch3x3dbl_3", 96, 96, (3, 3), 1, (1, 1)),
        (f"{name}.branch_pool", in_ch, pool_features, (1, 1), 1, (0, 0)),
    ]


def _inception_b(name: str, in_ch: int):
    return [
        (f"{name}.branch3x3", in_ch, 384, (3, 3), 2, (0, 0)),
        (f"{name}.branch3x3dbl_1", in_ch, 64, (1, 1), 1, (0, 0)),
        (f"{name}.branch3x3dbl_2", 64, 96, (3, 3), 1, (1, 1)),
        (f"{name}.branch3x3dbl_3", 96, 96, (3, 3), 2, (0, 0)),
    ]


def _inception_c(name: str, in_ch: int, c7: int):
    return [
        (f"{name}.branch1x1", in_ch, 192, (1, 1), 1, (0, 0)),
        (f"{name}.branch7x7_1", in_ch, c7, (1, 1), 1, (0, 0)),
        (f"{name}.branch7x7_2", c7, c7, (1, 7), 1, (0, 3)),
        (f"{name}.branch7x7_3", c7, 192, (7, 1), 1, (3, 0)),
        (f"{name}.branch7x7dbl_1", in_ch, c7, (1, 1), 1, (0, 0)),
        (f"{name}.branch7x7dbl_2", c7, c7, (7, 1), 1, (3, 0)),
        (f"{name}.branch7x7dbl_3", c7, c7, (1, 7), 1, (0, 3)),
        (f"{name}.branch7x7dbl_4", c7, c7, (7, 1), 1, (3, 0)),
        (f"{name}.branch7x7dbl_5", c7, 192, (1, 7), 1, (0, 3)),
        (f"{name}.branch_pool", in_ch, 192, (1, 1), 1, (0, 0)),
    ]


def _inception_d(name: str, in_ch: int):
    return [
        (f"{name}.branch3x3_1", in_ch, 192, (1, 1), 1, (0, 0)),
        (f"{name}.branch3x3_2", 192, 320, (3, 3), 2, (0, 0)),
        (f"{name}.branch7x7x3_1", in_ch, 192, (1, 1), 1, (0, 0)),
        (f"{name}.branch7x7x3_2", 192, 192, (1, 7), 1, (0, 3)),
        (f"{name}.branch7x7x3_3", 192, 192, (7, 1), 1, (3, 0)),
        (f"{name}.branch7x7x3_4", 192, 192, (3, 3), 2, (0, 0)),
    ]


def _inception_e(name: str, in_ch: int):
    return [
        (f"{name}.branch1x1", in_ch, 320, (1, 1), 1, (0, 0)),
        (f"{name}.branch3x3_1", in_ch, 384, (1, 1), 1, (0, 0)),
        (f"{name}.branch3x3_2a", 384, 384, (1, 3), 1, (0, 1)),
        (f"{name}.branch3x3_2b", 384, 384, (3, 1), 1, (1, 0)),
        (f"{name}.branch3x3dbl_1", in_ch, 448, (1, 1), 1, (0, 0)),
        (f"{name}.branch3x3dbl_2", 448, 384, (3, 3), 1, (1, 1)),
        (f"{name}.branch3x3dbl_3a", 384, 384, (1, 3), 1, (0, 1)),
        (f"{name}.branch3x3dbl_3b", 384, 384, (3, 1), 1, (1, 0)),
        (f"{name}.branch_pool", in_ch, 192, (1, 1), 1, (0, 0)),
    ]


_CONV_SPECS: List[Tuple[str, int, int, Tuple[int, int], int, Tuple[int, int]]] = [
    ("Conv2d_1a_3x3", 3, 32, (3, 3), 2, (0, 0)),
    ("Conv2d_2a_3x3", 32, 32, (3, 3), 1, (0, 0)),
    ("Conv2d_2b_3x3", 32, 64, (3, 3), 1, (1, 1)),
    ("Conv2d_3b_1x1", 64, 80, (1, 1), 1, (0, 0)),
    ("Conv2d_4a_3x3", 80, 192, (3, 3), 1, (0, 0)),
    *_inception_a("Mixed_5b", 192, 32),
    *_inception_a("Mixed_5c", 256, 64),
    *_inception_a("Mixed_5d", 288, 64),
    *_inception_b("Mixed_6a", 288),
    *_inception_c("Mixed_6b", 768, 128),
    *_inception_c("Mixed_6c", 768, 160),
    *_inception_c("Mixed_6d", 768, 160),
    *_inception_c("Mixed_6e", 768, 192),
    *_inception_d("Mixed_7a", 768),
    *_inception_e("Mixed_7b", 1280),
    *_inception_e("Mixed_7c", 2048),
]


def inception_param_spec() -> Dict[str, Tuple[int, ...]]:
    """``{torch_state_dict_key: shape}`` for every parameter of the network."""
    spec: Dict[str, Tuple[int, ...]] = {}
    for name, cin, cout, (kh, kw), _stride, _pad in _CONV_SPECS:
        spec[f"{name}.conv.weight"] = (cout, cin, kh, kw)
        spec[f"{name}.bn.weight"] = (cout,)
        spec[f"{name}.bn.bias"] = (cout,)
        spec[f"{name}.bn.running_mean"] = (cout,)
        spec[f"{name}.bn.running_var"] = (cout,)
    spec["fc.weight"] = (NUM_CLASSES, 2048)
    spec["fc.bias"] = (NUM_CLASSES,)
    return spec


def random_inception_params(seed: int = 0) -> Dict[str, np.ndarray]:
    """Random-but-stable parameters (BN stats kept benign so activations stay
    O(1) through the 48-conv stack) — for architecture parity tests."""
    rng = np.random.default_rng(seed)
    params: Dict[str, np.ndarray] = {}
    for key, shape in inception_param_spec().items():
        if key.endswith("conv.weight") or key == "fc.weight":
            fan_in = int(np.prod(shape[1:]))
            params[key] = (rng.standard_normal(shape) / np.sqrt(fan_in)).astype(np.float32)
        elif key.endswith("running_var"):
            params[key] = rng.uniform(0.5, 1.5, shape).astype(np.float32)
        elif key.endswith("bn.weight"):
            params[key] = (1.0 + 0.1 * rng.standard_normal(shape)).astype(np.float32)
        else:  # bn.bias / running_mean / fc.bias
            params[key] = (0.1 * rng.standard_normal(shape)).astype(np.float32)
    return params


def check_inception_params(params: Mapping[str, np.ndarray]) -> None:
    spec = inception_param_spec()
    missing = sorted(set(spec) - set(params))
    if missing:
        raise ValueError(
            f"InceptionV3 parameters are missing {len(missing)} entries, e.g. {missing[:4]};"
            " convert the reference checkpoint with"
            " `python -m tpumetrics.image._inception_convert <pt_inception.pth> <out.npz>`."
        )
    for key, shape in spec.items():
        got = tuple(params[key].shape)
        if got != shape:
            raise ValueError(f"InceptionV3 parameter `{key}` has shape {got}, expected {shape}")


_PARAMS_CACHE: Dict[Tuple[str, float], Dict[str, np.ndarray]] = {}


def load_inception_params(path: str) -> Dict[str, np.ndarray]:
    """Load a converted ``.npz`` parameter file (see ``_inception_convert``).

    Cached per (absolute path, mtime) as HOST numpy arrays — device residency
    belongs to the backbone registry (:mod:`tpumetrics.backbones`), which
    ``device_put``s exactly one copy per (weights, mesh, dtype policy) no
    matter how many FID/KID/IS instances load the same file.  Treat the
    returned mapping as read-only.
    """
    import os

    key = (os.path.abspath(path), os.path.getmtime(path))
    if key in _PARAMS_CACHE:
        return _PARAMS_CACHE[key]
    with np.load(path) as data:
        params = {k: np.asarray(data[k]) for k in data.files}
    check_inception_params(params)
    _PARAMS_CACHE.clear()  # keep at most one weight set cached
    _PARAMS_CACHE[key] = params
    return params


def _inception_weights_key(path: str) -> str:
    """Registry weights-identity for a converted checkpoint file: hashing the
    (absolute path, mtime) pair stands in for digesting the ~95 MB tree."""
    import hashlib
    import os

    return hashlib.sha1(
        f"{os.path.abspath(path)}:{os.path.getmtime(path)}".encode()
    ).hexdigest()


# ---------------------------------------------------------------- kernels


def tf1_bilinear_resize(x: Array, size: Tuple[int, int]) -> Array:
    """TF1 ``resize_bilinear(align_corners=False)`` on NCHW input.

    Source coordinate is ``dst * (in / out)`` — the legacy TF1 projection with
    no half-pixel offset (what torch-fidelity's
    ``interpolate_bilinear_2d_like_tensorflow1x`` replicates and FID scores
    depend on, reference fid.py:83-88).  Gather + lerp per axis; fully
    jit/TPU-compatible (static index tables).
    """
    out_h, out_w = size
    _, _, in_h, in_w = x.shape
    dtype = x.dtype if jnp.issubdtype(x.dtype, jnp.floating) else jnp.float32

    def axis_tables(in_size: int, out_size: int):
        scale = in_size / out_size
        src = jnp.arange(out_size, dtype=dtype) * scale
        lo = jnp.floor(src).astype(jnp.int32)
        lo = jnp.clip(lo, 0, in_size - 1)
        hi = jnp.minimum(lo + 1, in_size - 1)
        frac = src - lo.astype(dtype)
        return lo, hi, frac

    h_lo, h_hi, h_frac = axis_tables(in_h, out_h)
    w_lo, w_hi, w_frac = axis_tables(in_w, out_w)

    x = x.astype(dtype)
    top = x[:, :, h_lo, :]
    bottom = x[:, :, h_hi, :]
    rows = top + (bottom - top) * h_frac[None, None, :, None]
    left = rows[:, :, :, w_lo]
    right = rows[:, :, :, w_hi]
    return left + (right - left) * w_frac[None, None, None, :]


def _avgpool3_no_pad_count(x: Array) -> Array:
    """torch ``avg_pool2d(kernel=3, stride=1, padding=1, count_include_pad=False)``
    — the FID-variant pooling in the A/C/E_1 blocks."""
    summed = lax.reduce_window(
        x, 0.0, lax.add, (1, 1, 3, 3), (1, 1, 1, 1), [(0, 0), (0, 0), (1, 1), (1, 1)]
    )
    ones = jnp.ones((1, 1) + x.shape[2:], x.dtype)
    counts = lax.reduce_window(
        ones, 0.0, lax.add, (1, 1, 3, 3), (1, 1, 1, 1), [(0, 0), (0, 0), (1, 1), (1, 1)]
    )
    return summed / counts


def _maxpool3(x: Array, stride: int, padding: int = 0) -> Array:
    return lax.reduce_window(
        x,
        -jnp.inf,
        lax.max,
        (1, 1, 3, 3),
        (1, 1, stride, stride),
        [(0, 0), (0, 0), (padding, padding), (padding, padding)],
    )


def _global_avgpool(x: Array) -> Array:
    return jnp.mean(x, axis=(2, 3))


class _Net:
    """Bound parameters + per-BasicConv2d fused conv→BN→relu application."""

    def __init__(self, params: Mapping[str, Array]):
        self.p = params
        self.spec = {name: (k, s, pad) for name, _ci, _co, k, s, pad in _CONV_SPECS}

    def conv(self, x: Array, name: str) -> Array:
        kernel, stride, (ph, pw) = self.spec[name]
        w = self.p[f"{name}.conv.weight"]
        if getattr(w, "dtype", None) != x.dtype:
            # legacy direct callers only — registry-placed params arrive
            # pre-cast, keeping the program free of fp32 constants under bf16
            w = jnp.asarray(w, x.dtype)
        out = lax.conv_general_dilated(
            x, w, (stride, stride), [(ph, ph), (pw, pw)], dimension_numbers=("NCHW", "OIHW", "NCHW")
        )
        # inference BN folded to scale/shift (eps matches torch BN default for
        # inception: 0.001)
        gamma = self.p[f"{name}.bn.weight"]
        beta = self.p[f"{name}.bn.bias"]
        mean = self.p[f"{name}.bn.running_mean"]
        var = self.p[f"{name}.bn.running_var"]
        scale = (gamma / jnp.sqrt(var + _BN_EPS)).astype(x.dtype).reshape(1, -1, 1, 1)
        shift = (beta - gamma * mean / jnp.sqrt(var + _BN_EPS)).astype(x.dtype).reshape(1, -1, 1, 1)
        return jax.nn.relu(out * scale + shift)

    def block_a(self, x: Array, name: str) -> Array:
        b1 = self.conv(x, f"{name}.branch1x1")
        b5 = self.conv(self.conv(x, f"{name}.branch5x5_1"), f"{name}.branch5x5_2")
        b3 = self.conv(
            self.conv(self.conv(x, f"{name}.branch3x3dbl_1"), f"{name}.branch3x3dbl_2"),
            f"{name}.branch3x3dbl_3",
        )
        bp = self.conv(_avgpool3_no_pad_count(x), f"{name}.branch_pool")
        return jnp.concatenate([b1, b5, b3, bp], axis=1)

    def block_b(self, x: Array, name: str) -> Array:
        b3 = self.conv(x, f"{name}.branch3x3")
        bd = self.conv(
            self.conv(self.conv(x, f"{name}.branch3x3dbl_1"), f"{name}.branch3x3dbl_2"),
            f"{name}.branch3x3dbl_3",
        )
        bp = _maxpool3(x, stride=2)
        return jnp.concatenate([b3, bd, bp], axis=1)

    def block_c(self, x: Array, name: str) -> Array:
        b1 = self.conv(x, f"{name}.branch1x1")
        b7 = self.conv(
            self.conv(self.conv(x, f"{name}.branch7x7_1"), f"{name}.branch7x7_2"),
            f"{name}.branch7x7_3",
        )
        bd = x
        for i in range(1, 6):
            bd = self.conv(bd, f"{name}.branch7x7dbl_{i}")
        bp = self.conv(_avgpool3_no_pad_count(x), f"{name}.branch_pool")
        return jnp.concatenate([b1, b7, bd, bp], axis=1)

    def block_d(self, x: Array, name: str) -> Array:
        b3 = self.conv(self.conv(x, f"{name}.branch3x3_1"), f"{name}.branch3x3_2")
        b7 = x
        for i in range(1, 5):
            b7 = self.conv(b7, f"{name}.branch7x7x3_{i}")
        bp = _maxpool3(x, stride=2)
        return jnp.concatenate([b3, b7, bp], axis=1)

    def block_e(self, x: Array, name: str, pool: str) -> Array:
        b1 = self.conv(x, f"{name}.branch1x1")
        b3 = self.conv(x, f"{name}.branch3x3_1")
        b3 = jnp.concatenate(
            [self.conv(b3, f"{name}.branch3x3_2a"), self.conv(b3, f"{name}.branch3x3_2b")], axis=1
        )
        bd = self.conv(self.conv(x, f"{name}.branch3x3dbl_1"), f"{name}.branch3x3dbl_2")
        bd = jnp.concatenate(
            [self.conv(bd, f"{name}.branch3x3dbl_3a"), self.conv(bd, f"{name}.branch3x3dbl_3b")], axis=1
        )
        # E_2 (Mixed_7c) uses a max pool where E_1 averages — the TF port's
        # deviation from torchvision that FID features depend on
        pooled = _maxpool3(x, stride=1, padding=1) if pool == "max" else _avgpool3_no_pad_count(x)
        bp = self.conv(pooled, f"{name}.branch_pool")
        return jnp.concatenate([b1, b3, bd, bp], axis=1)


def inception_v3_features(
    params: Mapping[str, Array], features: Sequence[str] = ("2048",)
) -> Callable[[Array], Tuple[Array, ...]]:
    """Build the forward: uint8 NCHW images → tuple of requested feature taps.

    ``features`` entries are the reference's names: "64", "192", "768",
    "2048", "logits_unbiased", "logits" (reference fid.py:90-151).  The
    network is truncated after the deepest requested tap.
    """
    known = tuple(str(f) for f in VALID_INT_FEATURES) + VALID_STR_FEATURES
    for f in features:
        if f not in known:
            raise ValueError(f"InceptionV3 feature must be one of {known}, got {f!r}")
    check_inception_params(params)
    net = _Net(params)
    wanted = list(features)
    depth_order = [str(f) for f in VALID_INT_FEATURES] + list(VALID_STR_FEATURES)
    deepest = max(depth_order.index(f) for f in wanted)

    def forward(x: Array) -> Tuple[Array, ...]:
        if x.ndim != 4 or x.shape[1] != 3:
            raise ValueError(f"Expected (N, 3, H, W) image batch, got shape {tuple(x.shape)}")
        out: Dict[str, Array] = {}
        h = x if jnp.issubdtype(x.dtype, jnp.floating) else x.astype(jnp.float32)
        h = tf1_bilinear_resize(h, (INPUT_IMAGE_SIZE, INPUT_IMAGE_SIZE))
        h = (h - 128.0) / 128.0

        h = net.conv(h, "Conv2d_1a_3x3")
        h = net.conv(h, "Conv2d_2a_3x3")
        h = net.conv(h, "Conv2d_2b_3x3")
        h = _maxpool3(h, stride=2)
        if "64" in wanted:
            out["64"] = _global_avgpool(h)
        if deepest > depth_order.index("64"):
            h = net.conv(h, "Conv2d_3b_1x1")
            h = net.conv(h, "Conv2d_4a_3x3")
            h = _maxpool3(h, stride=2)
            if "192" in wanted:
                out["192"] = _global_avgpool(h)
        if deepest > depth_order.index("192"):
            h = net.block_a(h, "Mixed_5b")
            h = net.block_a(h, "Mixed_5c")
            h = net.block_a(h, "Mixed_5d")
            h = net.block_b(h, "Mixed_6a")
            h = net.block_c(h, "Mixed_6b")
            h = net.block_c(h, "Mixed_6c")
            h = net.block_c(h, "Mixed_6d")
            h = net.block_c(h, "Mixed_6e")
            if "768" in wanted:
                out["768"] = _global_avgpool(h)
        if deepest > depth_order.index("768"):
            h = net.block_d(h, "Mixed_7a")
            h = net.block_e(h, "Mixed_7b", pool="avg")
            h = net.block_e(h, "Mixed_7c", pool="max")
            h = _global_avgpool(h)
            if "2048" in wanted:
                out["2048"] = h
        if deepest > depth_order.index("2048"):
            fc_w, fc_b = params["fc.weight"], params["fc.bias"]
            if getattr(fc_w, "dtype", None) != h.dtype:
                fc_w = jnp.asarray(fc_w, h.dtype)
            if getattr(fc_b, "dtype", None) != h.dtype:
                fc_b = jnp.asarray(fc_b, h.dtype)
            logits = h @ fc_w.T
            if "logits_unbiased" in wanted:
                out["logits_unbiased"] = logits
            if "logits" in wanted:
                out["logits"] = logits + fc_b[None]
        return tuple(out[f] for f in wanted)

    return forward


def inception_feature_extractor(
    feature,
    weights_path: Optional[str] = None,
    *,
    dtype_policy: str = "float32",
    mesh=None,
    acquire: bool = False,
):
    """Resolve an int/str ``feature`` request into a single-tap extractor.

    The converted-weights path comes from ``weights_path`` or the
    ``TPUMETRICS_INCEPTION_WEIGHTS`` environment variable; without one this
    raises with the conversion recipe (the reference equally gates this path
    on torch-fidelity being installed + its checkpoint download,
    reference fid.py:53-58).

    Returns a :class:`~tpumetrics.backbones.registry.BackboneHandle` from the
    process-global registry: FID + KID + IS over the same converted file
    share ONE resident weight set and one compiled forward per tap.  With
    ``acquire=True`` the caller owns a reference and must ``close()`` it
    (the Metric classes route that through ``release_backbones()``).
    """
    import os

    tap = str(feature)
    known = tuple(str(f) for f in VALID_INT_FEATURES) + VALID_STR_FEATURES
    if tap not in known:
        raise ValueError(
            f"Integer/str `feature` must be one of {VALID_INT_FEATURES + VALID_STR_FEATURES}, got {feature!r}"
        )
    path = weights_path or os.environ.get("TPUMETRICS_INCEPTION_WEIGHTS")
    if not path:
        raise ModuleNotFoundError(
            f"feature={feature!r} requests the pretrained FID InceptionV3, whose weights are not"
            " bundled and cannot be downloaded here. Convert the reference checkpoint offline with"
            " `python -m tpumetrics.image._inception_convert pt_inception-2015-12-05-6726825d.pth"
            " inception.npz` and pass feature_extractor_weights_path='inception.npz' (or set"
            " TPUMETRICS_INCEPTION_WEIGHTS). Alternatively pass any callable image→(N, D)"
            " feature extractor."
        )
    from tpumetrics.backbones.registry import get_backbone

    return get_backbone(
        f"inception:{tap}",
        load_inception_params(path),
        key=_inception_weights_key(path),
        dtype_policy=dtype_policy,
        mesh=mesh,
        acquire=acquire,
    )
