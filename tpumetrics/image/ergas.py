"""ErrorRelativeGlobalDimensionlessSynthesis (counterpart of reference ``image/ergas.py``)."""

from __future__ import annotations

from typing import Any, List, Optional

import jax

from tpumetrics.functional.image.ergas import _ergas_compute, _ergas_update
from tpumetrics.metric import Metric
from tpumetrics.utils.data import dim_zero_cat

Array = jax.Array


class ErrorRelativeGlobalDimensionlessSynthesis(Metric):
    """ERGAS accumulated over batches (reference ergas.py:33-133).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> from tpumetrics.image import ErrorRelativeGlobalDimensionlessSynthesis
        >>> preds = jax.random.uniform(jax.random.PRNGKey(42), (16, 1, 16, 16))
        >>> target = preds * 0.75
        >>> ergas = ErrorRelativeGlobalDimensionlessSynthesis()
        >>> bool(150.0 < float(ergas(preds, target)) < 160.0)  # rounds to 154/155 depending on build
        True
    """

    higher_is_better: bool = False
    is_differentiable: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    preds: List[Array]
    target: List[Array]

    def __init__(self, ratio: float = 4, reduction: Optional[str] = "elementwise_mean", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.add_state("target", default=[], dist_reduce_fx="cat")
        self.ratio = ratio
        self.reduction = reduction

    def update(self, preds: Array, target: Array) -> None:
        """Append image batches."""
        preds, target = _ergas_update(preds, target)
        self.preds.append(preds)
        self.target.append(target)

    def compute(self) -> Array:
        return _ergas_compute(dim_zero_cat(self.preds), dim_zero_cat(self.target), self.ratio, self.reduction)
