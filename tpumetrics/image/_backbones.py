"""LPIPS feature backbones as pure-jax forward functions.

The LPIPS metric needs the feature stacks of AlexNet / VGG-16 / SqueezeNet-1.1
sliced at specific ReLUs (reference ``functional/image/lpips.py:66-203``,
itself a port of richzhang/PerceptualSimilarity, BSD-2-Clause).  The
architectures are public; pretrained ImageNet weights cannot be downloaded in
an offline environment, so these forwards take the convolution parameters as
data: a flat list of ``(weight, bias)`` pairs in torch's OIHW layout, which a
user converts offline from torchvision with::

    feats = torchvision.models.alexnet(weights="IMAGENET1K_V1").features
    params = [(m.weight.detach().numpy(), m.bias.detach().numpy())
              for m in feats.modules() if isinstance(m, torch.nn.Conv2d)]

(for SqueezeNet each Fire module contributes its squeeze / expand1x1 /
expand3x3 convs, in that order — i.e. the order ``Conv2d`` modules appear in
``features.modules()``).

Everything here is jit-compatible: fixed conv plans, ``lax`` pooling windows,
no data-dependent control flow.  On TPU the convs land on the MXU.
"""

from __future__ import annotations

from typing import Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

Array = jax.Array
ConvParams = Tuple[Array, Array]

# per-layer feature channels each backbone must emit — the bundled LPIPS
# heads (lpips_head_weights) are trained against exactly these widths
LPIPS_CHANNELS = {
    "alex": [64, 192, 384, 256, 256],
    "vgg": [64, 128, 256, 512, 512],
    "squeeze": [64, 128, 256, 384, 384, 512, 512],
}


def _conv(x: Array, wb: ConvParams, stride: int = 1, padding: int = 0) -> Array:
    w, b = wb
    # params normally enter the trace already in the forward's dtype (the
    # backbone registry casts the whole tree once at placement); the guards
    # only fire for legacy direct callers with host/mismatched params, so a
    # bf16 run no longer carries fp32 constants + per-conv converts
    if getattr(w, "dtype", None) != x.dtype:
        w = jnp.asarray(w, x.dtype)
    if getattr(b, "dtype", None) != x.dtype:
        b = jnp.asarray(b, x.dtype)
    out = lax.conv_general_dilated(
        x,
        w,
        window_strides=(stride, stride),
        padding=[(padding, padding), (padding, padding)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )
    return out + jnp.reshape(b, (1, -1, 1, 1))


def _maxpool(x: Array, kernel: int = 3, stride: int = 2, ceil_mode: bool = False) -> Array:
    """torch ``MaxPool2d(kernel, stride)``; ``ceil_mode`` pads the bottom/right
    edge with -inf so partial windows count (SqueezeNet uses ceil_mode=True)."""
    pads = [(0, 0), (0, 0)]
    for dim in (2, 3):
        size = x.shape[dim]
        if ceil_mode:
            out = -(-(size - kernel) // stride) + 1
            needed = (out - 1) * stride + kernel
            pads.append((0, max(0, needed - size)))
        else:
            pads.append((0, 0))
    return lax.reduce_window(
        x,
        -jnp.inf,
        lax.max,
        window_dimensions=(1, 1, kernel, kernel),
        window_strides=(1, 1, stride, stride),
        padding=pads,
    )


def _check_params(net_type: str, params: Sequence[ConvParams], expected: int) -> None:
    if len(params) != expected:
        raise ValueError(
            f"LPIPS `{net_type}` backbone expects {expected} (weight, bias) conv-parameter pairs"
            f" in torch Conv2d order, got {len(params)}"
        )


def alexnet_features(params: Sequence[ConvParams]) -> Callable[[Array], List[Array]]:
    """AlexNet feature stack sliced at the 5 LPIPS ReLUs (reference lpips.py:104-152)."""
    _check_params("alex", params, 5)

    def forward(x: Array) -> List[Array]:
        outs = []
        h = jax.nn.relu(_conv(x, params[0], stride=4, padding=2))
        outs.append(h)  # relu1 (64)
        h = jax.nn.relu(_conv(_maxpool(h), params[1], padding=2))
        outs.append(h)  # relu2 (192)
        h = jax.nn.relu(_conv(_maxpool(h), params[2], padding=1))
        outs.append(h)  # relu3 (384)
        h = jax.nn.relu(_conv(h, params[3], padding=1))
        outs.append(h)  # relu4 (256)
        h = jax.nn.relu(_conv(h, params[4], padding=1))
        outs.append(h)  # relu5 (256)
        return outs

    return forward


def vgg16_features(params: Sequence[ConvParams]) -> Callable[[Array], List[Array]]:
    """VGG-16 feature stack sliced at relu{1_2,2_2,3_3,4_3,5_3} (reference lpips.py:155-203)."""
    _check_params("vgg", params, 13)
    # conv counts per slice; a maxpool precedes every slice but the first
    blocks = [2, 2, 3, 3, 3]

    def forward(x: Array) -> List[Array]:
        outs = []
        h = x
        idx = 0
        for block_i, n_convs in enumerate(blocks):
            if block_i:
                h = _maxpool(h, kernel=2, stride=2)
            for _ in range(n_convs):
                h = jax.nn.relu(_conv(h, params[idx], padding=1))
                idx += 1
            outs.append(h)
        return outs

    return forward


def squeezenet_features(params: Sequence[ConvParams]) -> Callable[[Array], List[Array]]:
    """SqueezeNet-1.1 feature stack sliced at the 7 LPIPS points (reference lpips.py:66-101).

    ``params``: conv0 then 8 Fire modules x (squeeze, expand1x1, expand3x3) = 25 pairs.
    """
    _check_params("squeeze", params, 25)

    def fire(h: Array, base: int) -> Array:
        s = jax.nn.relu(_conv(h, params[base]))
        e1 = jax.nn.relu(_conv(s, params[base + 1]))
        e3 = jax.nn.relu(_conv(s, params[base + 2], padding=1))
        return jnp.concatenate([e1, e3], axis=1)

    def forward(x: Array) -> List[Array]:
        outs = []
        h = jax.nn.relu(_conv(x, params[0], stride=2))
        outs.append(h)  # relu1 (64)
        h = _maxpool(h, ceil_mode=True)
        h = fire(h, 1)
        h = fire(h, 4)
        outs.append(h)  # relu2 (128)
        h = _maxpool(h, ceil_mode=True)
        h = fire(h, 7)
        h = fire(h, 10)
        outs.append(h)  # relu3 (256)
        h = _maxpool(h, ceil_mode=True)
        h = fire(h, 13)
        outs.append(h)  # relu4 (384)
        h = fire(h, 16)
        outs.append(h)  # relu5 (384)
        h = fire(h, 19)
        outs.append(h)  # relu6 (512)
        h = fire(h, 22)
        outs.append(h)  # relu7 (512)
        return outs

    return forward


_BACKBONE_BUILDERS = {
    "alex": alexnet_features,
    "vgg": vgg16_features,
    "squeeze": squeezenet_features,
}


def lpips_backbone(net_type: str, params: Sequence[ConvParams]) -> Callable[[Array], List[Array]]:
    """Build the named LPIPS backbone forward from converted conv parameters."""
    if net_type not in _BACKBONE_BUILDERS:
        raise ValueError(f"Argument `net_type` must be one of {tuple(_BACKBONE_BUILDERS)}, got {net_type}")
    return _BACKBONE_BUILDERS[net_type](params)
