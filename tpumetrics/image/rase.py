"""RelativeAverageSpectralError (counterpart of reference ``image/rase.py``)."""

from __future__ import annotations

from typing import Any, List

import jax

from tpumetrics.functional.image.rase import relative_average_spectral_error
from tpumetrics.metric import Metric
from tpumetrics.utils.data import dim_zero_cat

Array = jax.Array


class RelativeAverageSpectralError(Metric):
    """RASE accumulated over batches (reference rase.py:30-117).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> from tpumetrics.image import RelativeAverageSpectralError
        >>> preds = jax.random.uniform(jax.random.PRNGKey(42), (4, 3, 16, 16))
        >>> target = preds * 0.75
        >>> rase = RelativeAverageSpectralError()
        >>> float(rase(preds, target)) > 0
        True
    """

    higher_is_better: bool = False
    is_differentiable: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    preds: List[Array]
    target: List[Array]

    def __init__(self, window_size: int = 8, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(window_size, int) or window_size < 1:
            raise ValueError(f"Argument `window_size` is expected to be a positive integer, but got {window_size}")
        self.window_size = window_size
        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.add_state("target", default=[], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        """Append image batches."""
        self.preds.append(preds)
        self.target.append(target)

    def compute(self) -> Array:
        return relative_average_spectral_error(
            dim_zero_cat(self.preds), dim_zero_cat(self.target), self.window_size
        )
