"""RootMeanSquaredErrorUsingSlidingWindow (counterpart of reference ``image/rmse_sw.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from tpumetrics.functional.image.rmse_sw import _rmse_sw_compute, _rmse_sw_update
from tpumetrics.metric import Metric

Array = jax.Array


class RootMeanSquaredErrorUsingSlidingWindow(Metric):
    """Windowed RMSE accumulated over batches (reference rmse_sw.py:26-109).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> from tpumetrics.image import RootMeanSquaredErrorUsingSlidingWindow
        >>> preds = jax.random.uniform(jax.random.PRNGKey(42), (4, 3, 16, 16))
        >>> target = preds * 0.75
        >>> rmse_sw = RootMeanSquaredErrorUsingSlidingWindow()
        >>> float(rmse_sw(preds, target)) > 0
        True
    """

    higher_is_better: bool = False
    is_differentiable: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    def __init__(self, window_size: int = 8, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(window_size, int) and window_size > 0):
            raise ValueError("Argument `window_size` is expected to be a positive integer.")
        self.window_size = window_size
        self.add_state("rmse_val_sum", default=jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total_images", default=jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate windowed-RMSE sums (the map itself is not needed for
        the scalar result, so only the sums are carried; reference keeps the
        map as an unsynced plain attribute, rmse_sw.py:84-89)."""
        rmse_val_sum, _, total = _rmse_sw_update(
            preds, target, self.window_size, rmse_val_sum=None, rmse_map=None, total_images=None
        )
        self.rmse_val_sum = self.rmse_val_sum + rmse_val_sum
        self.total_images = self.total_images + total

    def compute(self) -> Optional[Array]:
        rmse, _ = _rmse_sw_compute(self.rmse_val_sum, jnp.zeros(()), self.total_images)
        return rmse
