"""LearnedPerceptualImagePatchSimilarity (counterpart of reference
``image/lpip.py:40``)."""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from tpumetrics.functional.image.lpips import learned_perceptual_image_patch_similarity
from tpumetrics.metric import Metric

Array = jax.Array


class LearnedPerceptualImagePatchSimilarity(Metric):
    """LPIPS accumulated over batches: sum/total scalar states
    (reference lpip.py:136-137).

    Args:
        net_type: ``"alex"``/``"vgg"``/``"squeeze"`` (pass the offline-converted
            conv weights as ``backbone_params``; the trained LPIPS linear heads
            ship with the package and are applied automatically) or a callable
            feature backbone (image→list of feature maps).
        backbone_params: converted ``(weight, bias)`` conv pairs for a string
            ``net_type`` — see :mod:`tpumetrics.image._backbones` for the
            one-line torchvision conversion recipe.
        layer_weights: optional trained per-layer channel weights (defaults to
            the bundled heads for string ``net_type``).
        reduction: ``mean`` or ``sum`` over accumulated images.
        normalize: inputs are [0,1] instead of [-1,1].

    Example:
        >>> import jax, jax.numpy as jnp
        >>> from tpumetrics.image import LearnedPerceptualImagePatchSimilarity
        >>> def toy_net(x):
        ...     return [x[:, :, ::2, ::2], x.mean(axis=1, keepdims=True)]
        >>> lpips = LearnedPerceptualImagePatchSimilarity(net_type=toy_net)
        >>> img1 = jax.random.uniform(jax.random.PRNGKey(0), (4, 3, 16, 16)) * 2 - 1
        >>> img2 = jax.random.uniform(jax.random.PRNGKey(1), (4, 3, 16, 16)) * 2 - 1
        >>> lpips.update(img1, img2)
        >>> float(lpips.compute()) > 0
        True
    """

    is_differentiable: bool = True
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    def __init__(
        self,
        net_type: Union[str, Callable] = "alex",
        reduction: str = "mean",
        normalize: bool = False,
        layer_weights: Optional[Sequence[Array]] = None,
        backbone_params: Optional[Sequence] = None,
        backbone_dtype_policy: str = "float32",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        from tpumetrics.functional.image.lpips import resolve_lpips_net

        # a string net resolves through the process-global backbone registry
        # (tpumetrics.backbones): this instance owns one refcounted handle to
        # the shared resident weight set — release it via release_backbones()
        net_type, layer_weights = resolve_lpips_net(
            net_type, backbone_params, layer_weights,
            dtype_policy=backbone_dtype_policy, acquire=True,
        )
        self.net = net_type
        self.backbone_dtype_policy = backbone_dtype_policy
        self._backbone_handles = ()
        if hasattr(net_type, "key") and hasattr(net_type, "close"):
            self._backbone_handles = (net_type,)
            # public str attr -> enters config_digest, so tenants over
            # different weight sets never share a service slot
            self.backbone_key = net_type.key
        valid_reduction = ("mean", "sum")
        if reduction not in valid_reduction:
            raise ValueError(f"Argument `reduction` must be one of {valid_reduction}, but got {reduction}")
        self.reduction = reduction
        if not isinstance(normalize, bool):
            raise ValueError(f"Argument `normalize` should be a bool but got {normalize}")
        self.normalize = normalize
        self.layer_weights = layer_weights

        self._jit_loss = None  # built lazily; cached across updates
        self.add_state("sum_scores", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, img1: Array, img2: Array) -> None:
        """Accumulate LPIPS sums (reference lpip.py:139-145).

        The whole update — backbone + normalize/diff/average chain AND the
        state accumulation — is ONE jit call (cached per input shape):
        eagerly this is dozens of dispatches, and even with a jitted loss the
        two scalar state adds would be two extra enqueues per step on a
        remote-attached accelerator."""
        if self._jit_loss is None:
            net, weights, normalize = self.net, self.layer_weights, self.normalize

            def step_fn(sum_scores, total, a, b):
                loss = learned_perceptual_image_patch_similarity(
                    a, b, net, weights, normalize, reduction="sum"
                )
                return sum_scores + loss, total + a.shape[0]

            from tpumetrics.utils.jit_fallback import JitWithEagerFallback

            self._jit_loss = JitWithEagerFallback(step_fn, "The LPIPS backbone")
        self.sum_scores, self.total = self._jit_loss(self.sum_scores, self.total, img1, img2)

    def compute(self) -> Array:
        """Reduced LPIPS (reference lpip.py:147-152)."""
        if self.reduction == "mean":
            return self.sum_scores / self.total
        return self.sum_scores

    def __getstate__(self):
        state = super().__getstate__()
        state.pop("_jit_loss", None)  # compiled fn, unpicklable; rebuilt lazily
        return state

    def __setstate__(self, state):
        super().__setstate__(state)
        self._jit_loss = None
