"""TotalVariation (counterpart of reference ``image/tv.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from tpumetrics.functional.image.tv import _total_variation_compute, _total_variation_update
from tpumetrics.metric import Metric
from tpumetrics.utils.data import dim_zero_cat

Array = jax.Array


class TotalVariation(Metric):
    """Total variation accumulated over batches (reference tv.py:30-123).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> from tpumetrics.image import TotalVariation
        >>> tv = TotalVariation()
        >>> img = jax.random.uniform(jax.random.PRNGKey(42), (5, 3, 28, 28))
        >>> float(tv(img)) > 0
        True
    """

    full_state_update: bool = False
    is_differentiable: bool = True
    higher_is_better: bool = False
    plot_lower_bound: float = 0.0

    def __init__(self, reduction: Optional[str] = "sum", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if reduction is not None and reduction not in ("sum", "mean", "none"):
            raise ValueError("Expected argument `reduction` to either be 'sum', 'mean', 'none' or None")
        self.reduction = reduction
        self.add_state("score_list", default=[], dist_reduce_fx="cat")
        self.add_state("score", default=jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("num_elements", default=jnp.zeros((), jnp.int32), dist_reduce_fx="sum")

    def update(self, img: Array) -> None:
        """Accumulate per-image TV scores."""
        score, num_elements = _total_variation_update(img)
        if self.reduction is None or self.reduction == "none":
            self.score_list.append(score)
        else:
            self.score = self.score + score.sum()
        self.num_elements = self.num_elements + num_elements

    def compute(self) -> Array:
        if self.reduction is None or self.reduction == "none":
            return dim_zero_cat(self.score_list)
        return _total_variation_compute(self.score, self.num_elements, self.reduction)
