"""SpectralAngleMapper (counterpart of reference ``image/sam.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from tpumetrics.functional.image.sam import _sam_compute, _sam_update
from tpumetrics.metric import Metric
from tpumetrics.utils.data import dim_zero_cat

Array = jax.Array


class SpectralAngleMapper(Metric):
    """Spectral angle between multispectral images, accumulated over batches
    (reference sam.py:35-152).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> from tpumetrics.image import SpectralAngleMapper
        >>> preds = jax.random.uniform(jax.random.PRNGKey(42), (16, 3, 16, 16))
        >>> target = jax.random.uniform(jax.random.PRNGKey(123), (16, 3, 16, 16))
        >>> sam = SpectralAngleMapper()
        >>> 0.0 < float(sam(preds, target)) < 1.6
        True
    """

    higher_is_better: bool = False
    is_differentiable: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    def __init__(self, reduction: Optional[str] = "elementwise_mean", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if reduction == "none" or reduction is None:
            self.add_state("preds", default=[], dist_reduce_fx="cat")
            self.add_state("target", default=[], dist_reduce_fx="cat")
        else:
            self.add_state("sum_sam", jnp.zeros(()), dist_reduce_fx="sum")
            self.add_state("numel", jnp.zeros(()), dist_reduce_fx="sum")
        self.reduction = reduction

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate spectral-angle sums (or raw images for reduction='none')."""
        preds, target = _sam_update(preds, target)
        if self.reduction == "none" or self.reduction is None:
            self.preds.append(preds)
            self.target.append(target)
        else:
            sam_map = _sam_compute(preds, target, reduction="none")
            self.sum_sam = self.sum_sam + sam_map.sum()
            self.numel = self.numel + sam_map.size

    def compute(self) -> Array:
        if self.reduction == "none" or self.reduction is None:
            return _sam_compute(dim_zero_cat(self.preds), dim_zero_cat(self.target), self.reduction)
        if self.reduction == "sum":
            return self.sum_sam
        return self.sum_sam / self.numel
