"""Task-string dispatch base for classification wrapper classes.

Counterpart of reference ``classification/base.py:19`` — classes like
``Accuracy(task="binary")`` resolve to the Binary/Multiclass/Multilabel
implementation in ``__new__``; calling update/compute on the wrapper itself
is an error.
"""

from __future__ import annotations

from typing import Any

from tpumetrics.metric import Metric


class _ClassificationTaskWrapper(Metric):
    """Base class for the task-dispatching wrapper metrics."""

    def update(self, *args: Any, **kwargs: Any) -> None:
        raise NotImplementedError(
            f"{self.__class__.__name__} metric does not have an `update` method. "
            "This is a wrapper class — construct it with a `task` argument to get a concrete metric."
        )

    def compute(self) -> None:
        raise NotImplementedError(
            f"{self.__class__.__name__} metric does not have a `compute` method. "
            "This is a wrapper class — construct it with a `task` argument to get a concrete metric."
        )
