"""Modular multilabel ranking metrics (counterpart of reference
``classification/ranking.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from tpumetrics.functional.classification.precision_recall_curve import (
    _multilabel_precision_recall_curve_tensor_validation,
)
from tpumetrics.functional.classification.ranking import (
    _multilabel_coverage_error_update,
    _multilabel_ranking_average_precision_update,
    _multilabel_ranking_format,
    _multilabel_ranking_loss_update,
    _ranking_reduce,
)
from tpumetrics.metric import Metric
from tpumetrics.utils.data import _count_dtype

Array = jax.Array


class _MultilabelRankingMetric(Metric):
    """Shared score/total sum-state machine for the ranking family."""

    is_differentiable: bool = False
    full_state_update: bool = False

    score: Array
    total: Array

    _update_fn = None  # set by subclass

    def __init__(
        self,
        num_labels: int,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            if not isinstance(num_labels, int) or num_labels < 2:
                raise ValueError(
                    f"Expected argument `num_labels` to be an integer larger than 1, but got {num_labels}"
                )
            if ignore_index is not None and not isinstance(ignore_index, int):
                raise ValueError(
                    f"Expected argument `ignore_index` to either be `None` or an int, but got {ignore_index}"
                )
        self.num_labels = num_labels
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self.add_state("score", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros((), dtype=_count_dtype()), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        if self.validate_args:
            _multilabel_precision_recall_curve_tensor_validation(
                preds, target, self.num_labels, self.ignore_index
            )
        preds, target = _multilabel_ranking_format(preds, target, self.num_labels, self.ignore_index)
        score, total = type(self)._update_fn(preds, target)
        self.score = self.score + score
        self.total = self.total + total

    def compute(self) -> Array:
        return _ranking_reduce(self.score, self.total)

    def plot(self, val: Any = None, ax: Any = None) -> Any:
        return self._plot(val, ax)


class MultilabelCoverageError(_MultilabelRankingMetric):
    """Coverage error (reference classification/ranking.py:28).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import MultilabelCoverageError
        >>> metric = MultilabelCoverageError(num_labels=3)
        >>> metric.update(jnp.asarray([[0.75, 0.05, 0.35], [0.45, 0.75, 0.05], [0.05, 0.65, 0.35]]),
        ...               jnp.asarray([[1, 0, 1], [0, 0, 1], [0, 1, 1]]))
        >>> round(float(metric.compute()), 4)
        2.3333
    """

    higher_is_better: bool = False
    plot_lower_bound: float = 0.0
    _update_fn = staticmethod(_multilabel_coverage_error_update)


class MultilabelRankingAveragePrecision(_MultilabelRankingMetric):
    """Label ranking average precision (reference classification/ranking.py:123).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import MultilabelRankingAveragePrecision
        >>> metric = MultilabelRankingAveragePrecision(num_labels=3)
        >>> metric.update(jnp.asarray([[0.75, 0.05, 0.35], [0.45, 0.75, 0.05], [0.05, 0.65, 0.35]]),
        ...               jnp.asarray([[1, 0, 1], [0, 0, 1], [0, 1, 1]]))
        >>> round(float(metric.compute()), 4)
        0.7778
    """

    higher_is_better: bool = True
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0
    _update_fn = staticmethod(_multilabel_ranking_average_precision_update)


class MultilabelRankingLoss(_MultilabelRankingMetric):
    """Label ranking loss (reference classification/ranking.py:219).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import MultilabelRankingLoss
        >>> metric = MultilabelRankingLoss(num_labels=3)
        >>> metric.update(jnp.asarray([[0.75, 0.05, 0.35], [0.45, 0.75, 0.05], [0.05, 0.65, 0.35]]),
        ...               jnp.asarray([[1, 0, 1], [0, 0, 1], [0, 1, 1]]))
        >>> round(float(metric.compute()), 4)
        0.3333
    """

    higher_is_better: bool = False
    plot_lower_bound: float = 0.0
    _update_fn = staticmethod(_multilabel_ranking_loss_update)
