"""Modular precision-recall-curve metrics — the curve-family state machine.

Counterpart of reference ``classification/precision_recall_curve.py``: the
two state modes (exact ``thresholds=None`` -> preds/target "cat" list
states; binned -> one static ``(T, [C,] 2, 2)`` "sum" confusion tensor,
reference functional precision_recall_curve.py:83-91/:190-240). The binned
mode is the TPU recommendation — constant memory, jit-able update, one psum
to sync. ROC/AUROC/AveragePrecision/{Precision,Recall}AtFixed*/
SpecificityAtSensitivity all subclass these classes, overriding ``compute``.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from tpumetrics.classification.base import _ClassificationTaskWrapper
from tpumetrics.functional.classification.precision_recall_curve import (
    Thresholds,
    _adjust_threshold_arg,
    _binary_precision_recall_curve_arg_validation,
    _binary_precision_recall_curve_compute,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_arg_validation,
    _multiclass_precision_recall_curve_compute,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_arg_validation,
    _multilabel_precision_recall_curve_compute,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)
from tpumetrics.metric import Metric
from tpumetrics.utils.data import _count_dtype, dim_zero_cat
from tpumetrics.utils.enums import ClassificationTask
from tpumetrics.utils.plot import plot_curve

Array = jax.Array


class BinaryPrecisionRecallCurve(Metric):
    """Precision-recall curve for binary tasks (reference
    classification/precision_recall_curve.py:29).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import BinaryPrecisionRecallCurve
        >>> metric = BinaryPrecisionRecallCurve(thresholds=5)
        >>> metric.update(jnp.asarray([0.1, 0.4, 0.35, 0.8]), jnp.asarray([0, 0, 1, 1]))
        >>> precision, recall, thresholds = metric.compute()
        >>> precision.tolist()
        [0.5, 0.6666666865348816, 1.0, 1.0, 0.0, 1.0]
    """

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = None
    full_state_update: bool = False

    preds: List[Array]
    target: List[Array]
    confmat: Array

    def __init__(
        self,
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
        self.ignore_index = ignore_index
        self.validate_args = validate_args

        thresholds = _adjust_threshold_arg(thresholds)
        if thresholds is None:
            self.thresholds = thresholds
            self.add_state("preds", default=[], dist_reduce_fx="cat")
            self.add_state("target", default=[], dist_reduce_fx="cat")
        else:
            self.thresholds = thresholds
            self.add_state(
                "confmat", default=jnp.zeros((len(thresholds), 2, 2), dtype=_count_dtype()), dist_reduce_fx="sum"
            )

    def update(self, preds: Array, target: Array) -> None:
        if self.validate_args:
            _binary_precision_recall_curve_tensor_validation(preds, target, self.ignore_index)
        preds, target, _ = _binary_precision_recall_curve_format(
            preds, target, self.thresholds, self.ignore_index
        )
        state = _binary_precision_recall_curve_update(preds, target, self.thresholds, self.ignore_index)
        if isinstance(state, tuple):
            self.preds.append(state[0])
            self.target.append(state[1])
        else:
            self.confmat = self.confmat + state

    def _final_state(self) -> Union[Array, Tuple[Array, Array]]:
        if self.thresholds is not None:
            return self.confmat
        return dim_zero_cat(self.preds), dim_zero_cat(self.target)

    def compute(self) -> Tuple[Array, Array, Array]:
        return _binary_precision_recall_curve_compute(self._final_state(), self.thresholds)

    def plot(self, curve: Optional[Tuple] = None, score: Any = None, ax: Any = None) -> Any:
        curve_computed = curve or self.compute()
        return plot_curve(
            curve_computed, score=score, ax=ax, label_names=("Recall", "Precision"),
            name=self.__class__.__name__,
        )


class MulticlassPrecisionRecallCurve(Metric):
    """Per-class precision-recall curves for multiclass tasks (reference
    classification/precision_recall_curve.py:168).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import MulticlassPrecisionRecallCurve
        >>> metric = MulticlassPrecisionRecallCurve(num_classes=3, thresholds=5)
        >>> metric.update(jnp.asarray([[0.8, 0.1, 0.1], [0.1, 0.8, 0.1]]), jnp.asarray([0, 1]))
        >>> precision, recall, thresholds = metric.compute()
        >>> precision.shape
        (3, 6)
    """

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = None
    full_state_update: bool = False

    preds: List[Array]
    target: List[Array]
    confmat: Array

    def __init__(
        self,
        num_classes: int,
        thresholds: Thresholds = None,
        average: Optional[str] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index, average)
        self.num_classes = num_classes
        self.average = average
        self.ignore_index = ignore_index
        self.validate_args = validate_args

        thresholds = _adjust_threshold_arg(thresholds)
        self.thresholds = thresholds
        if thresholds is None:
            self.add_state("preds", default=[], dist_reduce_fx="cat")
            self.add_state("target", default=[], dist_reduce_fx="cat")
        else:
            shape = (len(thresholds), 2, 2) if average == "micro" else (len(thresholds), num_classes, 2, 2)
            self.add_state("confmat", default=jnp.zeros(shape, dtype=_count_dtype()), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        if self.validate_args:
            _multiclass_precision_recall_curve_tensor_validation(preds, target, self.num_classes, self.ignore_index)
        preds, target, _ = _multiclass_precision_recall_curve_format(
            preds, target, self.num_classes, self.thresholds, self.ignore_index, self.average
        )
        state = _multiclass_precision_recall_curve_update(
            preds, target, self.num_classes, self.thresholds, self.average, self.ignore_index
        )
        if isinstance(state, tuple):
            self.preds.append(state[0])
            self.target.append(state[1])
        else:
            self.confmat = self.confmat + state

    def _final_state(self) -> Union[Array, Tuple[Array, Array]]:
        if self.thresholds is not None:
            return self.confmat
        return dim_zero_cat(self.preds), dim_zero_cat(self.target)

    def compute(self) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
        return _multiclass_precision_recall_curve_compute(
            self._final_state(), self.num_classes, self.thresholds, self.average
        )

    def plot(self, curve: Optional[Tuple] = None, score: Any = None, ax: Any = None) -> Any:
        curve_computed = curve or self.compute()
        return plot_curve(
            curve_computed, score=score, ax=ax, label_names=("Recall", "Precision"),
            name=self.__class__.__name__,
        )


class MultilabelPrecisionRecallCurve(Metric):
    """Per-label precision-recall curves for multilabel tasks (reference
    classification/precision_recall_curve.py:317).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import MultilabelPrecisionRecallCurve
        >>> metric = MultilabelPrecisionRecallCurve(num_labels=2, thresholds=5)
        >>> metric.update(jnp.asarray([[0.8, 0.1], [0.1, 0.8]]), jnp.asarray([[1, 0], [0, 1]]))
        >>> precision, recall, thresholds = metric.compute()
        >>> precision.shape
        (2, 6)
    """

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = None
    full_state_update: bool = False

    preds: List[Array]
    target: List[Array]
    confmat: Array

    def __init__(
        self,
        num_labels: int,
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
        self.num_labels = num_labels
        self.ignore_index = ignore_index
        self.validate_args = validate_args

        thresholds = _adjust_threshold_arg(thresholds)
        self.thresholds = thresholds
        if thresholds is None:
            self.add_state("preds", default=[], dist_reduce_fx="cat")
            self.add_state("target", default=[], dist_reduce_fx="cat")
        else:
            self.add_state(
                "confmat",
                default=jnp.zeros((len(thresholds), num_labels, 2, 2), dtype=_count_dtype()),
                dist_reduce_fx="sum",
            )

    def update(self, preds: Array, target: Array) -> None:
        if self.validate_args:
            _multilabel_precision_recall_curve_tensor_validation(preds, target, self.num_labels, self.ignore_index)
        preds, target, _ = _multilabel_precision_recall_curve_format(
            preds, target, self.num_labels, self.thresholds, self.ignore_index
        )
        state = _multilabel_precision_recall_curve_update(
            preds, target, self.num_labels, self.thresholds, self.ignore_index
        )
        if isinstance(state, tuple):
            self.preds.append(state[0])
            self.target.append(state[1])
        else:
            self.confmat = self.confmat + state

    def _final_state(self) -> Union[Array, Tuple[Array, Array]]:
        if self.thresholds is not None:
            return self.confmat
        return dim_zero_cat(self.preds), dim_zero_cat(self.target)

    def compute(self) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
        return _multilabel_precision_recall_curve_compute(
            self._final_state(), self.num_labels, self.thresholds, self.ignore_index
        )

    def plot(self, curve: Optional[Tuple] = None, score: Any = None, ax: Any = None) -> Any:
        curve_computed = curve or self.compute()
        return plot_curve(
            curve_computed, score=score, ax=ax, label_names=("Recall", "Precision"),
            name=self.__class__.__name__,
        )


class PrecisionRecallCurve(_ClassificationTaskWrapper):
    """Task-string wrapper (reference classification/precision_recall_curve.py:463).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics import PrecisionRecallCurve
        >>> probs = jnp.asarray([0.11, 0.84, 0.22, 0.73, 0.33, 0.92])
        >>> target = jnp.asarray([0, 1, 0, 1, 0, 1])
        >>> metric = PrecisionRecallCurve(task="binary", thresholds=4)
        >>> metric.update(probs, target)
        >>> precision, recall, thresholds = metric.compute()
        >>> precision.shape, recall.shape, thresholds.shape
        ((5,), (5,), (4,))
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        thresholds: Thresholds = None,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str(task)
        kwargs.update({"thresholds": thresholds, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryPrecisionRecallCurve(**kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
            return MulticlassPrecisionRecallCurve(num_classes, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
            return MultilabelPrecisionRecallCurve(num_labels, **kwargs)
        raise ValueError(f"Not handled value: {task}")


class _AtFixedValuePlotMixin:
    """Plot override for the (value, threshold)-tuple metrics
    (Precision@Recall / Recall@Precision / Specificity@Sensitivity): the
    default plot shows the primary value only, matching the reference's
    per-class ``plot`` overrides (reference
    classification/precision_fixed_recall.py:135-177)."""

    def plot(self, val=None, ax=None):
        if val is None:
            val = self.compute()[0]
        return self._plot(val, ax)
