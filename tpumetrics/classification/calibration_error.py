"""Modular calibration error metrics (counterpart of reference
``classification/calibration_error.py``)."""

from __future__ import annotations

from typing import Any, List, Optional

import jax
import jax.numpy as jnp

from tpumetrics.classification.base import _ClassificationTaskWrapper
from tpumetrics.functional.classification.calibration_error import (
    _binary_calibration_error_arg_validation,
    _binary_calibration_error_update,
    _ce_compute,
    _multiclass_calibration_error_arg_validation,
    _multiclass_calibration_error_update,
)
from tpumetrics.functional.classification.precision_recall_curve import (
    _binary_precision_recall_curve_tensor_validation,
)
from tpumetrics.functional.classification.stat_scores import (
    _multiclass_stat_scores_tensor_validation,
)
from tpumetrics.metric import Metric
from tpumetrics.utils.compute import normalize_logits_if_needed
from tpumetrics.utils.data import dim_zero_cat
from tpumetrics.utils.enums import ClassificationTaskNoMultilabel

Array = jax.Array


class BinaryCalibrationError(Metric):
    """Top-label calibration error, binary (reference classification/calibration_error.py:33).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import BinaryCalibrationError
        >>> metric = BinaryCalibrationError(n_bins=2, norm='l1')
        >>> metric.update(jnp.asarray([0.25, 0.25, 0.55, 0.75, 0.75]), jnp.asarray([0, 0, 1, 1, 1]))
        >>> round(float(metric.compute()), 4)
        0.29
    """

    is_differentiable: bool = False
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    confidences: List[Array]
    accuracies: List[Array]

    def __init__(
        self,
        n_bins: int = 15,
        norm: str = "l1",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _binary_calibration_error_arg_validation(n_bins, norm, ignore_index)
        self.n_bins = n_bins
        self.norm = norm
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self.add_state("confidences", [], dist_reduce_fx="cat")
        self.add_state("accuracies", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        if self.validate_args:
            _binary_precision_recall_curve_tensor_validation(preds, target, self.ignore_index)
        preds = preds.ravel()
        target = target.ravel()
        if self.ignore_index is not None:
            idx = target != self.ignore_index
            preds = preds[idx]
            target = target[idx]
        preds = normalize_logits_if_needed(preds, "sigmoid")
        confidences, accuracies = _binary_calibration_error_update(preds, target)
        self.confidences.append(confidences.astype(jnp.float32))
        self.accuracies.append(accuracies.astype(jnp.float32))

    def compute(self) -> Array:
        confidences = dim_zero_cat(self.confidences)
        accuracies = dim_zero_cat(self.accuracies)
        return _ce_compute(confidences, accuracies, self.n_bins, self.norm)


class MulticlassCalibrationError(Metric):
    """Top-label calibration error, multiclass (reference classification/calibration_error.py:165).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import MulticlassCalibrationError
        >>> metric = MulticlassCalibrationError(num_classes=3)
        >>> metric.update(jnp.asarray([[0.9, 0.05, 0.05], [0.1, 0.8, 0.1]]), jnp.asarray([0, 1]))
        >>> round(float(metric.compute()), 4)
        0.15
    """

    is_differentiable: bool = False
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    confidences: List[Array]
    accuracies: List[Array]

    def __init__(
        self,
        num_classes: int,
        n_bins: int = 15,
        norm: str = "l1",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multiclass_calibration_error_arg_validation(num_classes, n_bins, norm, ignore_index)
        self.num_classes = num_classes
        self.n_bins = n_bins
        self.norm = norm
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self.add_state("confidences", [], dist_reduce_fx="cat")
        self.add_state("accuracies", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        if self.validate_args:
            _multiclass_stat_scores_tensor_validation(preds, target, self.num_classes, "global", self.ignore_index)
        preds = jnp.moveaxis(preds, 1, -1).reshape(-1, self.num_classes)
        target = target.ravel()
        if self.ignore_index is not None:
            idx = target != self.ignore_index
            preds = preds[idx]
            target = target[idx]
        confidences, accuracies = _multiclass_calibration_error_update(preds, target)
        self.confidences.append(confidences)
        self.accuracies.append(accuracies)

    def compute(self) -> Array:
        confidences = dim_zero_cat(self.confidences)
        accuracies = dim_zero_cat(self.accuracies)
        return _ce_compute(confidences, accuracies, self.n_bins, self.norm)


class CalibrationError(_ClassificationTaskWrapper):
    """Task-string wrapper (reference classification/calibration_error.py:297).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics import CalibrationError
        >>> probs = jnp.asarray([0.11, 0.84, 0.22, 0.73, 0.33, 0.92])
        >>> target = jnp.asarray([0, 1, 0, 1, 0, 1])
        >>> metric = CalibrationError(task="binary", n_bins=4)
        >>> metric.update(probs, target)
        >>> round(float(metric.compute()), 4)
        0.195
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        n_bins: int = 15,
        norm: str = "l1",
        num_classes: Optional[int] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTaskNoMultilabel.from_str(task)
        kwargs.update({"n_bins": n_bins, "norm": norm, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTaskNoMultilabel.BINARY:
            return BinaryCalibrationError(**kwargs)
        if task == ClassificationTaskNoMultilabel.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
            return MulticlassCalibrationError(num_classes, **kwargs)
        raise ValueError(f"Not handled value: {task}")
