"""Modular specificity metrics (counterpart of reference ``classification/specificity.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax

from tpumetrics.classification.base import _ClassificationTaskWrapper
from tpumetrics.classification.stat_scores import BinaryStatScores, MulticlassStatScores, MultilabelStatScores
from tpumetrics.functional.classification.specificity import _specificity_reduce
from tpumetrics.metric import Metric
from tpumetrics.utils.enums import ClassificationTask

Array = jax.Array


class BinarySpecificity(BinaryStatScores):
    """Binary specificity: tn / (tn + fp).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import BinarySpecificity
        >>> metric = BinarySpecificity()
        >>> metric.update(jnp.asarray([0, 0, 1, 1, 0, 1]), jnp.asarray([0, 1, 0, 1, 0, 1]))
        >>> round(float(metric.compute()), 4)
        0.6667
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _specificity_reduce(tp, fp, tn, fn, average="binary", multidim_average=self.multidim_average)


class MulticlassSpecificity(MulticlassStatScores):
    """Multiclass specificity."""

    is_differentiable = False
    higher_is_better = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0
    plot_legend_name: str = "Class"

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _specificity_reduce(tp, fp, tn, fn, average=self.average, multidim_average=self.multidim_average)


class MultilabelSpecificity(MultilabelStatScores):
    """Multilabel specificity."""

    is_differentiable = False
    higher_is_better = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0
    plot_legend_name: str = "Label"

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _specificity_reduce(
            tp, fp, tn, fn, average=self.average, multidim_average=self.multidim_average, multilabel=True
        )


class Specificity(_ClassificationTaskWrapper):
    """Task-string wrapper for specificity.

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics import Specificity
        >>> logits = jnp.asarray([[2.0, 0.5, 0.1], [0.3, 2.1, 0.2], [0.2, 0.3, 2.2], [2.0, 0.1, 0.4]])
        >>> target = jnp.asarray([0, 1, 2, 1])
        >>> metric = Specificity(task="multiclass", num_classes=3, average="macro")
        >>> metric.update(logits, target)
        >>> round(float(metric.compute()), 4)
        0.8889
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        average: Optional[str] = "micro",
        multidim_average: str = "global",
        top_k: Optional[int] = 1,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str(task)
        kwargs.update(
            {"multidim_average": multidim_average, "ignore_index": ignore_index, "validate_args": validate_args}
        )
        if task == ClassificationTask.BINARY:
            return BinarySpecificity(threshold, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
            if not isinstance(top_k, int):
                raise ValueError(f"`top_k` is expected to be `int` but `{type(top_k)} was passed.`")
            return MulticlassSpecificity(num_classes, top_k, average, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
            return MultilabelSpecificity(num_labels, threshold, average, **kwargs)
        raise ValueError(f"Not handled value: {task}")
