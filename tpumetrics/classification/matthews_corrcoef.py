"""Modular Matthews correlation coefficient metrics (counterpart of reference
``classification/matthews_corrcoef.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax

from tpumetrics.classification.base import _ClassificationTaskWrapper
from tpumetrics.classification.confusion_matrix import (
    BinaryConfusionMatrix,
    MulticlassConfusionMatrix,
    MultilabelConfusionMatrix,
)
from tpumetrics.functional.classification.matthews_corrcoef import _matthews_corrcoef_reduce
from tpumetrics.metric import Metric
from tpumetrics.utils.enums import ClassificationTask

Array = jax.Array


class BinaryMatthewsCorrCoef(BinaryConfusionMatrix):
    """MCC, binary (reference classification/matthews_corrcoef.py:29).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import BinaryMatthewsCorrCoef
        >>> metric = BinaryMatthewsCorrCoef()
        >>> metric.update(jnp.asarray([0.35, 0.85, 0.48, 0.01]), jnp.asarray([1, 1, 0, 0]))
        >>> round(float(metric.compute()), 4)
        0.5774
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = -1.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        threshold: float = 0.5,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            threshold=threshold, normalize=None, ignore_index=ignore_index, validate_args=validate_args, **kwargs
        )

    def compute(self) -> Array:
        return _matthews_corrcoef_reduce(self.confmat)

    def plot(self, val: Any = None, ax: Any = None) -> Any:
        return self._plot(val, ax)


class MulticlassMatthewsCorrCoef(MulticlassConfusionMatrix):
    """MCC, multiclass (reference classification/matthews_corrcoef.py:139).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import MulticlassMatthewsCorrCoef
        >>> metric = MulticlassMatthewsCorrCoef(num_classes=3)
        >>> metric.update(jnp.asarray([2, 1, 0, 1]), jnp.asarray([2, 1, 0, 0]))
        >>> round(float(metric.compute()), 4)
        0.7
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = -1.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        num_classes: int,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            num_classes=num_classes, normalize=None, ignore_index=ignore_index,
            validate_args=validate_args, **kwargs,
        )

    def compute(self) -> Array:
        return _matthews_corrcoef_reduce(self.confmat)

    def plot(self, val: Any = None, ax: Any = None) -> Any:
        return self._plot(val, ax)


class MultilabelMatthewsCorrCoef(MultilabelConfusionMatrix):
    """MCC, multilabel (reference classification/matthews_corrcoef.py:245).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import MultilabelMatthewsCorrCoef
        >>> metric = MultilabelMatthewsCorrCoef(num_labels=3)
        >>> metric.update(jnp.asarray([[0, 0, 1], [1, 0, 1]]), jnp.asarray([[0, 1, 0], [1, 0, 1]]))
        >>> round(float(metric.compute()), 4)
        0.3333
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = -1.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        num_labels: int,
        threshold: float = 0.5,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            num_labels=num_labels, threshold=threshold, normalize=None, ignore_index=ignore_index,
            validate_args=validate_args, **kwargs,
        )

    def compute(self) -> Array:
        return _matthews_corrcoef_reduce(self.confmat)

    def plot(self, val: Any = None, ax: Any = None) -> Any:
        return self._plot(val, ax)


class MatthewsCorrCoef(_ClassificationTaskWrapper):
    """Task-string wrapper (reference classification/matthews_corrcoef.py:355).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics import MatthewsCorrCoef
        >>> logits = jnp.asarray([[2.0, 0.5, 0.1], [0.3, 2.1, 0.2], [0.2, 0.3, 2.2], [2.0, 0.1, 0.4]])
        >>> target = jnp.asarray([0, 1, 2, 1])
        >>> metric = MatthewsCorrCoef(task="multiclass", num_classes=3)
        >>> metric.update(logits, target)
        >>> round(float(metric.compute()), 4)
        0.7
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str(task)
        kwargs.update({"ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryMatthewsCorrCoef(threshold, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
            return MulticlassMatthewsCorrCoef(num_classes, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
            return MultilabelMatthewsCorrCoef(num_labels, threshold, **kwargs)
        raise ValueError(f"Not handled value: {task}")
