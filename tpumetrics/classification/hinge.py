"""Modular hinge-loss metrics (counterpart of reference
``classification/hinge.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from tpumetrics.classification.base import _ClassificationTaskWrapper
from tpumetrics.functional.classification.hinge import (
    _binary_hinge_loss_arg_validation,
    _binary_hinge_loss_update,
    _hinge_loss_compute,
    _multiclass_hinge_loss_arg_validation,
    _multiclass_hinge_loss_update,
)
from tpumetrics.functional.classification.precision_recall_curve import (
    _binary_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_tensor_validation,
)
from tpumetrics.metric import Metric
from tpumetrics.utils.compute import normalize_logits_if_needed
from tpumetrics.utils.enums import ClassificationTaskNoMultilabel
from tpumetrics.utils.data import _count_dtype

Array = jax.Array


class BinaryHingeLoss(Metric):
    """Mean hinge loss, binary (reference classification/hinge.py:28).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import BinaryHingeLoss
        >>> metric = BinaryHingeLoss()
        >>> metric.update(jnp.asarray([0.25, 0.25, 0.55, 0.75, 0.75]), jnp.asarray([0, 0, 1, 1, 1]))
        >>> round(float(metric.compute()), 4)
        0.69
    """

    is_differentiable: bool = True
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    measures: Array
    total: Array

    def __init__(
        self,
        squared: bool = False,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _binary_hinge_loss_arg_validation(squared, ignore_index)
        self.squared = squared
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self.add_state("measures", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros((), dtype=_count_dtype()), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        if self.validate_args:
            _binary_precision_recall_curve_tensor_validation(preds, target, self.ignore_index)
        preds = preds.ravel()
        target = target.ravel()
        if self.ignore_index is not None:
            idx = target != self.ignore_index
            preds = preds[idx]
            target = target[idx]
        preds = normalize_logits_if_needed(preds, "sigmoid")
        measures, total = _binary_hinge_loss_update(preds, target, self.squared)
        self.measures = self.measures + measures
        self.total = self.total + total

    def compute(self) -> Array:
        return _hinge_loss_compute(self.measures, self.total)

    def plot(self, val: Any = None, ax: Any = None) -> Any:
        return self._plot(val, ax)


class MulticlassHingeLoss(Metric):
    """Mean hinge loss, multiclass (reference classification/hinge.py:120).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import MulticlassHingeLoss
        >>> metric = MulticlassHingeLoss(num_classes=3)
        >>> metric.update(
        ...     jnp.asarray([[0.25, 0.20, 0.55], [0.55, 0.05, 0.40], [0.10, 0.30, 0.60], [0.90, 0.05, 0.05]]),
        ...     jnp.asarray([0, 1, 2, 0]))
        >>> round(float(metric.compute()), 4)
        0.9125
    """

    is_differentiable: bool = True
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    measures: Array
    total: Array

    def __init__(
        self,
        num_classes: int,
        squared: bool = False,
        multiclass_mode: str = "crammer-singer",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multiclass_hinge_loss_arg_validation(num_classes, squared, multiclass_mode, ignore_index)
        self.num_classes = num_classes
        self.squared = squared
        self.multiclass_mode = multiclass_mode
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self.add_state(
            "measures",
            jnp.zeros(()) if multiclass_mode == "crammer-singer" else jnp.zeros(num_classes),
            dist_reduce_fx="sum",
        )
        self.add_state("total", jnp.zeros((), dtype=_count_dtype()), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        if self.validate_args:
            _multiclass_precision_recall_curve_tensor_validation(preds, target, self.num_classes, self.ignore_index)
        preds = jnp.moveaxis(preds, 1, -1).reshape(-1, self.num_classes)
        target = target.ravel()
        if self.ignore_index is not None:
            idx = target != self.ignore_index
            preds = preds[idx]
            target = target[idx]
        preds = normalize_logits_if_needed(preds, "softmax")
        measures, total = _multiclass_hinge_loss_update(preds, target, self.squared, self.multiclass_mode)
        self.measures = self.measures + measures
        self.total = self.total + total

    def compute(self) -> Array:
        return _hinge_loss_compute(self.measures, self.total)

    def plot(self, val: Any = None, ax: Any = None) -> Any:
        return self._plot(val, ax)


class HingeLoss(_ClassificationTaskWrapper):
    """Task-string wrapper (reference classification/hinge.py:233).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics import HingeLoss
        >>> probs = jnp.asarray([0.11, 0.84, 0.22, 0.73, 0.33, 0.92])
        >>> target = jnp.asarray([0, 1, 0, 1, 0, 1])
        >>> metric = HingeLoss(task="binary")
        >>> metric.update(probs, target)
        >>> round(float(metric.compute()), 4)
        0.695
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        num_classes: Optional[int] = None,
        squared: bool = False,
        multiclass_mode: str = "crammer-singer",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTaskNoMultilabel.from_str(task)
        kwargs.update({"squared": squared, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTaskNoMultilabel.BINARY:
            return BinaryHingeLoss(**kwargs)
        if task == ClassificationTaskNoMultilabel.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
            return MulticlassHingeLoss(num_classes, multiclass_mode=multiclass_mode, **kwargs)
        raise ValueError(f"Not handled value: {task}")
