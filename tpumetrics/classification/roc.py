"""Modular ROC metrics (counterpart of reference ``classification/roc.py`` —
subclasses of the PR-curve state classes overriding ``compute``)."""

from __future__ import annotations

from typing import Any, List, Optional, Tuple, Union

import jax

from tpumetrics.classification.base import _ClassificationTaskWrapper
from tpumetrics.classification.precision_recall_curve import (
    BinaryPrecisionRecallCurve,
    MulticlassPrecisionRecallCurve,
    MultilabelPrecisionRecallCurve,
)
from tpumetrics.functional.classification.precision_recall_curve import Thresholds
from tpumetrics.functional.classification.roc import (
    _binary_roc_compute,
    _multiclass_roc_compute,
    _multilabel_roc_compute,
)
from tpumetrics.metric import Metric
from tpumetrics.utils.enums import ClassificationTask
from tpumetrics.utils.plot import plot_curve

Array = jax.Array


class BinaryROC(BinaryPrecisionRecallCurve):
    """ROC curve for binary tasks (reference classification/roc.py:26).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import BinaryROC
        >>> metric = BinaryROC(thresholds=5)
        >>> metric.update(jnp.asarray([0.1, 0.4, 0.35, 0.8]), jnp.asarray([0, 0, 1, 1]))
        >>> fpr, tpr, thresholds = metric.compute()
        >>> tpr.tolist()
        [0.0, 0.5, 0.5, 1.0, 1.0]
    """

    def compute(self) -> Tuple[Array, Array, Array]:
        return _binary_roc_compute(self._final_state(), self.thresholds)

    def plot(self, curve: Optional[Tuple] = None, score: Any = None, ax: Any = None) -> Any:
        curve_computed = curve or self.compute()
        return plot_curve(
            curve_computed, score=score, ax=ax, label_names=("False positive rate", "True positive rate"),
            name=self.__class__.__name__,
        )


class MulticlassROC(MulticlassPrecisionRecallCurve):
    """Per-class one-vs-rest ROC curves (reference classification/roc.py:154).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import MulticlassROC
        >>> metric = MulticlassROC(num_classes=3, thresholds=5)
        >>> metric.update(jnp.asarray([[0.8, 0.1, 0.1], [0.1, 0.8, 0.1]]), jnp.asarray([0, 1]))
        >>> fpr, tpr, thresholds = metric.compute()
        >>> fpr.shape
        (3, 5)
    """

    def compute(self) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
        return _multiclass_roc_compute(self._final_state(), self.num_classes, self.thresholds, self.average)

    def plot(self, curve: Optional[Tuple] = None, score: Any = None, ax: Any = None) -> Any:
        curve_computed = curve or self.compute()
        return plot_curve(
            curve_computed, score=score, ax=ax, label_names=("False positive rate", "True positive rate"),
            name=self.__class__.__name__,
        )


class MultilabelROC(MultilabelPrecisionRecallCurve):
    """Per-label ROC curves (reference classification/roc.py:265).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import MultilabelROC
        >>> metric = MultilabelROC(num_labels=2, thresholds=5)
        >>> metric.update(jnp.asarray([[0.8, 0.1], [0.1, 0.8]]), jnp.asarray([[1, 0], [0, 1]]))
        >>> fpr, tpr, thresholds = metric.compute()
        >>> fpr.shape
        (2, 5)
    """

    def compute(self) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
        return _multilabel_roc_compute(self._final_state(), self.num_labels, self.thresholds, self.ignore_index)

    def plot(self, curve: Optional[Tuple] = None, score: Any = None, ax: Any = None) -> Any:
        curve_computed = curve or self.compute()
        return plot_curve(
            curve_computed, score=score, ax=ax, label_names=("False positive rate", "True positive rate"),
            name=self.__class__.__name__,
        )


class ROC(_ClassificationTaskWrapper):
    """Task-string wrapper for ROC (reference classification/roc.py:389).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics import ROC
        >>> probs = jnp.asarray([0.11, 0.84, 0.22, 0.73, 0.33, 0.92])
        >>> target = jnp.asarray([0, 1, 0, 1, 0, 1])
        >>> metric = ROC(task="binary", thresholds=4)
        >>> metric.update(probs, target)
        >>> fpr, tpr, thresholds = metric.compute()
        >>> fpr.shape, tpr.shape, thresholds.shape
        ((4,), (4,), (4,))
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        thresholds: Thresholds = None,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str(task)
        kwargs.update({"thresholds": thresholds, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryROC(**kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
            return MulticlassROC(num_classes, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
            return MultilabelROC(num_labels, **kwargs)
        raise ValueError(f"Not handled value: {task}")
