"""Modular Hamming distance metrics (counterpart of reference ``classification/hamming.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax

from tpumetrics.classification.base import _ClassificationTaskWrapper
from tpumetrics.classification.stat_scores import BinaryStatScores, MulticlassStatScores, MultilabelStatScores
from tpumetrics.functional.classification.hamming import _hamming_distance_reduce
from tpumetrics.metric import Metric
from tpumetrics.utils.enums import ClassificationTask

Array = jax.Array


class BinaryHammingDistance(BinaryStatScores):
    """Binary Hamming distance: fraction of wrong predictions.

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import BinaryHammingDistance
        >>> metric = BinaryHammingDistance()
        >>> metric.update(jnp.asarray([0, 0, 1, 1, 0, 1]), jnp.asarray([0, 1, 0, 1, 0, 1]))
        >>> round(float(metric.compute()), 4)
        0.3333
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _hamming_distance_reduce(tp, fp, tn, fn, average="binary", multidim_average=self.multidim_average)


class MulticlassHammingDistance(MulticlassStatScores):
    """Multiclass Hamming distance."""

    is_differentiable = False
    higher_is_better = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0
    plot_legend_name: str = "Class"

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _hamming_distance_reduce(
            tp, fp, tn, fn, average=self.average, multidim_average=self.multidim_average
        )


class MultilabelHammingDistance(MultilabelStatScores):
    """Multilabel Hamming distance."""

    is_differentiable = False
    higher_is_better = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0
    plot_legend_name: str = "Label"

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _hamming_distance_reduce(
            tp, fp, tn, fn, average=self.average, multidim_average=self.multidim_average, multilabel=True
        )


class HammingDistance(_ClassificationTaskWrapper):
    """Task-string wrapper for Hamming distance.

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics import HammingDistance
        >>> probs = jnp.asarray([0.11, 0.84, 0.22, 0.73, 0.33, 0.92])
        >>> target = jnp.asarray([0, 1, 0, 1, 0, 1])
        >>> metric = HammingDistance(task="binary")
        >>> metric.update(probs, target)
        >>> round(float(metric.compute()), 4)
        0.0
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        average: Optional[str] = "micro",
        multidim_average: str = "global",
        top_k: Optional[int] = 1,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str(task)
        kwargs.update(
            {"multidim_average": multidim_average, "ignore_index": ignore_index, "validate_args": validate_args}
        )
        if task == ClassificationTask.BINARY:
            return BinaryHammingDistance(threshold, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
            if not isinstance(top_k, int):
                raise ValueError(f"`top_k` is expected to be `int` but `{type(top_k)} was passed.`")
            return MulticlassHammingDistance(num_classes, top_k, average, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
            return MultilabelHammingDistance(num_labels, threshold, average, **kwargs)
        raise ValueError(f"Not handled value: {task}")
