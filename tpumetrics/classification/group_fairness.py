"""Modular group-fairness metrics (counterpart of reference
``classification/group_fairness.py`` — `_AbstractGroupStatScores` :33,
`BinaryGroupStatRates` :62, `BinaryFairness` :129)."""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from tpumetrics.functional.classification.group_fairness import (
    _binary_groups_stat_scores,
    _compute_binary_demographic_parity,
    _compute_binary_equal_opportunity,
    _groups_reduce,
    _groups_stat_transform,
)
from tpumetrics.metric import Metric
from tpumetrics.utils.data import _count_dtype

Array = jax.Array


class _AbstractGroupStatScores(Metric):
    """Per-group tp/fp/tn/fn accumulators, shape (num_groups,)
    (reference group_fairness.py:33-59)."""

    tp: Array
    fp: Array
    tn: Array
    fn: Array

    def _create_states(self, num_groups: int) -> None:
        default = lambda: jnp.zeros(num_groups, dtype=_count_dtype())  # noqa: E731
        for name in ("tp", "fp", "tn", "fn"):
            self.add_state(name, default(), dist_reduce_fx="sum")

    def _update_states(self, group_stats: list) -> None:
        self.tp = self.tp + jnp.stack([s[0] for s in group_stats])
        self.fp = self.fp + jnp.stack([s[1] for s in group_stats])
        self.tn = self.tn + jnp.stack([s[2] for s in group_stats])
        self.fn = self.fn + jnp.stack([s[3] for s in group_stats])


class BinaryGroupStatRates(_AbstractGroupStatScores):
    """tp/fp/tn/fn rates by group (reference group_fairness.py:62).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import BinaryGroupStatRates
        >>> metric = BinaryGroupStatRates(num_groups=2)
        >>> metric.update(jnp.asarray([0, 1, 0, 1]), jnp.asarray([0, 1, 0, 1]), jnp.asarray([0, 1, 0, 1]))
        >>> {k: v.tolist() for k, v in metric.compute().items()}
        {'group_0': [0.0, 0.0, 1.0, 0.0], 'group_1': [1.0, 0.0, 0.0, 0.0]}
    """

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = None
    full_state_update: bool = False

    def __init__(
        self,
        num_groups: int,
        threshold: float = 0.5,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args and (not isinstance(num_groups, int) or num_groups < 2):
            raise ValueError(f"Expected argument `num_groups` to be an int larger than 1, but got {num_groups}")
        self.num_groups = num_groups
        self.threshold = threshold
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self._create_states(num_groups)

    def update(self, preds: Array, target: Array, groups: Array) -> None:
        group_stats = _binary_groups_stat_scores(
            preds, target, groups, self.num_groups, self.threshold, self.ignore_index, self.validate_args
        )
        self._update_states(group_stats)

    def compute(self) -> Dict[str, Array]:
        group_stats = [(self.tp[g], self.fp[g], self.tn[g], self.fn[g]) for g in range(self.num_groups)]
        return _groups_reduce(group_stats)


class BinaryFairness(_AbstractGroupStatScores):
    """Demographic parity / equal opportunity between groups
    (reference group_fairness.py:129).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import BinaryFairness
        >>> metric = BinaryFairness(num_groups=2)
        >>> metric.update(jnp.asarray([0.11, 0.84, 0.22, 0.73]), jnp.asarray([0, 1, 0, 1]),
        ...               jnp.asarray([0, 1, 0, 1]))
        >>> sorted(metric.compute().keys())
        ['DP_0_1', 'EO_0_1']
    """

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = None
    full_state_update: bool = False

    def __init__(
        self,
        num_groups: int,
        task: str = "all",
        threshold: float = 0.5,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if task not in ["demographic_parity", "equal_opportunity", "all"]:
            raise ValueError(
                f"Expected argument `task` to either be ``demographic_parity``,"
                f"``equal_opportunity`` or ``all`` but got {task}."
            )
        if validate_args and (not isinstance(num_groups, int) or num_groups < 2):
            raise ValueError(f"Expected argument `num_groups` to be an int larger than 1, but got {num_groups}")
        self.num_groups = num_groups
        self.task = task
        self.threshold = threshold
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self._create_states(num_groups)

    def update(self, preds: Array, target: Optional[Array], groups: Array) -> None:
        if self.task == "demographic_parity":
            target = jnp.zeros_like(jnp.asarray(preds), dtype=jnp.int32)
        group_stats = _binary_groups_stat_scores(
            preds, target, groups, self.num_groups, self.threshold, self.ignore_index, self.validate_args
        )
        self._update_states(group_stats)

    def compute(self) -> Dict[str, Array]:
        transformed = _groups_stat_transform(
            [(self.tp[g], self.fp[g], self.tn[g], self.fn[g]) for g in range(self.num_groups)]
        )
        if self.task == "demographic_parity":
            return _compute_binary_demographic_parity(**transformed)
        if self.task == "equal_opportunity":
            return _compute_binary_equal_opportunity(**transformed)
        return {
            **_compute_binary_demographic_parity(**transformed),
            **_compute_binary_equal_opportunity(**transformed),
        }
