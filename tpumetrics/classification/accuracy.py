"""Modular accuracy metrics (counterpart of reference ``classification/accuracy.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax

from tpumetrics.classification.base import _ClassificationTaskWrapper
from tpumetrics.classification.stat_scores import BinaryStatScores, MulticlassStatScores, MultilabelStatScores
from tpumetrics.functional.classification.accuracy import _accuracy_reduce
from tpumetrics.metric import Metric
from tpumetrics.utils.enums import ClassificationTask

Array = jax.Array


class BinaryAccuracy(BinaryStatScores):
    """Binary accuracy: fraction of correct predictions.

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import BinaryAccuracy
        >>> target = jnp.asarray([0, 1, 0, 1, 0, 1])
        >>> preds = jnp.asarray([0, 0, 1, 1, 0, 1])
        >>> metric = BinaryAccuracy()
        >>> metric.update(preds, target)
        >>> round(float(metric.compute()), 4)
        0.6667
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _accuracy_reduce(tp, fp, tn, fn, average="binary", multidim_average=self.multidim_average)


class MulticlassAccuracy(MulticlassStatScores):
    """Multiclass accuracy.

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import MulticlassAccuracy
        >>> target = jnp.asarray([2, 1, 0, 0])
        >>> preds = jnp.asarray([2, 1, 0, 1])
        >>> metric = MulticlassAccuracy(num_classes=3)
        >>> metric.update(preds, target)
        >>> round(float(metric.compute()), 4)
        0.8333
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0
    plot_legend_name: str = "Class"

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _accuracy_reduce(tp, fp, tn, fn, average=self.average, multidim_average=self.multidim_average)


class MultilabelAccuracy(MultilabelStatScores):
    """Multilabel accuracy.

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import MultilabelAccuracy
        >>> target = jnp.asarray([[0, 1, 0], [1, 0, 1]])
        >>> preds = jnp.asarray([[0, 0, 1], [1, 0, 1]])
        >>> metric = MultilabelAccuracy(num_labels=3)
        >>> metric.update(preds, target)
        >>> round(float(metric.compute()), 4)
        0.6667
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0
    plot_legend_name: str = "Label"

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _accuracy_reduce(
            tp, fp, tn, fn, average=self.average, multidim_average=self.multidim_average, multilabel=True
        )


class Accuracy(_ClassificationTaskWrapper):
    """Task-string wrapper: ``Accuracy(task="multiclass", num_classes=5)``
    (reference classification/accuracy.py task dispatch).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import Accuracy
        >>> target = jnp.asarray([0, 1, 2, 3])
        >>> preds = jnp.asarray([0, 2, 1, 3])
        >>> metric = Accuracy(task="multiclass", num_classes=4)
        >>> metric.update(preds, target)
        >>> float(metric.compute())
        0.5
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        average: Optional[str] = "micro",
        multidim_average: str = "global",
        top_k: Optional[int] = 1,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str(task)
        kwargs.update(
            {"multidim_average": multidim_average, "ignore_index": ignore_index, "validate_args": validate_args}
        )
        if task == ClassificationTask.BINARY:
            return BinaryAccuracy(threshold, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
            if not isinstance(top_k, int):
                raise ValueError(f"`top_k` is expected to be `int` but `{type(top_k)} was passed.`")
            return MulticlassAccuracy(num_classes, top_k, average, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
            return MultilabelAccuracy(num_labels, threshold, average, **kwargs)
        raise ValueError(f"Not handled value: {task}")
