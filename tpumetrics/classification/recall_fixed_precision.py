"""Modular RecallAtFixedPrecision metrics (counterpart of reference
``classification/recall_fixed_precision.py``)."""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax

from tpumetrics.classification.base import _ClassificationTaskWrapper
from tpumetrics.classification.precision_recall_curve import (
    BinaryPrecisionRecallCurve,
    _AtFixedValuePlotMixin,
    MulticlassPrecisionRecallCurve,
    MultilabelPrecisionRecallCurve,
)
from tpumetrics.functional.classification.precision_recall_curve import Thresholds
from tpumetrics.functional.classification.recall_fixed_precision import (
    _binary_recall_at_fixed_precision_arg_validation,
    _binary_recall_at_fixed_precision_compute,
    _multiclass_recall_at_fixed_precision_arg_validation,
    _multiclass_recall_at_fixed_precision_compute,
    _multilabel_recall_at_fixed_precision_arg_validation,
    _multilabel_recall_at_fixed_precision_compute,
)
from tpumetrics.metric import Metric
from tpumetrics.utils.enums import ClassificationTask

Array = jax.Array


class BinaryRecallAtFixedPrecision(_AtFixedValuePlotMixin, BinaryPrecisionRecallCurve):
    """Max recall subject to precision >= min_precision, binary (reference
    classification/recall_fixed_precision.py:29).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import BinaryRecallAtFixedPrecision
        >>> metric = BinaryRecallAtFixedPrecision(min_precision=0.5)
        >>> metric.update(jnp.asarray([0.1, 0.4, 0.35, 0.8]), jnp.asarray([0, 0, 1, 1]))
        >>> recall, threshold = metric.compute()
        >>> (round(float(recall), 4), round(float(threshold), 4))
        (1.0, 0.35)
    """

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = None
    full_state_update: bool = False

    def __init__(
        self,
        min_precision: float,
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(thresholds=thresholds, ignore_index=ignore_index, validate_args=False, **kwargs)
        if validate_args:
            _binary_recall_at_fixed_precision_arg_validation(min_precision, thresholds, ignore_index)
        self.validate_args = validate_args
        self.min_precision = min_precision

    def compute(self) -> Tuple[Array, Array]:
        return _binary_recall_at_fixed_precision_compute(
            self._final_state(), self.thresholds, self.min_precision
        )


class MulticlassRecallAtFixedPrecision(_AtFixedValuePlotMixin, MulticlassPrecisionRecallCurve):
    """Per-class max recall subject to precision >= min_precision (reference
    classification/recall_fixed_precision.py:136).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import MulticlassRecallAtFixedPrecision
        >>> metric = MulticlassRecallAtFixedPrecision(num_classes=3, min_precision=0.5)
        >>> metric.update(jnp.asarray([[0.8, 0.1, 0.1], [0.1, 0.8, 0.1], [0.1, 0.1, 0.8]]),
        ...               jnp.asarray([0, 1, 2]))
        >>> recall, thresholds = metric.compute()
        >>> recall.tolist()
        [1.0, 1.0, 1.0]
    """

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = None
    full_state_update: bool = False
    plot_legend_name: str = "Class"

    def __init__(
        self,
        num_classes: int,
        min_precision: float,
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            num_classes=num_classes, thresholds=thresholds, average=None,
            ignore_index=ignore_index, validate_args=False, **kwargs,
        )
        if validate_args:
            _multiclass_recall_at_fixed_precision_arg_validation(
                num_classes, min_precision, thresholds, ignore_index
            )
        self.validate_args = validate_args
        self.min_precision = min_precision

    def compute(self) -> Tuple[Array, Array]:
        return _multiclass_recall_at_fixed_precision_compute(
            self._final_state(), self.num_classes, self.thresholds, self.min_precision
        )


class MultilabelRecallAtFixedPrecision(_AtFixedValuePlotMixin, MultilabelPrecisionRecallCurve):
    """Per-label max recall subject to precision >= min_precision (reference
    classification/recall_fixed_precision.py:247).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import MultilabelRecallAtFixedPrecision
        >>> metric = MultilabelRecallAtFixedPrecision(num_labels=2, min_precision=0.5)
        >>> metric.update(jnp.asarray([[0.8, 0.1], [0.1, 0.8]]), jnp.asarray([[1, 0], [0, 1]]))
        >>> recall, thresholds = metric.compute()
        >>> recall.tolist()
        [1.0, 1.0]
    """

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = None
    full_state_update: bool = False
    plot_legend_name: str = "Label"

    def __init__(
        self,
        num_labels: int,
        min_precision: float,
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            num_labels=num_labels, thresholds=thresholds, ignore_index=ignore_index,
            validate_args=False, **kwargs,
        )
        if validate_args:
            _multilabel_recall_at_fixed_precision_arg_validation(
                num_labels, min_precision, thresholds, ignore_index
            )
        self.validate_args = validate_args
        self.min_precision = min_precision

    def compute(self) -> Tuple[Array, Array]:
        return _multilabel_recall_at_fixed_precision_compute(
            self._final_state(), self.num_labels, self.thresholds, self.ignore_index, self.min_precision
        )


class RecallAtFixedPrecision(_ClassificationTaskWrapper):
    """Task-string wrapper (reference classification/recall_fixed_precision.py:358).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics import RecallAtFixedPrecision
        >>> probs = jnp.asarray([0.11, 0.84, 0.22, 0.73, 0.33, 0.92])
        >>> target = jnp.asarray([0, 1, 0, 1, 0, 1])
        >>> metric = RecallAtFixedPrecision(task="binary", min_precision=0.5)
        >>> metric.update(probs, target)
        >>> [round(float(v), 4) for v in metric.compute()]
        [1.0, 0.73]
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        min_precision: float,
        thresholds: Thresholds = None,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str(task)
        kwargs.update({"thresholds": thresholds, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryRecallAtFixedPrecision(min_precision, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
            return MulticlassRecallAtFixedPrecision(num_classes, min_precision, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
            return MultilabelRecallAtFixedPrecision(num_labels, min_precision, **kwargs)
        raise ValueError(f"Not handled value: {task}")
