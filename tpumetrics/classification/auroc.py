"""Modular AUROC metrics (counterpart of reference ``classification/auroc.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax

from tpumetrics.classification.base import _ClassificationTaskWrapper
from tpumetrics.classification.precision_recall_curve import (
    BinaryPrecisionRecallCurve,
    MulticlassPrecisionRecallCurve,
    MultilabelPrecisionRecallCurve,
)
from tpumetrics.functional.classification.auroc import (
    _binary_auroc_arg_validation,
    _binary_auroc_compute,
    _multiclass_auroc_arg_validation,
    _multiclass_auroc_compute,
    _multilabel_auroc_arg_validation,
    _multilabel_auroc_compute,
)
from tpumetrics.functional.classification.precision_recall_curve import Thresholds
from tpumetrics.metric import Metric
from tpumetrics.utils.enums import ClassificationTask

Array = jax.Array


class BinaryAUROC(BinaryPrecisionRecallCurve):
    """Area under the ROC curve, binary tasks (reference classification/auroc.py:35).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import BinaryAUROC
        >>> metric = BinaryAUROC()
        >>> metric.update(jnp.asarray([0.1, 0.4, 0.35, 0.8]), jnp.asarray([0, 0, 1, 1]))
        >>> round(float(metric.compute()), 4)
        0.75
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        max_fpr: Optional[float] = None,
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(thresholds=thresholds, ignore_index=ignore_index, validate_args=False, **kwargs)
        if validate_args:
            _binary_auroc_arg_validation(max_fpr, thresholds, ignore_index)
        self.max_fpr = max_fpr
        self.validate_args = validate_args

    def compute(self) -> Array:
        return _binary_auroc_compute(self._final_state(), self.thresholds, self.max_fpr)

    def plot(self, val: Any = None, ax: Any = None) -> Any:
        return self._plot(val, ax)


class MulticlassAUROC(MulticlassPrecisionRecallCurve):
    """AUROC over one-vs-rest curves, multiclass (reference classification/auroc.py:146).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import MulticlassAUROC
        >>> metric = MulticlassAUROC(num_classes=3)
        >>> metric.update(jnp.asarray([[0.8, 0.1, 0.1], [0.1, 0.8, 0.1], [0.1, 0.1, 0.8]]),
        ...               jnp.asarray([0, 1, 2]))
        >>> round(float(metric.compute()), 4)
        1.0
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0
    plot_legend_name: str = "Class"

    def __init__(
        self,
        num_classes: int,
        average: Optional[str] = "macro",
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        # curve-state average stays None; `average` here is the AUC reduction
        super().__init__(
            num_classes=num_classes, thresholds=thresholds, average=None,
            ignore_index=ignore_index, validate_args=False, **kwargs,
        )
        if validate_args:
            _multiclass_auroc_arg_validation(num_classes, average, thresholds, ignore_index)
        self.average_auroc = average
        self.validate_args = validate_args

    def compute(self) -> Array:
        return _multiclass_auroc_compute(
            self._final_state(), self.num_classes, self.average_auroc, self.thresholds
        )

    def plot(self, val: Any = None, ax: Any = None) -> Any:
        return self._plot(val, ax)


class MultilabelAUROC(MultilabelPrecisionRecallCurve):
    """AUROC over per-label curves, multilabel (reference classification/auroc.py:263).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import MultilabelAUROC
        >>> metric = MultilabelAUROC(num_labels=2)
        >>> metric.update(jnp.asarray([[0.8, 0.1], [0.1, 0.8]]), jnp.asarray([[1, 0], [0, 1]]))
        >>> round(float(metric.compute()), 4)
        1.0
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0
    plot_legend_name: str = "Label"

    def __init__(
        self,
        num_labels: int,
        average: Optional[str] = "macro",
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            num_labels=num_labels, thresholds=thresholds, ignore_index=ignore_index,
            validate_args=False, **kwargs,
        )
        if validate_args:
            _multilabel_auroc_arg_validation(num_labels, average, thresholds, ignore_index)
        self.average_auroc = average
        self.validate_args = validate_args

    def compute(self) -> Array:
        return _multilabel_auroc_compute(
            self._final_state(), self.num_labels, self.average_auroc, self.thresholds, self.ignore_index
        )

    def plot(self, val: Any = None, ax: Any = None) -> Any:
        return self._plot(val, ax)


class AUROC(_ClassificationTaskWrapper):
    """Task-string wrapper for AUROC (reference classification/auroc.py:391).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics import AUROC
        >>> probs = jnp.asarray([0.11, 0.84, 0.22, 0.73, 0.33, 0.92])
        >>> target = jnp.asarray([0, 1, 0, 1, 0, 1])
        >>> metric = AUROC(task="binary", thresholds=8)
        >>> metric.update(probs, target)
        >>> round(float(metric.compute()), 4)
        1.0
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        thresholds: Thresholds = None,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        average: Optional[str] = "macro",
        max_fpr: Optional[float] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str(task)
        kwargs.update({"thresholds": thresholds, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryAUROC(max_fpr, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
            return MulticlassAUROC(num_classes, average, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
            return MultilabelAUROC(num_labels, average, **kwargs)
        raise ValueError(f"Not handled value: {task}")
