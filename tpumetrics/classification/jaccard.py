"""Modular Jaccard index metrics (counterpart of reference
``classification/jaccard.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax

from tpumetrics.classification.base import _ClassificationTaskWrapper
from tpumetrics.classification.confusion_matrix import (
    BinaryConfusionMatrix,
    MulticlassConfusionMatrix,
    MultilabelConfusionMatrix,
)
from tpumetrics.functional.classification.jaccard import _jaccard_index_reduce
from tpumetrics.metric import Metric
from tpumetrics.utils.enums import ClassificationTask

Array = jax.Array


class BinaryJaccardIndex(BinaryConfusionMatrix):
    """Jaccard index / IoU, binary (reference classification/jaccard.py:30).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import BinaryJaccardIndex
        >>> metric = BinaryJaccardIndex()
        >>> metric.update(jnp.asarray([0.35, 0.85, 0.48, 0.01]), jnp.asarray([1, 1, 0, 0]))
        >>> round(float(metric.compute()), 4)
        0.5
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        threshold: float = 0.5,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            threshold=threshold, normalize=None, ignore_index=ignore_index, validate_args=validate_args, **kwargs
        )

    def compute(self) -> Array:
        return _jaccard_index_reduce(self.confmat, average="binary")

    def plot(self, val: Any = None, ax: Any = None) -> Any:
        return self._plot(val, ax)


class MulticlassJaccardIndex(MulticlassConfusionMatrix):
    """Jaccard index, multiclass (reference classification/jaccard.py:137).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import MulticlassJaccardIndex
        >>> metric = MulticlassJaccardIndex(num_classes=3)
        >>> metric.update(jnp.asarray([2, 1, 0, 1]), jnp.asarray([2, 1, 0, 0]))
        >>> round(float(metric.compute()), 4)
        0.6667
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0
    plot_legend_name: str = "Class"

    def __init__(
        self,
        num_classes: int,
        average: Optional[str] = "macro",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            num_classes=num_classes, normalize=None, ignore_index=ignore_index,
            validate_args=validate_args, **kwargs,
        )
        if validate_args and average not in ("micro", "macro", "weighted", "none", None):
            raise ValueError(
                f"Expected argument `average` to be one of ('micro', 'macro', 'weighted', 'none', None)"
                f" but got {average}"
            )
        self.average = average

    def compute(self) -> Array:
        return _jaccard_index_reduce(self.confmat, average=self.average, ignore_index=self.ignore_index)

    def plot(self, val: Any = None, ax: Any = None) -> Any:
        return self._plot(val, ax)


class MultilabelJaccardIndex(MultilabelConfusionMatrix):
    """Jaccard index, multilabel (reference classification/jaccard.py:248).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import MultilabelJaccardIndex
        >>> metric = MultilabelJaccardIndex(num_labels=3)
        >>> metric.update(jnp.asarray([[0, 0, 1], [1, 0, 1]]), jnp.asarray([[0, 1, 0], [1, 0, 1]]))
        >>> round(float(metric.compute()), 4)
        0.5
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0
    plot_legend_name: str = "Label"

    def __init__(
        self,
        num_labels: int,
        threshold: float = 0.5,
        average: Optional[str] = "macro",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            num_labels=num_labels, threshold=threshold, normalize=None, ignore_index=ignore_index,
            validate_args=validate_args, **kwargs,
        )
        if validate_args and average not in ("micro", "macro", "weighted", "none", None):
            raise ValueError(
                f"Expected argument `average` to be one of ('micro', 'macro', 'weighted', 'none', None)"
                f" but got {average}"
            )
        self.average = average

    def compute(self) -> Array:
        return _jaccard_index_reduce(self.confmat, average=self.average, ignore_index=self.ignore_index)

    def plot(self, val: Any = None, ax: Any = None) -> Any:
        return self._plot(val, ax)


class JaccardIndex(_ClassificationTaskWrapper):
    """Task-string wrapper (reference classification/jaccard.py:357).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics import JaccardIndex
        >>> logits = jnp.asarray([[2.0, 0.5, 0.1], [0.3, 2.1, 0.2], [0.2, 0.3, 2.2], [2.0, 0.1, 0.4]])
        >>> target = jnp.asarray([0, 1, 2, 1])
        >>> metric = JaccardIndex(task="multiclass", num_classes=3)
        >>> metric.update(logits, target)
        >>> round(float(metric.compute()), 4)
        0.6667
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        average: Optional[str] = "macro",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str(task)
        kwargs.update({"ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryJaccardIndex(threshold, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
            return MulticlassJaccardIndex(num_classes, average, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
            return MultilabelJaccardIndex(num_labels, threshold, average, **kwargs)
        raise ValueError(f"Not handled value: {task}")
