"""Modular stat-scores metrics: the shared tp/fp/tn/fn state machine.

Counterpart of reference ``classification/stat_scores.py`` —
``_AbstractStatScores`` (:43-88) keeps tensor states with "sum" reduce for
``multidim_average="global"`` and list states with "cat" reduce for
``"samplewise"``; Binary/Multiclass/Multilabel subclasses feed it via the L2
functional helpers.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from tpumetrics.classification.base import _ClassificationTaskWrapper
from tpumetrics.functional.classification.stat_scores import (
    _binary_stat_scores_arg_validation,
    _binary_stat_scores_compute,
    _binary_stat_scores_format,
    _binary_stat_scores_tensor_validation,
    _binary_stat_scores_update,
    _multiclass_stat_scores_arg_validation,
    _multiclass_stat_scores_compute,
    _multiclass_stat_scores_format,
    _multiclass_stat_scores_tensor_validation,
    _multiclass_stat_scores_update,
    _multilabel_stat_scores_arg_validation,
    _multilabel_stat_scores_compute,
    _multilabel_stat_scores_format,
    _multilabel_stat_scores_tensor_validation,
    _multilabel_stat_scores_update,
)
from tpumetrics.metric import Metric
from tpumetrics.utils.data import _count_dtype, dim_zero_cat
from tpumetrics.utils.enums import ClassificationTask

Array = jax.Array


class _AbstractStatScores(Metric):
    """Shared tp/fp/tn/fn state machine (reference classification/stat_scores.py:43-88)."""

    tp: Any
    fp: Any
    tn: Any
    fn: Any

    def _create_state(self, size: int, multidim_average: str = "global") -> None:
        """Tensor states + "sum" for global; list states + "cat" for samplewise."""
        default: Any
        if multidim_average == "samplewise":
            default = lambda: []  # noqa: E731
            dist_reduce_fx = "cat"
        else:
            default = lambda: jnp.zeros(size, dtype=_count_dtype())  # noqa: E731
            dist_reduce_fx = "sum"
        for name in ("tp", "fp", "tn", "fn"):
            self.add_state(name, default(), dist_reduce_fx=dist_reduce_fx)

    def _update_state(self, tp: Array, fp: Array, tn: Array, fn: Array) -> None:
        if isinstance(self.tp, list):
            self.tp.append(tp)
            self.fp.append(fp)
            self.tn.append(tn)
            self.fn.append(fn)
        else:
            self.tp = self.tp + tp
            self.fp = self.fp + fp
            self.tn = self.tn + tn
            self.fn = self.fn + fn

    def _final_state(self) -> tuple:
        """Concatenate list states / return tensor states."""
        tp = dim_zero_cat(self.tp)
        fp = dim_zero_cat(self.fp)
        tn = dim_zero_cat(self.tn)
        fn = dim_zero_cat(self.fn)
        return tp, fp, tn, fn


class BinaryStatScores(_AbstractStatScores):
    """tp/fp/tn/fn for binary classification (reference classification/stat_scores.py:95).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import BinaryStatScores
        >>> target = jnp.asarray([0, 1, 0, 1, 0, 1])
        >>> preds = jnp.asarray([0, 0, 1, 1, 0, 1])
        >>> metric = BinaryStatScores()
        >>> metric.update(preds, target)
        >>> metric.compute().tolist()
        [2, 1, 2, 1, 3]
    """

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = None
    full_state_update: bool = False

    def __init__(
        self,
        threshold: float = 0.5,
        multidim_average: str = "global",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _binary_stat_scores_arg_validation(threshold, multidim_average, ignore_index)
        self.threshold = threshold
        self.multidim_average = multidim_average
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self._create_state(size=1, multidim_average=multidim_average)

    def update(self, preds: Array, target: Array) -> None:
        if self.validate_args:
            _binary_stat_scores_tensor_validation(preds, target, self.multidim_average, self.ignore_index)
        preds, target, mask = _binary_stat_scores_format(preds, target, self.threshold, self.ignore_index)
        tp, fp, tn, fn = _binary_stat_scores_update(preds, target, mask, self.multidim_average)
        self._update_state(tp, fp, tn, fn)

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _binary_stat_scores_compute(tp, fp, tn, fn, self.multidim_average)


class MulticlassStatScores(_AbstractStatScores):
    """Per-class tp/fp/tn/fn for multiclass classification
    (reference classification/stat_scores.py:215).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import MulticlassStatScores
        >>> target = jnp.asarray([2, 1, 0, 0])
        >>> preds = jnp.asarray([2, 1, 0, 1])
        >>> metric = MulticlassStatScores(num_classes=3, average='micro')
        >>> metric.update(preds, target)
        >>> metric.compute().tolist()
        [3, 1, 7, 1, 4]
    """

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = None
    full_state_update: bool = False

    def __init__(
        self,
        num_classes: int,
        top_k: int = 1,
        average: Optional[str] = "macro",
        multidim_average: str = "global",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multiclass_stat_scores_arg_validation(num_classes, top_k, average, multidim_average, ignore_index)
        self.num_classes = num_classes
        self.top_k = top_k
        self.average = average
        self.multidim_average = multidim_average
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self._create_state(size=num_classes, multidim_average=multidim_average)

    def update(self, preds: Array, target: Array) -> None:
        if self.validate_args:
            _multiclass_stat_scores_tensor_validation(
                preds, target, self.num_classes, self.multidim_average, self.ignore_index
            )
        preds, target, mask = _multiclass_stat_scores_format(
            preds, target, self.num_classes, self.ignore_index, self.top_k
        )
        tp, fp, tn, fn = _multiclass_stat_scores_update(
            preds, target, mask, self.num_classes, self.top_k, self.average, self.multidim_average
        )
        self._update_state(tp, fp, tn, fn)

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _multiclass_stat_scores_compute(tp, fp, tn, fn, self.average, self.multidim_average)


class MultilabelStatScores(_AbstractStatScores):
    """Per-label tp/fp/tn/fn for multilabel classification
    (reference classification/stat_scores.py:357).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import MultilabelStatScores
        >>> target = jnp.asarray([[0, 1, 0], [1, 0, 1]])
        >>> preds = jnp.asarray([[0, 0, 1], [1, 0, 1]])
        >>> metric = MultilabelStatScores(num_labels=3, average='micro')
        >>> metric.update(preds, target)
        >>> metric.compute().tolist()
        [2, 1, 2, 1, 3]
    """

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = None
    full_state_update: bool = False

    def __init__(
        self,
        num_labels: int,
        threshold: float = 0.5,
        average: Optional[str] = "macro",
        multidim_average: str = "global",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multilabel_stat_scores_arg_validation(num_labels, threshold, average, multidim_average, ignore_index)
        self.num_labels = num_labels
        self.threshold = threshold
        self.average = average
        self.multidim_average = multidim_average
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self._create_state(size=num_labels, multidim_average=multidim_average)

    def update(self, preds: Array, target: Array) -> None:
        if self.validate_args:
            _multilabel_stat_scores_tensor_validation(
                preds, target, self.num_labels, self.multidim_average, self.ignore_index
            )
        preds, target, mask = _multilabel_stat_scores_format(
            preds, target, self.num_labels, self.threshold, self.ignore_index
        )
        tp, fp, tn, fn = _multilabel_stat_scores_update(preds, target, mask, self.multidim_average)
        self._update_state(tp, fp, tn, fn)

    def compute(self) -> Array:
        tp, fp, tn, fn = self._final_state()
        return _multilabel_stat_scores_compute(tp, fp, tn, fn, self.average, self.multidim_average)


class StatScores(_ClassificationTaskWrapper):
    """Task-string wrapper: ``StatScores(task="binary", ...)`` resolves to the
    concrete metric (reference classification/stat_scores.py:480, ``__new__`` dispatch).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics import StatScores
        >>> probs = jnp.asarray([0.11, 0.84, 0.22, 0.73, 0.33, 0.92])
        >>> target = jnp.asarray([0, 1, 0, 1, 0, 1])
        >>> metric = StatScores(task="binary")
        >>> metric.update(probs, target)
        >>> metric.compute()
        Array([3, 0, 3, 0, 3], dtype=int32)
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        average: Optional[str] = "micro",
        multidim_average: str = "global",
        top_k: Optional[int] = 1,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str(task)
        kwargs.update(
            {"multidim_average": multidim_average, "ignore_index": ignore_index, "validate_args": validate_args}
        )
        if task == ClassificationTask.BINARY:
            return BinaryStatScores(threshold, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
            if not isinstance(top_k, int):
                raise ValueError(f"`top_k` is expected to be `int` but `{type(top_k)} was passed.`")
            return MulticlassStatScores(num_classes, top_k, average, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
            return MultilabelStatScores(num_labels, threshold, average, **kwargs)
        raise ValueError(f"Not handled value: {task}")
