"""Modular Dice metric (counterpart of reference ``classification/dice.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from tpumetrics.functional.classification.dice import _dice_format, _dice_samplewise
from tpumetrics.metric import Metric
from tpumetrics.utils.compute import _safe_divide

Array = jax.Array


class Dice(Metric):
    """Dice = 2*TP / (2*TP + FP + FN) (reference classification/dice.py:33).

    ``average='micro'``/``'samples'`` keep scalar accumulators; the per-class
    averages (``'macro'``/``'weighted'``/``'none'``) require ``num_classes``.

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import Dice
        >>> metric = Dice(average='micro')
        >>> metric.update(jnp.asarray([2, 0, 2, 1]), jnp.asarray([1, 1, 2, 0]))
        >>> round(float(metric.compute()), 4)
        0.25
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    tp: Array
    fp: Array
    fn: Array

    def __init__(
        self,
        zero_division: int = 0,
        num_classes: Optional[int] = None,
        threshold: float = 0.5,
        average: Optional[str] = "micro",
        mdmc_average: Optional[str] = "global",
        ignore_index: Optional[int] = None,
        top_k: Optional[int] = None,
        multiclass: Optional[bool] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        allowed_average = ("micro", "macro", "weighted", "samples", "none", None)
        if average not in allowed_average:
            raise ValueError(f"The `average` has to be one of {allowed_average}, got {average}.")
        if average in ("macro", "weighted", "none", None) and num_classes is None:
            raise ValueError(f"When you set `average` as {average}, you have to provide the number of classes.")
        if num_classes is not None and ignore_index is not None and not 0 <= ignore_index < num_classes:
            raise ValueError(f"The `ignore_index` {ignore_index} is not valid for inputs with {num_classes} classes")
        if mdmc_average not in (None, "samplewise", "global"):
            raise ValueError(f"The `mdmc_average` {mdmc_average} is not valid.")
        if mdmc_average == "samplewise" and average not in ("micro", "macro"):
            raise ValueError(
                "mdmc_average='samplewise' supports average in ('micro', 'macro') here"
            )
        if multiclass is False:
            raise NotImplementedError(
                "The deprecated `multiclass=False` binary reinterpretation is not supported;"
                " use BinaryF1Score (Dice == F1 for binary inputs) instead."
            )
        self.zero_division = zero_division
        self.num_classes = num_classes
        self.threshold = threshold
        self.average = average
        self.mdmc_average = mdmc_average
        self.ignore_index = ignore_index
        self.top_k = top_k
        self.multiclass = multiclass

        if average == "samples" or mdmc_average == "samplewise":
            # samplewise-style accumulation never touches tp/fp/fn — don't
            # register dead states that would ride every sync and checkpoint
            self.add_state("sample_score", jnp.zeros(()), dist_reduce_fx="sum")
            self.add_state("sample_total", jnp.zeros(()), dist_reduce_fx="sum")
        else:
            size = 1 if average == "micro" else num_classes
            default = lambda: jnp.zeros(size, dtype=jnp.float32)  # noqa: E731
            self.add_state("tp", default(), dist_reduce_fx="sum")
            self.add_state("fp", default(), dist_reduce_fx="sum")
            self.add_state("fn", default(), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        preds_oh, target_oh, n_cls = _dice_format(preds, target, self.threshold, self.top_k, self.num_classes)
        # tpulint: disable-next=TPL102 -- n_cls is a host int from the eager-only dice format helper; Dice is eager-only by reference contract
        if self.ignore_index is not None and 0 <= self.ignore_index < n_cls:
            keep = jnp.ones(n_cls).at[self.ignore_index].set(0.0).astype(jnp.int32)
            preds_oh = preds_oh * keep
            target_oh = target_oh * keep

        if self.mdmc_average is None and target.ndim > 1:
            raise ValueError(
                "When your inputs are multi-dimensional multi-class, you have to set the"
                " `mdmc_average` parameter ('global' or 'samplewise')."
            )
        if self.mdmc_average == "samplewise":
            # per ORIGINAL sample: stats over that sample's positions, the
            # class-average applied within the sample, then a mean over
            # samples (the deprecated stat-scores mdmc_reduce='samplewise',
            # reference dice.py:82-96); a standard (N, C)/(N,) batch makes
            # each row a one-position sample, matching the reference's
            # measured behavior on 2-D scores (its 1-D path crashes)
            score_sum, count = _dice_samplewise(
                preds, target, preds_oh, target_oh, n_cls, self.average,
                self.zero_division, self.ignore_index,
            )
            self.sample_score = self.sample_score + score_sum
            self.sample_total = self.sample_total + count
            return

        if self.average == "samples":
            tp = jnp.sum(preds_oh * target_oh, axis=1).astype(jnp.float32)
            fp = jnp.sum(preds_oh * (1 - target_oh), axis=1).astype(jnp.float32)
            fn = jnp.sum((1 - preds_oh) * target_oh, axis=1).astype(jnp.float32)
            scores = _safe_divide(2.0 * tp, 2.0 * tp + fp + fn, self.zero_division)
            self.sample_score = self.sample_score + scores.sum()
            self.sample_total = self.sample_total + scores.shape[0]
            return

        tp = jnp.sum(preds_oh * target_oh, axis=0).astype(jnp.float32)
        fp = jnp.sum(preds_oh * (1 - target_oh), axis=0).astype(jnp.float32)
        fn = jnp.sum((1 - preds_oh) * target_oh, axis=0).astype(jnp.float32)
        if self.average == "micro":
            tp, fp, fn = tp.sum(keepdims=True), fp.sum(keepdims=True), fn.sum(keepdims=True)
        self.tp = self.tp + tp
        self.fp = self.fp + fp
        self.fn = self.fn + fn

    def compute(self) -> Array:
        # routing is on host-side config only, so functional_compute stays
        # jittable
        if self.average == "samples" or self.mdmc_average == "samplewise":
            return self.sample_score / self.sample_total
        if self.average == "micro":
            return _safe_divide(2.0 * self.tp[0], 2.0 * self.tp[0] + self.fp[0] + self.fn[0], self.zero_division)
        scores = _safe_divide(2.0 * self.tp, 2.0 * self.tp + self.fp + self.fn, self.zero_division)
        if self.average in ("none", None):
            return scores
        if self.average == "weighted":
            weights = self.tp + self.fn
            return jnp.sum(scores * _safe_divide(weights, weights.sum()))
        present = ((self.tp + self.fp + self.fn) > 0).astype(scores.dtype)
        if self.ignore_index is not None and self.num_classes and 0 <= self.ignore_index < self.num_classes:
            present = present.at[self.ignore_index].set(0.0)
        return jnp.sum(scores * present) / jnp.maximum(present.sum(), 1.0)

    def plot(self, val: Any = None, ax: Any = None) -> Any:
        return self._plot(val, ax)
