"""Modular AveragePrecision metrics (counterpart of reference
``classification/average_precision.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax

from tpumetrics.classification.base import _ClassificationTaskWrapper
from tpumetrics.classification.precision_recall_curve import (
    BinaryPrecisionRecallCurve,
    MulticlassPrecisionRecallCurve,
    MultilabelPrecisionRecallCurve,
)
from tpumetrics.functional.classification.average_precision import (
    _binary_average_precision_compute,
    _multiclass_average_precision_arg_validation,
    _multiclass_average_precision_compute,
    _multilabel_average_precision_arg_validation,
    _multilabel_average_precision_compute,
)
from tpumetrics.functional.classification.precision_recall_curve import Thresholds
from tpumetrics.metric import Metric
from tpumetrics.utils.enums import ClassificationTask

Array = jax.Array


class BinaryAveragePrecision(BinaryPrecisionRecallCurve):
    """Average precision for binary tasks (reference classification/average_precision.py:34).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import BinaryAveragePrecision
        >>> metric = BinaryAveragePrecision()
        >>> metric.update(jnp.asarray([0.1, 0.4, 0.35, 0.8]), jnp.asarray([0, 0, 1, 1]))
        >>> round(float(metric.compute()), 4)
        0.8333
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def compute(self) -> Array:
        return _binary_average_precision_compute(self._final_state(), self.thresholds)

    def plot(self, val: Any = None, ax: Any = None) -> Any:
        return self._plot(val, ax)


class MulticlassAveragePrecision(MulticlassPrecisionRecallCurve):
    """Average precision over one-vs-rest PR curves, multiclass (reference
    classification/average_precision.py:143).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import MulticlassAveragePrecision
        >>> metric = MulticlassAveragePrecision(num_classes=3)
        >>> metric.update(jnp.asarray([[0.8, 0.1, 0.1], [0.1, 0.8, 0.1], [0.1, 0.1, 0.8]]),
        ...               jnp.asarray([0, 1, 2]))
        >>> round(float(metric.compute()), 4)
        1.0
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0
    plot_legend_name: str = "Class"

    def __init__(
        self,
        num_classes: int,
        average: Optional[str] = "macro",
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            num_classes=num_classes, thresholds=thresholds, average=None,
            ignore_index=ignore_index, validate_args=False, **kwargs,
        )
        if validate_args:
            _multiclass_average_precision_arg_validation(num_classes, average, thresholds, ignore_index)
        self.average_ap = average
        self.validate_args = validate_args

    def compute(self) -> Array:
        return _multiclass_average_precision_compute(
            self._final_state(), self.num_classes, self.average_ap, self.thresholds
        )

    def plot(self, val: Any = None, ax: Any = None) -> Any:
        return self._plot(val, ax)


class MultilabelAveragePrecision(MultilabelPrecisionRecallCurve):
    """Average precision over per-label PR curves, multilabel (reference
    classification/average_precision.py:261).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import MultilabelAveragePrecision
        >>> metric = MultilabelAveragePrecision(num_labels=2)
        >>> metric.update(jnp.asarray([[0.8, 0.1], [0.1, 0.8]]), jnp.asarray([[1, 0], [0, 1]]))
        >>> round(float(metric.compute()), 4)
        1.0
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0
    plot_legend_name: str = "Label"

    def __init__(
        self,
        num_labels: int,
        average: Optional[str] = "macro",
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            num_labels=num_labels, thresholds=thresholds, ignore_index=ignore_index,
            validate_args=False, **kwargs,
        )
        if validate_args:
            _multilabel_average_precision_arg_validation(num_labels, average, thresholds, ignore_index)
        self.average_ap = average
        self.validate_args = validate_args

    def compute(self) -> Array:
        return _multilabel_average_precision_compute(
            self._final_state(), self.num_labels, self.average_ap, self.thresholds, self.ignore_index
        )

    def plot(self, val: Any = None, ax: Any = None) -> Any:
        return self._plot(val, ax)


class AveragePrecision(_ClassificationTaskWrapper):
    """Task-string wrapper (reference classification/average_precision.py:388).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics import AveragePrecision
        >>> probs = jnp.asarray([0.11, 0.84, 0.22, 0.73, 0.33, 0.92])
        >>> target = jnp.asarray([0, 1, 0, 1, 0, 1])
        >>> metric = AveragePrecision(task="binary", thresholds=8)
        >>> metric.update(probs, target)
        >>> round(float(metric.compute()), 4)
        1.0
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        thresholds: Thresholds = None,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        average: Optional[str] = "macro",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str(task)
        kwargs.update({"thresholds": thresholds, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryAveragePrecision(**kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
            return MulticlassAveragePrecision(num_classes, average, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
            return MultilabelAveragePrecision(num_labels, average, **kwargs)
        raise ValueError(f"Not handled value: {task}")
