"""Modular confusion-matrix metrics (counterpart of reference
``classification/confusion_matrix.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from tpumetrics.classification.base import _ClassificationTaskWrapper
from tpumetrics.functional.classification.confusion_matrix import (
    _binary_confusion_matrix_arg_validation,
    _confusion_matrix_reduce,
    _masked_confmat,
    _multiclass_confusion_matrix_arg_validation,
    _multilabel_confmat,
    _multilabel_confusion_matrix_arg_validation,
)
from tpumetrics.functional.classification.stat_scores import (
    _binary_stat_scores_format,
    _binary_stat_scores_tensor_validation,
    _multiclass_stat_scores_format,
    _multiclass_stat_scores_tensor_validation,
    _multilabel_stat_scores_format,
    _multilabel_stat_scores_tensor_validation,
)
from tpumetrics.metric import Metric
from tpumetrics.utils.enums import ClassificationTask
from tpumetrics.utils.plot import plot_confusion_matrix
from tpumetrics.utils.data import _count_dtype

Array = jax.Array


class BinaryConfusionMatrix(Metric):
    """2x2 confusion matrix for binary tasks.

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import BinaryConfusionMatrix
        >>> metric = BinaryConfusionMatrix()
        >>> metric.update(jnp.asarray([0, 1, 0, 0]), jnp.asarray([1, 1, 0, 0]))
        >>> metric.compute().tolist()
        [[2, 0], [1, 1]]
    """

    is_differentiable = False
    higher_is_better = None
    full_state_update: bool = False

    confmat: Array

    def __init__(
        self,
        threshold: float = 0.5,
        normalize: Optional[str] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _binary_confusion_matrix_arg_validation(threshold, ignore_index, normalize)
        self.threshold = threshold
        self.normalize = normalize
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self.add_state("confmat", jnp.zeros((2, 2), dtype=_count_dtype()), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        if self.validate_args:
            _binary_stat_scores_tensor_validation(preds, target, "global", self.ignore_index)
        preds, target, mask = _binary_stat_scores_format(preds, target, self.threshold, self.ignore_index)
        self.confmat = self.confmat + _masked_confmat(preds, target, mask, 2)

    def compute(self) -> Array:
        return _confusion_matrix_reduce(self.confmat, self.normalize)

    def plot(self, val: Optional[Array] = None, ax: Any = None, add_text: bool = True, labels: Any = None) -> Any:
        val = val if val is not None else self.compute()
        return plot_confusion_matrix(val, ax=ax, add_text=add_text, labels=labels)


class MulticlassConfusionMatrix(Metric):
    """(C, C) confusion matrix for multiclass tasks.

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import MulticlassConfusionMatrix
        >>> metric = MulticlassConfusionMatrix(num_classes=3)
        >>> metric.update(jnp.asarray([2, 1, 0, 1]), jnp.asarray([2, 1, 0, 0]))
        >>> metric.compute().tolist()
        [[1, 1, 0], [0, 1, 0], [0, 0, 1]]
    """

    is_differentiable = False
    higher_is_better = None
    full_state_update: bool = False

    confmat: Array

    def __init__(
        self,
        num_classes: int,
        normalize: Optional[str] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multiclass_confusion_matrix_arg_validation(num_classes, ignore_index, normalize)
        self.num_classes = num_classes
        self.normalize = normalize
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self.add_state("confmat", jnp.zeros((num_classes, num_classes), dtype=_count_dtype()), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        if self.validate_args:
            _multiclass_stat_scores_tensor_validation(preds, target, self.num_classes, "global", self.ignore_index)
        preds, target, mask = _multiclass_stat_scores_format(preds, target, self.num_classes, self.ignore_index, 1)
        self.confmat = self.confmat + _masked_confmat(preds, target, mask, self.num_classes)

    def compute(self) -> Array:
        return _confusion_matrix_reduce(self.confmat, self.normalize)

    def plot(self, val: Optional[Array] = None, ax: Any = None, add_text: bool = True, labels: Any = None) -> Any:
        val = val if val is not None else self.compute()
        return plot_confusion_matrix(val, ax=ax, add_text=add_text, labels=labels)


class MultilabelConfusionMatrix(Metric):
    """(num_labels, 2, 2) per-label confusion matrices.

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import MultilabelConfusionMatrix
        >>> metric = MultilabelConfusionMatrix(num_labels=3)
        >>> metric.update(jnp.asarray([[0, 0, 1], [1, 0, 1]]), jnp.asarray([[0, 1, 0], [1, 0, 1]]))
        >>> metric.compute().tolist()
        [[[1, 0], [0, 1]], [[1, 0], [1, 0]], [[0, 1], [0, 1]]]
    """

    is_differentiable = False
    higher_is_better = None
    full_state_update: bool = False

    confmat: Array

    def __init__(
        self,
        num_labels: int,
        threshold: float = 0.5,
        normalize: Optional[str] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multilabel_confusion_matrix_arg_validation(num_labels, threshold, ignore_index, normalize)
        self.num_labels = num_labels
        self.threshold = threshold
        self.normalize = normalize
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self.add_state("confmat", jnp.zeros((num_labels, 2, 2), dtype=_count_dtype()), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        if self.validate_args:
            _multilabel_stat_scores_tensor_validation(preds, target, self.num_labels, "global", self.ignore_index)
        preds, target, mask = _multilabel_stat_scores_format(
            preds, target, self.num_labels, self.threshold, self.ignore_index
        )
        self.confmat = self.confmat + _multilabel_confmat(preds, target, mask)

    def compute(self) -> Array:
        return _confusion_matrix_reduce(self.confmat, self.normalize)

    def plot(self, val: Optional[Array] = None, ax: Any = None, add_text: bool = True, labels: Any = None) -> Any:
        val = val if val is not None else self.compute()
        return plot_confusion_matrix(val, ax=ax, add_text=add_text, labels=labels)


class ConfusionMatrix(_ClassificationTaskWrapper):
    """Task-string wrapper for confusion matrix.

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics import ConfusionMatrix
        >>> logits = jnp.asarray([[2.0, 0.5, 0.1], [0.3, 2.1, 0.2], [0.2, 0.3, 2.2], [2.0, 0.1, 0.4]])
        >>> target = jnp.asarray([0, 1, 2, 1])
        >>> metric = ConfusionMatrix(task="multiclass", num_classes=3)
        >>> metric.update(logits, target)
        >>> metric.compute()
        Array([[1, 0, 0],
               [1, 1, 0],
               [0, 0, 1]], dtype=int32)
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        normalize: Optional[str] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str(task)
        kwargs.update({"normalize": normalize, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryConfusionMatrix(threshold, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
            return MulticlassConfusionMatrix(num_classes, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
            return MultilabelConfusionMatrix(num_labels, threshold, **kwargs)
        raise ValueError(f"Not handled value: {task}")
