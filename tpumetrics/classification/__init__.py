"""Modular classification metrics (counterpart of reference
``torchmetrics/classification/__init__.py``)."""

from tpumetrics.classification.accuracy import (
    Accuracy,
    BinaryAccuracy,
    MulticlassAccuracy,
    MultilabelAccuracy,
)
from tpumetrics.classification.confusion_matrix import (
    BinaryConfusionMatrix,
    ConfusionMatrix,
    MulticlassConfusionMatrix,
    MultilabelConfusionMatrix,
)
from tpumetrics.classification.exact_match import (
    ExactMatch,
    MulticlassExactMatch,
    MultilabelExactMatch,
)
from tpumetrics.classification.f_beta import (
    BinaryF1Score,
    BinaryFBetaScore,
    F1Score,
    FBetaScore,
    MulticlassF1Score,
    MulticlassFBetaScore,
    MultilabelF1Score,
    MultilabelFBetaScore,
)
from tpumetrics.classification.hamming import (
    BinaryHammingDistance,
    HammingDistance,
    MulticlassHammingDistance,
    MultilabelHammingDistance,
)
from tpumetrics.classification.precision_recall import (
    BinaryPrecision,
    BinaryRecall,
    MulticlassPrecision,
    MulticlassRecall,
    MultilabelPrecision,
    MultilabelRecall,
    Precision,
    Recall,
)
from tpumetrics.classification.specificity import (
    BinarySpecificity,
    MulticlassSpecificity,
    MultilabelSpecificity,
    Specificity,
)
from tpumetrics.classification.stat_scores import (
    BinaryStatScores,
    MulticlassStatScores,
    MultilabelStatScores,
    StatScores,
)

__all__ = [
    "Accuracy",
    "BinaryAccuracy",
    "BinaryConfusionMatrix",
    "BinaryF1Score",
    "BinaryFBetaScore",
    "BinaryHammingDistance",
    "BinaryPrecision",
    "BinaryRecall",
    "BinarySpecificity",
    "BinaryStatScores",
    "ConfusionMatrix",
    "ExactMatch",
    "F1Score",
    "FBetaScore",
    "HammingDistance",
    "MulticlassAccuracy",
    "MulticlassConfusionMatrix",
    "MulticlassExactMatch",
    "MulticlassF1Score",
    "MulticlassFBetaScore",
    "MulticlassHammingDistance",
    "MulticlassPrecision",
    "MulticlassRecall",
    "MulticlassSpecificity",
    "MulticlassStatScores",
    "MultilabelAccuracy",
    "MultilabelConfusionMatrix",
    "MultilabelExactMatch",
    "MultilabelF1Score",
    "MultilabelFBetaScore",
    "MultilabelHammingDistance",
    "MultilabelPrecision",
    "MultilabelRecall",
    "MultilabelSpecificity",
    "MultilabelStatScores",
    "Precision",
    "Recall",
    "Specificity",
    "StatScores",
]
