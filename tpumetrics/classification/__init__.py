"""Modular classification metrics (counterpart of reference
``torchmetrics/classification/__init__.py``)."""

from tpumetrics.classification.accuracy import (
    Accuracy,
    BinaryAccuracy,
    MulticlassAccuracy,
    MultilabelAccuracy,
)
from tpumetrics.classification.auroc import (
    AUROC,
    BinaryAUROC,
    MulticlassAUROC,
    MultilabelAUROC,
)
from tpumetrics.classification.average_precision import (
    AveragePrecision,
    BinaryAveragePrecision,
    MulticlassAveragePrecision,
    MultilabelAveragePrecision,
)
from tpumetrics.classification.calibration_error import (
    BinaryCalibrationError,
    CalibrationError,
    MulticlassCalibrationError,
)
from tpumetrics.classification.cohen_kappa import (
    BinaryCohenKappa,
    CohenKappa,
    MulticlassCohenKappa,
)
from tpumetrics.classification.confusion_matrix import (
    BinaryConfusionMatrix,
    ConfusionMatrix,
    MulticlassConfusionMatrix,
    MultilabelConfusionMatrix,
)
from tpumetrics.classification.dice import Dice
from tpumetrics.classification.exact_match import (
    ExactMatch,
    MulticlassExactMatch,
    MultilabelExactMatch,
)
from tpumetrics.classification.f_beta import (
    BinaryF1Score,
    BinaryFBetaScore,
    F1Score,
    FBetaScore,
    MulticlassF1Score,
    MulticlassFBetaScore,
    MultilabelF1Score,
    MultilabelFBetaScore,
)
from tpumetrics.classification.group_fairness import (
    BinaryFairness,
    BinaryGroupStatRates,
)
from tpumetrics.classification.hamming import (
    BinaryHammingDistance,
    HammingDistance,
    MulticlassHammingDistance,
    MultilabelHammingDistance,
)
from tpumetrics.classification.hinge import (
    BinaryHingeLoss,
    HingeLoss,
    MulticlassHingeLoss,
)
from tpumetrics.classification.jaccard import (
    BinaryJaccardIndex,
    JaccardIndex,
    MulticlassJaccardIndex,
    MultilabelJaccardIndex,
)
from tpumetrics.classification.matthews_corrcoef import (
    BinaryMatthewsCorrCoef,
    MatthewsCorrCoef,
    MulticlassMatthewsCorrCoef,
    MultilabelMatthewsCorrCoef,
)
from tpumetrics.classification.precision_fixed_recall import (
    BinaryPrecisionAtFixedRecall,
    MulticlassPrecisionAtFixedRecall,
    MultilabelPrecisionAtFixedRecall,
    PrecisionAtFixedRecall,
)
from tpumetrics.classification.precision_recall import (
    BinaryPrecision,
    BinaryRecall,
    MulticlassPrecision,
    MulticlassRecall,
    MultilabelPrecision,
    MultilabelRecall,
    Precision,
    Recall,
)
from tpumetrics.classification.precision_recall_curve import (
    BinaryPrecisionRecallCurve,
    MulticlassPrecisionRecallCurve,
    MultilabelPrecisionRecallCurve,
    PrecisionRecallCurve,
)
from tpumetrics.classification.ranking import (
    MultilabelCoverageError,
    MultilabelRankingAveragePrecision,
    MultilabelRankingLoss,
)
from tpumetrics.classification.recall_fixed_precision import (
    BinaryRecallAtFixedPrecision,
    MulticlassRecallAtFixedPrecision,
    MultilabelRecallAtFixedPrecision,
    RecallAtFixedPrecision,
)
from tpumetrics.classification.roc import (
    ROC,
    BinaryROC,
    MulticlassROC,
    MultilabelROC,
)
from tpumetrics.classification.specificity import (
    BinarySpecificity,
    MulticlassSpecificity,
    MultilabelSpecificity,
    Specificity,
)
from tpumetrics.classification.specificity_sensitivity import (
    BinarySpecificityAtSensitivity,
    MulticlassSpecificityAtSensitivity,
    MultilabelSpecificityAtSensitivity,
    SpecificityAtSensitivity,
)
from tpumetrics.classification.stat_scores import (
    BinaryStatScores,
    MulticlassStatScores,
    MultilabelStatScores,
    StatScores,
)

__all__ = [
    "AUROC",
    "Accuracy",
    "AveragePrecision",
    "BinaryAUROC",
    "BinaryAccuracy",
    "BinaryAveragePrecision",
    "BinaryCalibrationError",
    "BinaryCohenKappa",
    "BinaryConfusionMatrix",
    "BinaryF1Score",
    "BinaryFBetaScore",
    "BinaryFairness",
    "BinaryGroupStatRates",
    "BinaryHammingDistance",
    "BinaryHingeLoss",
    "BinaryJaccardIndex",
    "BinaryMatthewsCorrCoef",
    "BinaryPrecision",
    "BinaryPrecisionAtFixedRecall",
    "BinaryPrecisionRecallCurve",
    "BinaryROC",
    "BinaryRecall",
    "BinaryRecallAtFixedPrecision",
    "BinarySpecificity",
    "BinarySpecificityAtSensitivity",
    "BinaryStatScores",
    "CalibrationError",
    "CohenKappa",
    "ConfusionMatrix",
    "Dice",
    "ExactMatch",
    "F1Score",
    "FBetaScore",
    "HammingDistance",
    "HingeLoss",
    "JaccardIndex",
    "MatthewsCorrCoef",
    "MulticlassAUROC",
    "MulticlassAccuracy",
    "MulticlassAveragePrecision",
    "MulticlassCalibrationError",
    "MulticlassCohenKappa",
    "MulticlassConfusionMatrix",
    "MulticlassExactMatch",
    "MulticlassF1Score",
    "MulticlassFBetaScore",
    "MulticlassHammingDistance",
    "MulticlassHingeLoss",
    "MulticlassJaccardIndex",
    "MulticlassMatthewsCorrCoef",
    "MulticlassPrecision",
    "MulticlassPrecisionAtFixedRecall",
    "MulticlassPrecisionRecallCurve",
    "MulticlassROC",
    "MulticlassRecall",
    "MulticlassRecallAtFixedPrecision",
    "MulticlassSpecificity",
    "MulticlassSpecificityAtSensitivity",
    "MulticlassStatScores",
    "MultilabelAUROC",
    "MultilabelAccuracy",
    "MultilabelAveragePrecision",
    "MultilabelConfusionMatrix",
    "MultilabelCoverageError",
    "MultilabelExactMatch",
    "MultilabelF1Score",
    "MultilabelFBetaScore",
    "MultilabelHammingDistance",
    "MultilabelJaccardIndex",
    "MultilabelMatthewsCorrCoef",
    "MultilabelPrecision",
    "MultilabelPrecisionAtFixedRecall",
    "MultilabelPrecisionRecallCurve",
    "MultilabelROC",
    "MultilabelRankingAveragePrecision",
    "MultilabelRankingLoss",
    "MultilabelRecall",
    "MultilabelRecallAtFixedPrecision",
    "MultilabelSpecificity",
    "MultilabelSpecificityAtSensitivity",
    "MultilabelStatScores",
    "Precision",
    "PrecisionAtFixedRecall",
    "PrecisionRecallCurve",
    "ROC",
    "Recall",
    "RecallAtFixedPrecision",
    "Specificity",
    "SpecificityAtSensitivity",
    "StatScores",
]
