"""Modular exact-match metrics (counterpart of reference ``classification/exact_match.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from tpumetrics.classification.base import _ClassificationTaskWrapper
from tpumetrics.functional.classification.exact_match import (
    _exact_match_reduce,
    _multiclass_exact_match_update,
    _multilabel_exact_match_update,
)
from tpumetrics.functional.classification.stat_scores import (
    _multiclass_stat_scores_arg_validation,
    _multiclass_stat_scores_format,
    _multiclass_stat_scores_tensor_validation,
    _multilabel_stat_scores_arg_validation,
    _multilabel_stat_scores_format,
    _multilabel_stat_scores_tensor_validation,
)
from tpumetrics.metric import Metric
from tpumetrics.utils.data import dim_zero_cat
from tpumetrics.utils.enums import ClassificationTaskNoBinary

Array = jax.Array


class _AbstractExactMatch(Metric):
    """Shared correct/total state (reference classification/exact_match.py)."""

    correct: Any
    total: Any

    def _create_state(self, multidim_average: str) -> None:
        if multidim_average == "samplewise":
            self.add_state("correct", [], dist_reduce_fx="cat")
            self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")
        else:
            self.add_state("correct", jnp.asarray(0), dist_reduce_fx="sum")
            self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")

    def _update_state(self, correct: Array, total: Array) -> None:
        if isinstance(self.correct, list):
            self.correct.append(correct)
        else:
            self.correct = self.correct + correct
        self.total = self.total + jnp.sum(total)

    def compute(self) -> Array:
        correct = dim_zero_cat(self.correct)
        if self.multidim_average == "samplewise":
            return correct.astype(jnp.float32)
        return _exact_match_reduce(correct, self.total)


class MulticlassExactMatch(_AbstractExactMatch):
    """Exact match for multidim multiclass inputs.

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import MulticlassExactMatch
        >>> metric = MulticlassExactMatch(num_classes=3)
        >>> metric.update(jnp.asarray([[0, 1], [2, 1]]), jnp.asarray([[0, 1], [2, 2]]))
        >>> float(metric.compute())
        0.5
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        num_classes: int,
        multidim_average: str = "global",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multiclass_stat_scores_arg_validation(num_classes, 1, None, multidim_average, ignore_index)
        self.num_classes = num_classes
        self.multidim_average = multidim_average
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self._create_state(multidim_average)

    def update(self, preds: Array, target: Array) -> None:
        if self.validate_args:
            _multiclass_stat_scores_tensor_validation(
                preds, target, self.num_classes, self.multidim_average, self.ignore_index
            )
        preds, target, mask = _multiclass_stat_scores_format(
            preds, target, self.num_classes, self.ignore_index, 1
        )
        correct, total = _multiclass_exact_match_update(preds, target, mask, self.multidim_average)
        self._update_state(correct, total)


class MultilabelExactMatch(_AbstractExactMatch):
    """Exact match for multilabel inputs.

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import MultilabelExactMatch
        >>> metric = MultilabelExactMatch(num_labels=3)
        >>> metric.update(jnp.asarray([[0, 1, 0], [1, 0, 0]]), jnp.asarray([[0, 1, 0], [1, 0, 1]]))
        >>> float(metric.compute())
        0.5
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        num_labels: int,
        threshold: float = 0.5,
        multidim_average: str = "global",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multilabel_stat_scores_arg_validation(num_labels, threshold, None, multidim_average, ignore_index)
        self.num_labels = num_labels
        self.threshold = threshold
        self.multidim_average = multidim_average
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self._create_state(multidim_average)

    def update(self, preds: Array, target: Array) -> None:
        if self.validate_args:
            _multilabel_stat_scores_tensor_validation(
                preds, target, self.num_labels, self.multidim_average, self.ignore_index
            )
        preds, target, mask = _multilabel_stat_scores_format(
            preds, target, self.num_labels, self.threshold, self.ignore_index
        )
        correct, total = _multilabel_exact_match_update(preds, target, mask, self.multidim_average)
        self._update_state(correct, total)


class ExactMatch(_ClassificationTaskWrapper):
    """Task-string wrapper for exact match (multiclass | multilabel).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics import ExactMatch
        >>> preds = jnp.asarray([[0, 1], [2, 2], [1, 1]])
        >>> target = jnp.asarray([[0, 1], [2, 0], [1, 1]])
        >>> metric = ExactMatch(task="multiclass", num_classes=3)
        >>> metric.update(preds, target)
        >>> round(float(metric.compute()), 4)
        0.6667
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        multidim_average: str = "global",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTaskNoBinary.from_str(task)
        kwargs.update(
            {"multidim_average": multidim_average, "ignore_index": ignore_index, "validate_args": validate_args}
        )
        if task == ClassificationTaskNoBinary.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
            return MulticlassExactMatch(num_classes, **kwargs)
        if task == ClassificationTaskNoBinary.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
            return MultilabelExactMatch(num_labels, threshold, **kwargs)
        raise ValueError(f"Not handled value: {task}")
