"""Modular PrecisionAtFixedRecall metrics (counterpart of reference
``classification/precision_fixed_recall.py``)."""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax

from tpumetrics.classification.base import _ClassificationTaskWrapper
from tpumetrics.classification.precision_recall_curve import (
    BinaryPrecisionRecallCurve,
    _AtFixedValuePlotMixin,
    MulticlassPrecisionRecallCurve,
    MultilabelPrecisionRecallCurve,
)
from tpumetrics.functional.classification.precision_fixed_recall import _precision_at_recall
from tpumetrics.functional.classification.precision_recall_curve import Thresholds
from tpumetrics.functional.classification.recall_fixed_precision import (
    _binary_recall_at_fixed_precision_arg_validation,
    _binary_recall_at_fixed_precision_compute,
    _multiclass_recall_at_fixed_precision_arg_validation,
    _multiclass_recall_at_fixed_precision_compute,
    _multilabel_recall_at_fixed_precision_arg_validation,
    _multilabel_recall_at_fixed_precision_compute,
)
from tpumetrics.metric import Metric
from tpumetrics.utils.enums import ClassificationTask

Array = jax.Array


class BinaryPrecisionAtFixedRecall(_AtFixedValuePlotMixin, BinaryPrecisionRecallCurve):
    """Max precision subject to recall >= min_recall, binary (reference
    classification/precision_fixed_recall.py:32).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import BinaryPrecisionAtFixedRecall
        >>> metric = BinaryPrecisionAtFixedRecall(min_recall=0.5)
        >>> metric.update(jnp.asarray([0.1, 0.4, 0.35, 0.8]), jnp.asarray([0, 0, 1, 1]))
        >>> precision, threshold = metric.compute()
        >>> (round(float(precision), 4), round(float(threshold), 4))
        (1.0, 0.8)
    """

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = None
    full_state_update: bool = False

    def __init__(
        self,
        min_recall: float,
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(thresholds=thresholds, ignore_index=ignore_index, validate_args=False, **kwargs)
        if validate_args:
            _binary_recall_at_fixed_precision_arg_validation(min_recall, thresholds, ignore_index)
        self.validate_args = validate_args
        self.min_recall = min_recall

    def compute(self) -> Tuple[Array, Array]:
        return _binary_recall_at_fixed_precision_compute(
            self._final_state(), self.thresholds, self.min_recall, reduce_fn=_precision_at_recall
        )


class MulticlassPrecisionAtFixedRecall(_AtFixedValuePlotMixin, MulticlassPrecisionRecallCurve):
    """Per-class max precision subject to recall >= min_recall (reference
    classification/precision_fixed_recall.py:141).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import MulticlassPrecisionAtFixedRecall
        >>> metric = MulticlassPrecisionAtFixedRecall(num_classes=3, min_recall=0.5)
        >>> metric.update(jnp.asarray([[0.8, 0.1, 0.1], [0.1, 0.8, 0.1], [0.1, 0.1, 0.8]]),
        ...               jnp.asarray([0, 1, 2]))
        >>> precision, thresholds = metric.compute()
        >>> precision.tolist()
        [1.0, 1.0, 1.0]
    """

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = None
    full_state_update: bool = False
    plot_legend_name: str = "Class"

    def __init__(
        self,
        num_classes: int,
        min_recall: float,
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            num_classes=num_classes, thresholds=thresholds, average=None,
            ignore_index=ignore_index, validate_args=False, **kwargs,
        )
        if validate_args:
            _multiclass_recall_at_fixed_precision_arg_validation(num_classes, min_recall, thresholds, ignore_index)
        self.validate_args = validate_args
        self.min_recall = min_recall

    def compute(self) -> Tuple[Array, Array]:
        return _multiclass_recall_at_fixed_precision_compute(
            self._final_state(), self.num_classes, self.thresholds, self.min_recall,
            reduce_fn=_precision_at_recall,
        )


class MultilabelPrecisionAtFixedRecall(_AtFixedValuePlotMixin, MultilabelPrecisionRecallCurve):
    """Per-label max precision subject to recall >= min_recall (reference
    classification/precision_fixed_recall.py:252).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import MultilabelPrecisionAtFixedRecall
        >>> metric = MultilabelPrecisionAtFixedRecall(num_labels=2, min_recall=0.5)
        >>> metric.update(jnp.asarray([[0.8, 0.1], [0.1, 0.8]]), jnp.asarray([[1, 0], [0, 1]]))
        >>> precision, thresholds = metric.compute()
        >>> precision.tolist()
        [1.0, 1.0]
    """

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = None
    full_state_update: bool = False
    plot_legend_name: str = "Label"

    def __init__(
        self,
        num_labels: int,
        min_recall: float,
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            num_labels=num_labels, thresholds=thresholds, ignore_index=ignore_index,
            validate_args=False, **kwargs,
        )
        if validate_args:
            _multilabel_recall_at_fixed_precision_arg_validation(num_labels, min_recall, thresholds, ignore_index)
        self.validate_args = validate_args
        self.min_recall = min_recall

    def compute(self) -> Tuple[Array, Array]:
        return _multilabel_recall_at_fixed_precision_compute(
            self._final_state(), self.num_labels, self.thresholds, self.ignore_index, self.min_recall,
            reduce_fn=_precision_at_recall,
        )


class PrecisionAtFixedRecall(_ClassificationTaskWrapper):
    """Task-string wrapper (reference classification/precision_fixed_recall.py:356).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics import PrecisionAtFixedRecall
        >>> probs = jnp.asarray([0.11, 0.84, 0.22, 0.73, 0.33, 0.92])
        >>> target = jnp.asarray([0, 1, 0, 1, 0, 1])
        >>> metric = PrecisionAtFixedRecall(task="binary", min_recall=0.5)
        >>> metric.update(probs, target)
        >>> [round(float(v), 4) for v in metric.compute()]
        [1.0, 0.73]
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        min_recall: float,
        thresholds: Thresholds = None,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str(task)
        kwargs.update({"thresholds": thresholds, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryPrecisionAtFixedRecall(min_recall, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
            return MulticlassPrecisionAtFixedRecall(num_classes, min_recall, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
            return MultilabelPrecisionAtFixedRecall(num_labels, min_recall, **kwargs)
        raise ValueError(f"Not handled value: {task}")
