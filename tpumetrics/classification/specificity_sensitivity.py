"""Modular SpecificityAtSensitivity metrics (counterpart of reference
``classification/specificity_sensitivity.py``)."""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax

from tpumetrics.classification.base import _ClassificationTaskWrapper
from tpumetrics.classification.precision_recall_curve import (
    BinaryPrecisionRecallCurve,
    _AtFixedValuePlotMixin,
    MulticlassPrecisionRecallCurve,
    MultilabelPrecisionRecallCurve,
)
from tpumetrics.functional.classification.precision_recall_curve import Thresholds
from tpumetrics.functional.classification.specificity_sensitivity import (
    _binary_specificity_at_sensitivity_arg_validation,
    _binary_specificity_at_sensitivity_compute,
    _multiclass_specificity_at_sensitivity_arg_validation,
    _multiclass_specificity_at_sensitivity_compute,
    _multilabel_specificity_at_sensitivity_arg_validation,
    _multilabel_specificity_at_sensitivity_compute,
)
from tpumetrics.metric import Metric
from tpumetrics.utils.enums import ClassificationTask

Array = jax.Array


class BinarySpecificityAtSensitivity(_AtFixedValuePlotMixin, BinaryPrecisionRecallCurve):
    """Max specificity subject to sensitivity >= min_sensitivity, binary
    (reference classification/specificity_sensitivity.py:33).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import BinarySpecificityAtSensitivity
        >>> metric = BinarySpecificityAtSensitivity(min_sensitivity=0.5)
        >>> metric.update(jnp.asarray([0.1, 0.4, 0.35, 0.8]), jnp.asarray([0, 0, 1, 1]))
        >>> spec, threshold = metric.compute()
        >>> (round(float(spec), 4), round(float(threshold), 4))
        (1.0, 0.8)
    """

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = None
    full_state_update: bool = False

    def __init__(
        self,
        min_sensitivity: float,
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(thresholds=thresholds, ignore_index=ignore_index, validate_args=False, **kwargs)
        if validate_args:
            _binary_specificity_at_sensitivity_arg_validation(min_sensitivity, thresholds, ignore_index)
        self.validate_args = validate_args
        self.min_sensitivity = min_sensitivity

    def compute(self) -> Tuple[Array, Array]:
        return _binary_specificity_at_sensitivity_compute(
            self._final_state(), self.thresholds, self.min_sensitivity
        )


class MulticlassSpecificityAtSensitivity(_AtFixedValuePlotMixin, MulticlassPrecisionRecallCurve):
    """Per-class max specificity subject to sensitivity >= min_sensitivity
    (reference classification/specificity_sensitivity.py:146).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import MulticlassSpecificityAtSensitivity
        >>> metric = MulticlassSpecificityAtSensitivity(num_classes=3, min_sensitivity=0.5)
        >>> metric.update(jnp.asarray([[0.8, 0.1, 0.1], [0.1, 0.8, 0.1], [0.1, 0.1, 0.8]]),
        ...               jnp.asarray([0, 1, 2]))
        >>> spec, thresholds = metric.compute()
        >>> spec.tolist()
        [1.0, 1.0, 1.0]
    """

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = None
    full_state_update: bool = False
    plot_legend_name: str = "Class"

    def __init__(
        self,
        num_classes: int,
        min_sensitivity: float,
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            num_classes=num_classes, thresholds=thresholds, average=None,
            ignore_index=ignore_index, validate_args=False, **kwargs,
        )
        if validate_args:
            _multiclass_specificity_at_sensitivity_arg_validation(
                num_classes, min_sensitivity, thresholds, ignore_index
            )
        self.validate_args = validate_args
        self.min_sensitivity = min_sensitivity

    def compute(self) -> Tuple[Array, Array]:
        return _multiclass_specificity_at_sensitivity_compute(
            self._final_state(), self.num_classes, self.thresholds, self.min_sensitivity
        )


class MultilabelSpecificityAtSensitivity(_AtFixedValuePlotMixin, MultilabelPrecisionRecallCurve):
    """Per-label max specificity subject to sensitivity >= min_sensitivity
    (reference classification/specificity_sensitivity.py:255).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import MultilabelSpecificityAtSensitivity
        >>> metric = MultilabelSpecificityAtSensitivity(num_labels=2, min_sensitivity=0.5)
        >>> metric.update(jnp.asarray([[0.8, 0.1], [0.1, 0.8]]), jnp.asarray([[1, 0], [0, 1]]))
        >>> spec, thresholds = metric.compute()
        >>> spec.tolist()
        [1.0, 1.0]
    """

    is_differentiable: bool = False
    higher_is_better: Optional[bool] = None
    full_state_update: bool = False
    plot_legend_name: str = "Label"

    def __init__(
        self,
        num_labels: int,
        min_sensitivity: float,
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            num_labels=num_labels, thresholds=thresholds, ignore_index=ignore_index,
            validate_args=False, **kwargs,
        )
        if validate_args:
            _multilabel_specificity_at_sensitivity_arg_validation(
                num_labels, min_sensitivity, thresholds, ignore_index
            )
        self.validate_args = validate_args
        self.min_sensitivity = min_sensitivity

    def compute(self) -> Tuple[Array, Array]:
        return _multilabel_specificity_at_sensitivity_compute(
            self._final_state(), self.num_labels, self.thresholds, self.ignore_index, self.min_sensitivity
        )


class SpecificityAtSensitivity(_ClassificationTaskWrapper):
    """Task-string wrapper (reference classification/specificity_sensitivity.py:364).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics import SpecificityAtSensitivity
        >>> probs = jnp.asarray([0.11, 0.84, 0.22, 0.73, 0.33, 0.92])
        >>> target = jnp.asarray([0, 1, 0, 1, 0, 1])
        >>> metric = SpecificityAtSensitivity(task="binary", min_sensitivity=0.5)
        >>> metric.update(probs, target)
        >>> [round(float(v), 4) for v in metric.compute()]
        [1.0, 0.84]
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        min_sensitivity: float,
        thresholds: Thresholds = None,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTask.from_str(task)
        kwargs.update({"thresholds": thresholds, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinarySpecificityAtSensitivity(min_sensitivity, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
            return MulticlassSpecificityAtSensitivity(num_classes, min_sensitivity, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
            return MultilabelSpecificityAtSensitivity(num_labels, min_sensitivity, **kwargs)
        raise ValueError(f"Not handled value: {task}")
