"""Modular Cohen's kappa metrics (counterpart of reference
``classification/cohen_kappa.py`` — subclasses of the confusion-matrix
metrics overriding ``compute``)."""

from __future__ import annotations

from typing import Any, Optional

import jax

from tpumetrics.classification.base import _ClassificationTaskWrapper
from tpumetrics.classification.confusion_matrix import BinaryConfusionMatrix, MulticlassConfusionMatrix
from tpumetrics.functional.classification.cohen_kappa import (
    _cohen_kappa_reduce,
    _cohen_kappa_weights_validation,
)
from tpumetrics.metric import Metric
from tpumetrics.utils.enums import ClassificationTaskNoMultilabel

Array = jax.Array


class BinaryCohenKappa(BinaryConfusionMatrix):
    """Cohen's kappa, binary (reference classification/cohen_kappa.py:31).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import BinaryCohenKappa
        >>> metric = BinaryCohenKappa()
        >>> metric.update(jnp.asarray([0.35, 0.85, 0.48, 0.01]), jnp.asarray([1, 1, 0, 0]))
        >>> round(float(metric.compute()), 4)
        0.5
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        threshold: float = 0.5,
        ignore_index: Optional[int] = None,
        weights: Optional[str] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            threshold=threshold, normalize=None, ignore_index=ignore_index, validate_args=validate_args, **kwargs
        )
        if validate_args:
            _cohen_kappa_weights_validation(weights)
        self.weights = weights

    def compute(self) -> Array:
        return _cohen_kappa_reduce(self.confmat, self.weights)

    def plot(self, val: Any = None, ax: Any = None) -> Any:
        return self._plot(val, ax)


class MulticlassCohenKappa(MulticlassConfusionMatrix):
    """Cohen's kappa, multiclass (reference classification/cohen_kappa.py:142).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.classification import MulticlassCohenKappa
        >>> metric = MulticlassCohenKappa(num_classes=3)
        >>> metric.update(jnp.asarray([2, 1, 0, 1]), jnp.asarray([2, 1, 0, 0]))
        >>> round(float(metric.compute()), 4)
        0.6364
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        num_classes: int,
        ignore_index: Optional[int] = None,
        weights: Optional[str] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            num_classes=num_classes, normalize=None, ignore_index=ignore_index,
            validate_args=validate_args, **kwargs,
        )
        if validate_args:
            _cohen_kappa_weights_validation(weights)
        self.weights = weights

    def compute(self) -> Array:
        return _cohen_kappa_reduce(self.confmat, self.weights)

    def plot(self, val: Any = None, ax: Any = None) -> Any:
        return self._plot(val, ax)


class CohenKappa(_ClassificationTaskWrapper):
    """Task-string wrapper (reference classification/cohen_kappa.py:252).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics import CohenKappa
        >>> logits = jnp.asarray([[2.0, 0.5, 0.1], [0.3, 2.1, 0.2], [0.2, 0.3, 2.2], [2.0, 0.1, 0.4]])
        >>> target = jnp.asarray([0, 1, 2, 1])
        >>> metric = CohenKappa(task="multiclass", num_classes=3)
        >>> metric.update(logits, target)
        >>> round(float(metric.compute()), 4)
        0.6364
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        weights: Optional[str] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> Metric:
        task = ClassificationTaskNoMultilabel.from_str(task)
        kwargs.update({"weights": weights, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTaskNoMultilabel.BINARY:
            return BinaryCohenKappa(threshold, **kwargs)
        if task == ClassificationTaskNoMultilabel.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
            return MulticlassCohenKappa(num_classes, **kwargs)
        raise ValueError(f"Not handled value: {task}")
