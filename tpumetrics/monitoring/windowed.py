"""Sliding-window and exponentially-decayed aggregators for unbounded streams.

The run-to-completion aggregators (``tpumetrics.aggregation``) answer "what
is the mean/sum/extremum of *everything* seen so far" — the right question
for batch eval, the wrong one for serving: a monitoring stream never ends,
and "the metric" is the last N minutes, not the lifetime total.  Two
fixed-shape answers, both trace-safe and exact under the runtime's bucketed
paths:

- **Sliding window** (:class:`WindowedMean` / :class:`WindowedSum` /
  :class:`WindowedMax` / :class:`WindowedMin`): a ring buffer of ``slots``
  **sub-window states**, each covering ``window // slots`` consecutive
  ``update()`` calls.  An update folds the batch into the current slot;
  rotating into a slot resets just that slot — eviction is O(1) device work
  (one dynamic-index write), state shapes are static (``(slots,)``), and the
  ring index is a traced function of the ``count`` state, so nothing
  retraces.  With ``slots == window`` (the default) the window is exact;
  coarser ``slots`` trade pane-granularity staleness (the window covers
  between ``window - pane + 1`` and ``window`` most recent updates, ``pane =
  window // slots``) for ``slots``-sized state.
- **Exponential decay** (:class:`DecayedMean`): half-life-parameterized
  running mean — every update multiplies the accumulated sum/weight by
  ``alpha = 2**(-1/half_life)`` before adding the batch, so an observation's
  influence halves every ``half_life`` updates.  Two scalars of state.

Distribution contract: slot/decayed accumulators are per-rank *shares* of
each sub-window (``dist_reduce_fx="sum"``, extrema ``"max"``/``"min"``), and
the ``count`` tick is lockstep-identical across ranks (``"max"`` — the
idempotent fold).  That means windows fit the existing merge/reshard and
elastic machinery unchanged: reshard places slot sums on rank 0 (zeros
elsewhere) and broadcasts ticks/extrema, and a later fold — plus whatever
the resized world accumulates — reproduces the uninterrupted window exactly
(windows are "exactly once" across preemptions because slot content is
ordinary snapshot state).

Window length is **static by design** (it is state shape): passing a traced
or data-dependent ``window`` raises here, and tpulint flags literal
occurrences as TPL305.
"""

from __future__ import annotations

from typing import Any, Optional, Union

import jax
import jax.numpy as jnp

from tpumetrics.metric import Metric
from tpumetrics.monitoring.sketch import (
    _broadcast_rowmask,
    _require_static_int,
    ring_position,
)
from tpumetrics.utils.exceptions import TPUMetricsUserError

Array = jax.Array

__all__ = [
    "DecayedMean",
    "WindowedMax",
    "WindowedMean",
    "WindowedMin",
    "WindowedSum",
]


class _WindowedAggregator(Metric):
    """Ring-of-sub-window-states base: window bookkeeping + the trace-safe
    pane rotation.  Subclasses declare their slot states and fold batches
    via :meth:`_write_slot`."""

    is_differentiable = None
    higher_is_better = None
    full_state_update: bool = False

    def __init__(
        self,
        window: int,
        slots: Optional[int] = None,
        nan_strategy: Union[str, float] = "ignore",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.window = _require_static_int(window, "window")
        if self.window < 1:
            raise TPUMetricsUserError(f"window must be >= 1 update, got {self.window}")
        self.slots = _require_static_int(slots if slots is not None else self.window, "slots")
        if self.slots < 1 or self.slots > self.window or self.window % self.slots:
            raise TPUMetricsUserError(
                f"slots ({self.slots}) must evenly divide window ({self.window}): each "
                "slot covers window // slots consecutive updates."
            )
        if nan_strategy not in ("ignore", "disable") and not isinstance(nan_strategy, float):
            raise TPUMetricsUserError(
                "Windowed aggregators are trace-first: nan_strategy must be 'ignore', "
                f"'disable', or a float fill value, got {nan_strategy!r}"
            )
        self.nan_strategy = nan_strategy
        self._pane_updates = self.window // self.slots
        # lockstep tick counter driving the ring; ranks hold identical values
        self.add_state("count", default=jnp.asarray(0, jnp.int32), dist_reduce_fx="max")  # tpulint: disable=TPL301 -- lockstep tick counter: ranks hold identical nonnegative counts, so 0 is the fold identity on this domain

    # ------------------------------------------------------------- ingestion

    def _prepare(self, value: Any, weight: Any, valid: Optional[Array], neutral: float):
        """Batch → (values, weights) with the ``valid`` bucket mask and the
        NaN policy applied as pure masking (masked rows carry zero weight and
        the reduction's neutral element)."""
        v = jnp.atleast_1d(jnp.asarray(value, self._dtype))
        w = jnp.broadcast_to(jnp.asarray(weight, self._dtype), v.shape)
        if valid is not None:
            w = w * _broadcast_rowmask(valid, v).astype(v.dtype)
        if self.nan_strategy != "disable":
            nan = jnp.isnan(v) | jnp.isnan(w)
            if isinstance(self.nan_strategy, float):
                v = jnp.where(nan, self.nan_strategy, v)
                w = jnp.where(jnp.isnan(w), 0.0, w)
            else:  # "ignore": masked out entirely
                v = jnp.where(nan, neutral, v)
                w = jnp.where(nan, 0.0, w)
        dead = w == 0
        return jnp.where(dead, neutral, v), w

    def _write_slot(self, name: str, batch_value: Array, neutral: float, combine) -> None:
        """Fold ``batch_value`` into the current pane's slot of state
        ``name``; the first update of a pane resets (evicts) the slot first.
        One dynamic-index write — O(1) in the window length."""
        slots = getattr(self, name)
        idx, fresh = ring_position(self.count, self._pane_updates, self.slots)
        base = jnp.where(fresh, jnp.asarray(neutral, slots.dtype), slots[idx])
        setattr(self, name, slots.at[idx].set(combine(base, batch_value)))

    def _tick(self) -> None:
        self.count = self.count + 1


class WindowedMean(_WindowedAggregator):
    """(Weighted) mean over the last ``window`` updates.

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.monitoring import WindowedMean
        >>> m = WindowedMean(window=2)
        >>> for x in (1.0, 2.0, 3.0, 4.0):
        ...     m.update(x)
        >>> float(m.compute())  # mean of the last 2 updates
        3.5
    """

    def __init__(self, window: int, slots: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(window, slots=slots, **kwargs)
        self.add_state("slot_sum", default=jnp.zeros((self.slots,)), dist_reduce_fx="sum")
        self.add_state("slot_weight", default=jnp.zeros((self.slots,)), dist_reduce_fx="sum")

    def update(
        self, value: Any, weight: Any = 1.0, valid: Optional[Array] = None
    ) -> None:
        v, w = self._prepare(value, weight, valid, neutral=0.0)
        self._write_slot("slot_sum", jnp.sum(v * w), 0.0, jnp.add)
        self._write_slot("slot_weight", jnp.sum(w), 0.0, jnp.add)
        self._tick()

    def compute(self) -> Array:
        return jnp.sum(self.slot_sum) / jnp.sum(self.slot_weight)


class WindowedSum(_WindowedAggregator):
    """Sum over the last ``window`` updates.

    Example:
        >>> from tpumetrics.monitoring import WindowedSum
        >>> m = WindowedSum(window=2)
        >>> for x in (1.0, 2.0, 3.0):
        ...     m.update(x)
        >>> float(m.compute())
        5.0
    """

    def __init__(self, window: int, slots: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(window, slots=slots, **kwargs)
        self.add_state("slot_sum", default=jnp.zeros((self.slots,)), dist_reduce_fx="sum")

    def update(self, value: Any, valid: Optional[Array] = None) -> None:
        v, w = self._prepare(value, 1.0, valid, neutral=0.0)
        self._write_slot("slot_sum", jnp.sum(v * w), 0.0, jnp.add)
        self._tick()

    def compute(self) -> Array:
        return jnp.sum(self.slot_sum)


class WindowedMax(_WindowedAggregator):
    """Max over the last ``window`` updates (``-inf`` before any data).

    Example:
        >>> from tpumetrics.monitoring import WindowedMax
        >>> m = WindowedMax(window=2)
        >>> for x in (9.0, 1.0, 2.0):
        ...     m.update(x)
        >>> float(m.compute())  # the 9 has slid out
        2.0
    """

    def __init__(self, window: int, slots: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(window, slots=slots, **kwargs)
        self.add_state(
            "slot_max", default=jnp.full((self.slots,), -jnp.inf), dist_reduce_fx="max"
        )

    def update(self, value: Any, valid: Optional[Array] = None) -> None:
        v, _w = self._prepare(value, 1.0, valid, neutral=-jnp.inf)
        # initial= keeps a zero-size batch a neutral no-op (still ticks)
        self._write_slot("slot_max", jnp.max(v, initial=-jnp.inf), -jnp.inf, jnp.maximum)
        self._tick()

    def compute(self) -> Array:
        return jnp.max(self.slot_max)


class WindowedMin(_WindowedAggregator):
    """Min over the last ``window`` updates (``+inf`` before any data).

    Example:
        >>> from tpumetrics.monitoring import WindowedMin
        >>> m = WindowedMin(window=2)
        >>> for x in (0.5, 3.0, 2.0):
        ...     m.update(x)
        >>> float(m.compute())
        2.0
    """

    def __init__(self, window: int, slots: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(window, slots=slots, **kwargs)
        self.add_state(
            "slot_min", default=jnp.full((self.slots,), jnp.inf), dist_reduce_fx="min"
        )

    def update(self, value: Any, valid: Optional[Array] = None) -> None:
        v, _w = self._prepare(value, 1.0, valid, neutral=jnp.inf)
        self._write_slot("slot_min", jnp.min(v, initial=jnp.inf), jnp.inf, jnp.minimum)
        self._tick()

    def compute(self) -> Array:
        return jnp.min(self.slot_min)


class DecayedMean(Metric):
    """Exponentially-decayed (weighted) mean: each ``update()`` halves the
    influence of observations ``half_life`` updates old.

    Unlike a sliding window there is no eviction at all — two scalars of
    state (`decayed sum` and `decayed weight`, both ``dist_reduce_fx="sum"``)
    and one multiply-add per update, so it is the cheapest "recent average"
    for serving dashboards.  ``half_life`` is measured in ``update()`` calls
    and must be a static number (it parameterizes the trace, not the state
    shape).

    Example:
        >>> from tpumetrics.monitoring import DecayedMean
        >>> m = DecayedMean(half_life=1)
        >>> for x in (0.0, 0.0, 8.0):
        ...     m.update(x)
        >>> round(float(m.compute()), 4)  # recent 8 dominates: (8 + 0/2 + 0/4) / (1 + 1/2 + 1/4)
        4.5714
    """

    is_differentiable = None
    higher_is_better = None
    full_state_update: bool = False

    def __init__(self, half_life: float = 100.0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if isinstance(half_life, (jax.core.Tracer, jax.Array)):
            raise TPUMetricsUserError(
                "half_life must be a static python number: it parameterizes the "
                "compiled update, and a traced value would retrace every step."
            )
        self.half_life = float(half_life)
        if not self.half_life > 0:
            raise TPUMetricsUserError(f"half_life must be > 0 updates, got {half_life}")
        self._alpha = 2.0 ** (-1.0 / self.half_life)
        self.add_state("decayed_sum", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("decayed_weight", default=jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(
        self, value: Any, weight: Any = 1.0, valid: Optional[Array] = None
    ) -> None:
        v = jnp.atleast_1d(jnp.asarray(value, self._dtype))
        w = jnp.broadcast_to(jnp.asarray(weight, self._dtype), v.shape)
        if valid is not None:
            w = w * _broadcast_rowmask(valid, v).astype(v.dtype)
        nan = jnp.isnan(v) | jnp.isnan(w)
        v = jnp.where(nan, 0.0, v)
        w = jnp.where(nan, 0.0, w)
        self.decayed_sum = self.decayed_sum * self._alpha + jnp.sum(v * w)
        self.decayed_weight = self.decayed_weight * self._alpha + jnp.sum(w)

    def compute(self) -> Array:
        return self.decayed_sum / self.decayed_weight
