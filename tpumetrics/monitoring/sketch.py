"""Mergeable quantile/histogram sketches as a first-class metric state kind.

The sketch is a **log-linear histogram** (HDR-histogram-style compactor
levels): ``levels`` geometric magnitude ranges, each split into ``capacity``
linear buckets, one mirrored set per sign, plus exact total/min/max slots —
all packed into ONE flat ``float32`` ``jax.Array`` so the whole sketch is a
single fixed-shape, jit-compatible metric state:

======================= ==================================================
layout (last axis)      meaning
======================= ==================================================
``[0, L*k)``            positive-magnitude counts, level-major
``[L*k, 2*L*k)``        negative-magnitude counts, level-major
``[2*L*k]``             total observation count (exact)
``[2*L*k + 1]``         exact min (identity ``+inf``)
``[2*L*k + 2]``         exact max (identity ``-inf``)
======================= ==================================================

Level 0 covers magnitudes ``[0, unit)`` with linear buckets of width
``unit/capacity``; level ``l >= 1`` covers ``[unit*2**(l-1), unit*2**l)``
with ``capacity`` linear buckets each.  Quantile estimates therefore carry a
**relative error <= 1/capacity** for magnitudes in
``[unit, unit*2**(levels-1))`` and an absolute error ``<= unit/capacity``
below ``unit`` (values past the top level clip into the last bucket; the
exact max slot still bounds upper quantiles).  Counts are integers stored in
float32 — exact up to ``2**24`` observations per bucket.

Why this shape: the merge of two sketches is an **elementwise sum of the
count slots plus min/max of the extrema slots** — associative, commutative,
and bit-identical under any fold order (integer-valued float adds are
exact), which is precisely the contract ``dist_reduce_fx`` needs.  The
sketch registers through ``add_state(..., dist_reduce_fx=sketch_merge(...))``
— an :class:`~tpumetrics.parallel.merge.AssociativeMerge` whose declared
identity is the empty sketch — so the existing fold/reshard, elastic-cut,
and GSPMD machinery handle it like any other state: elastic reshard places
the folded sketch on rank 0 and empties elsewhere (mirroring
``cat_placement="rank0"``), and the sharded step keeps it replicated with
the merge lowered to the collective.

**Windowing**: sketch-backed metrics optionally keep a ring of ``slots``
sub-sketches (shape ``(slots, N)``), each covering ``window/slots``
consecutive updates; rotating into a slot resets just that row — O(1)
device-side eviction, fixed shapes, no retrace (the ring index is a traced
function of the ``count`` state).
"""

from __future__ import annotations

import math
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpumetrics.metric import Metric
from tpumetrics.parallel.merge import AssociativeMerge
from tpumetrics.utils.exceptions import TPUMetricsUserError

Array = jax.Array

__all__ = [
    "SketchLayout",
    "SketchQuantiles",
    "empty_sketch",
    "sketch_merge",
]


def _require_static_int(value: Any, name: str) -> int:
    """Sketch/window geometry is state SHAPE — it must be a concrete python
    int (a traced or data-dependent value would change shapes per step and
    retrace every update; tpulint flags windowed cases as TPL305).  A
    non-integral float is rejected too — silently truncating 2.5 to a
    2-update window would monitor something the caller never asked for."""
    if isinstance(value, (jax.core.Tracer, jax.Array, np.ndarray)) or isinstance(value, bool):
        raise TPUMetricsUserError(
            f"`{name}` must be a static python int (got {type(value).__name__}): "
            "it determines state shapes, and a data-dependent value would "
            "retrace the update step every call (tpulint TPL305)."
        )
    if int(value) != value:
        raise TPUMetricsUserError(
            f"`{name}` must be a static python int, got {value!r} (refusing to "
            "silently truncate; tpulint TPL305 flags non-int window literals)."
        )
    return int(value)


class SketchLayout:
    """Static geometry of one sketch row: index math, representative values,
    and the merge/identity pair.  Hash/eq by parameters so equal layouts
    share jit caches.

    ``unit`` defaults to ``2**(24 - levels)``, anchoring the TOP of the
    covered range at ``unit * 2**(levels-1) = 2**23 ≈ 8.4e6`` regardless of
    ``levels`` — so shrinking ``levels`` coarsens precision near zero
    instead of silently cutting the range off at tiny magnitudes (a
    levels=16 sketch with a bottom-anchored unit would top out at 0.03 and
    clip every real-world latency/score into one bucket).  Set ``unit``
    explicitly when small magnitudes need relative precision."""

    def __init__(
        self, levels: int = 44, capacity: int = 64, unit: Optional[float] = None
    ) -> None:
        self.levels = _require_static_int(levels, "levels")
        self.capacity = _require_static_int(capacity, "capacity")
        self.unit = float(unit) if unit is not None else 2.0 ** (24 - self.levels)
        if self.levels < 2 or self.capacity < 2:
            raise TPUMetricsUserError(
                f"Sketch needs levels >= 2 and capacity >= 2, got levels={self.levels}, "
                f"capacity={self.capacity}"
            )
        if not (self.unit > 0.0 and math.isfinite(self.unit)):
            raise TPUMetricsUserError(f"Sketch unit must be a positive finite float, got {unit}")
        self.side = self.levels * self.capacity  # buckets per sign
        self.total_index = 2 * self.side
        self.min_index = 2 * self.side + 1
        self.max_index = 2 * self.side + 2
        self.width = 2 * self.side + 3  # N: flat row length
        # representative (midpoint) magnitude per positive bucket, level-major
        lvl = np.repeat(np.arange(self.levels), self.capacity)
        j = np.tile(np.arange(self.capacity), self.levels)
        lo = np.where(lvl == 0, 0.0, self.unit * 2.0 ** (lvl - 1))
        width = np.where(lvl == 0, self.unit, self.unit * 2.0 ** (lvl - 1)) / self.capacity
        self._reps = (lo + (j + 0.5) * width).astype(np.float32)
        # canonical ascending value order: negatives (magnitude descending)
        # then positives (magnitude ascending)
        self._ordered_reps = np.concatenate([-self._reps[::-1], self._reps]).astype(np.float32)

    @property
    def params(self) -> dict:
        return {"levels": self.levels, "capacity": self.capacity, "unit": self.unit}

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, SketchLayout) and self.params == other.params

    def __hash__(self) -> int:
        return hash((self.levels, self.capacity, self.unit))

    def __repr__(self) -> str:
        return f"SketchLayout(levels={self.levels}, capacity={self.capacity}, unit={self.unit!r})"

    # ------------------------------------------------------------- ingestion

    def bucket_index(self, values: Array) -> Array:
        """Flat count-slot index per value (sign-mirrored, level-major);
        trace-safe, static output shape."""
        a = jnp.abs(values)
        safe = jnp.maximum(a, jnp.asarray(self.unit, values.dtype) * 2.0**-40)
        # clip in FLOAT space before the int cast: floor(log2(inf)) cast to
        # int32 saturates to INT32_MAX and the +1 would wrap to INT32_MIN,
        # sending an inf outlier to the near-zero bucket instead of the
        # documented top-bucket clip
        lvl = jnp.clip(
            jnp.floor(jnp.log2(safe / self.unit)) + 1.0, 0, self.levels - 1
        ).astype(jnp.int32)
        lo = jnp.where(lvl == 0, 0.0, self.unit * jnp.exp2((lvl - 1).astype(values.dtype)))
        width = jnp.where(lvl == 0, self.unit, self.unit * jnp.exp2((lvl - 1).astype(values.dtype)))
        j = jnp.clip(((a - lo) * self.capacity / width).astype(jnp.int32), 0, self.capacity - 1)
        flat = lvl * self.capacity + j
        return jnp.where(values < 0, flat + self.side, flat)

    def update_row(self, row: Array, values: Array, weights: Array) -> Array:
        """One sketch-row transition: scatter-add ``weights`` at each value's
        bucket, bump total, refresh exact min/max (weight-0 rows are inert).
        Pure and traceable; static shapes throughout."""
        values = values.reshape(-1)
        weights = weights.reshape(-1).astype(row.dtype)
        counts = row[: self.total_index].at[self.bucket_index(values)].add(weights)
        total = row[self.total_index] + jnp.sum(weights)
        live = weights > 0
        # initial= keeps a zero-size batch a neutral no-op
        minv = jnp.minimum(
            row[self.min_index], jnp.min(jnp.where(live, values, jnp.inf), initial=jnp.inf)
        )
        maxv = jnp.maximum(
            row[self.max_index], jnp.max(jnp.where(live, values, -jnp.inf), initial=-jnp.inf)
        )
        return jnp.concatenate([counts, total[None], minv[None], maxv[None]])

    # ----------------------------------------------------------------- fold

    def empty(self, panes: int = 1) -> Array:
        """The merge identity: zero counts, ``+inf`` min, ``-inf`` max — one
        ``(panes, N)`` ring of empty sub-sketch rows (``panes=1`` for an
        unwindowed sketch)."""
        row = np.zeros((self.width,), np.float32)
        row[self.min_index] = np.inf
        row[self.max_index] = -np.inf
        return jnp.asarray(np.broadcast_to(row, (int(panes), self.width)).copy())

    def merge(self, stacked: Array) -> Array:
        """Fold a rank-stacked sketch state ``(R, ..., N)`` along axis 0:
        counts (and the total slot) sum, min/max slots fold with min/max.
        Associative, commutative, and bit-identical under any fold order
        (counts are integer-valued floats)."""
        counts = jnp.sum(stacked[..., : self.total_index + 1], axis=0)
        minv = jnp.min(stacked[..., self.min_index : self.min_index + 1], axis=0)
        maxv = jnp.max(stacked[..., self.max_index : self.max_index + 1], axis=0)
        return jnp.concatenate([counts, minv, maxv], axis=-1)

    def merge_panes(self, ring: Array) -> Array:
        """Collapse a ``(panes, N)`` ring into one logical sketch row — the
        same fold as :meth:`merge`, over the pane axis."""
        return self.merge(ring)

    def identity_like(self, value: Any) -> Array:
        """The merge identity shaped like ``value`` (a method, not a
        closure, so sketch metrics stay picklable mid-stream)."""
        shape = tuple(jnp.shape(value))
        panes = int(np.prod(shape[:-1])) if len(shape) > 1 else 1
        return self.empty(panes).reshape(shape)

    # ---------------------------------------------------------------- reading

    def total(self, row: Array) -> Array:
        return row[..., self.total_index]

    def ordered_counts(self, row: Array) -> Array:
        """Counts in canonical ascending value order (most negative first)."""
        pos = row[..., : self.side]
        neg = row[..., self.side : self.total_index]
        return jnp.concatenate([neg[..., ::-1], pos], axis=-1)

    def pmf(self, row: Array, eps: float = 0.0) -> Array:
        """Bucket probability masses in canonical order; an empty sketch
        yields all-zeros.  ``eps`` floors each mass (drift-score smoothing)."""
        counts = self.ordered_counts(row)
        total = jnp.maximum(self.total(row), 1.0)
        p = counts / total
        return jnp.maximum(p, eps) if eps else p

    def quantile(self, row: Array, q: Any) -> Array:
        """Quantile estimate(s) from one logical sketch row: bucket-midpoint
        lookup on the cumulative counts, clamped into the exact
        ``[min, max]`` envelope.  ``q`` may be a scalar or a vector; an empty
        sketch returns NaN."""
        qs = jnp.asarray(q, jnp.float32)
        counts = self.ordered_counts(row)
        cdf = jnp.cumsum(counts)
        total = self.total(row)
        idx = jnp.clip(
            jnp.searchsorted(cdf, qs * total, side="left"), 0, 2 * self.side - 1
        )
        est = jnp.asarray(self._ordered_reps)[idx]
        est = jnp.clip(est, row[..., self.min_index], row[..., self.max_index])
        return jnp.where(total > 0, est, jnp.nan)


def empty_sketch(layout: SketchLayout, panes: int = 1) -> Array:
    """The sketch state default — the merge identity (tpulint TPL301 for the
    callable-merge kind: a non-identity default would double-count on every
    cross-rank fold)."""
    return layout.empty(panes)


def sketch_merge(layout: SketchLayout) -> AssociativeMerge:
    """The sketch's ``dist_reduce_fx``: an
    :class:`~tpumetrics.parallel.merge.AssociativeMerge` wrapping
    :meth:`SketchLayout.merge` with the empty sketch as its declared
    identity, carrying the layout parameters so snapshot spec mismatches
    name them (capacity/levels/unit).  Built from bound layout methods (no
    closures), so sketch metrics pickle/deepcopy mid-stream."""
    return AssociativeMerge(
        layout.merge, layout.identity_like, name="sketch", params=layout.params
    )


def ring_position(count: Array, pane_updates: int, slots: int) -> Tuple[Array, Array]:
    """``(slot index, is-first-update-of-its-pane)`` for the ``count``-th
    update of a ``slots``-slot ring whose panes span ``pane_updates``
    updates each.  THE one copy of the window-rotation math — the windowed
    aggregators and the sketch ring share it, which is what keeps the two
    families' pane alignment (and the lockstep mid-window resize guarantee)
    bit-identical."""
    idx = jnp.mod(count // pane_updates, slots)
    fresh = jnp.equal(jnp.mod(count, pane_updates), 0)
    return idx, fresh


def _broadcast_rowmask(mask: Array, like: Array) -> Array:
    """Expand a per-row ``valid`` mask to ``like``'s shape (mask covers the
    leading dims; trailing feature dims broadcast)."""
    mask = jnp.asarray(mask)
    extra = like.ndim - mask.ndim
    if extra > 0:
        mask = mask.reshape(mask.shape + (1,) * extra)
    return jnp.broadcast_to(mask, like.shape)


class _SketchBacked(Metric):
    """Shared machinery for sketch-state metrics: the ``(slots, N)`` ring
    state, the pane-rotating trace-safe update (native ``valid`` mask
    protocol — exact under the runtime's bucketed/megabatch paths), and the
    merged logical-row reader.

    ``window`` (in ``update()`` calls) splits into ``slots`` sub-sketches of
    ``window/slots`` updates each; rotation resets one ring row (O(1)
    eviction).  ``window=None`` keeps one cumulative sketch.
    """

    full_state_update: bool = False

    def __init__(
        self,
        levels: int = 44,
        capacity: int = 64,
        unit: Optional[float] = None,
        window: Optional[int] = None,
        slots: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        layout = SketchLayout(levels=levels, capacity=capacity, unit=unit)
        self._sketch_layout = layout
        self.levels = layout.levels
        self.capacity = layout.capacity
        self.unit = layout.unit
        if window is None:
            self.window = None
            self.slots = 1
        else:
            self.window = _require_static_int(window, "window")
            if self.window < 1:
                raise TPUMetricsUserError(f"window must be >= 1 update, got {self.window}")
            if slots is None:
                # largest divisor of the window <= 8: any window constructs
                slots = max(s for s in range(1, min(self.window, 8) + 1) if self.window % s == 0)
            self.slots = _require_static_int(slots, "slots")
            if self.slots < 1 or self.window % self.slots:
                raise TPUMetricsUserError(
                    f"window ({self.window}) must divide evenly into slots ({self.slots}) "
                    "sub-windows (pane size = window // slots)."
                )
        self._pane_updates = (self.window // self.slots) if self.window else 1
        self.add_state(
            "sketch",
            default=empty_sketch(layout, self.slots),
            dist_reduce_fx=sketch_merge(layout),
        )
        # lockstep tick counter driving the pane ring; ranks hold identical
        # values, so the idempotent max-fold is the correct merge
        self.add_state("count", default=jnp.asarray(0, jnp.int32), dist_reduce_fx="max")  # tpulint: disable=TPL301 -- lockstep tick counter: ranks hold identical nonnegative counts, so 0 is the fold identity on this domain

    def update(self, value: Any, valid: Optional[Array] = None) -> None:
        """Fold one batch of samples into the current sub-window's sketch.

        ``valid`` is the runtime's native bucket mask (per leading row);
        masked and NaN samples contribute zero weight.  Every call ticks the
        window by one update regardless of the mask."""
        v = jnp.asarray(value, self._dtype)
        v = jnp.atleast_1d(v)
        w = jnp.ones_like(v)
        if valid is not None:
            w = w * _broadcast_rowmask(valid, v).astype(v.dtype)
        nan = jnp.isnan(v)
        w = jnp.where(nan, 0.0, w)
        v = jnp.where(nan, 0.0, v)

        layout = self._sketch_layout
        if self.window is None:
            self.sketch = layout.update_row(self.sketch[0], v, w)[None, :]
        else:
            idx, fresh = ring_position(self.count, self._pane_updates, self.slots)
            base = jnp.where(fresh, layout.empty(1)[0], self.sketch[idx])
            self.sketch = self.sketch.at[idx].set(layout.update_row(base, v, w))
        self.count = self.count + 1

    def merged_row(self) -> Array:
        """The ring collapsed to one logical sketch row (pure)."""
        return self._sketch_layout.merge_panes(self.sketch)

    def compute(self) -> Any:  # pragma: no cover - abstract-ish
        raise NotImplementedError


class SketchQuantiles(_SketchBacked):
    """Streaming quantiles over an unbounded (optionally windowed) stream.

    ``compute()`` returns one estimate per requested quantile, with relative
    error ``<= 1/capacity`` inside the sketch's magnitude range
    (:mod:`tpumetrics.monitoring.sketch` module docstring has the exact
    bounds).  State is a fixed-shape mergeable sketch: cross-rank sync,
    snapshots, elastic resize, and the fused/bucketed runtime paths all work
    like any reduce-op metric.

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.monitoring import SketchQuantiles
        >>> m = SketchQuantiles(quantiles=(0.5,), capacity=128)
        >>> m.update(jnp.arange(1.0, 101.0))
        >>> bool(abs(float(m.compute()) - 50.0) < 1.0)
        True
    """

    def __init__(self, quantiles: Sequence[float] = (0.5, 0.9, 0.99), **kwargs: Any) -> None:
        super().__init__(**kwargs)
        qs = tuple(float(q) for q in quantiles)
        if not qs or any(not (0.0 <= q <= 1.0) for q in qs):
            raise TPUMetricsUserError(f"quantiles must be within [0, 1], got {quantiles}")
        self.quantiles = qs

    def compute(self) -> Array:
        return self._sketch_layout.quantile(self.merged_row(), jnp.asarray(self.quantiles))
