"""Continuous-monitoring metrics: windows, decay, sketches, drift.

The online-monitoring workload class (`docs/monitoring.md`): unbounded
serving streams where "the metric" is a sliding window, a decayed average, a
streaming quantile, or a drift score — all with fixed-shape, trace-safe,
*mergeable* state, so the existing runtime (bucketed/fused/megabatch paths),
snapshot/elastic, and GSPMD machinery carry them unchanged.
"""

from tpumetrics.monitoring.drift import (
    DriftMonitor,
    KLDrift,
    KSDistance,
    PSI,
    current_stream,
    monitoring_stats,
    release_stream,
    stream_scope,
)
from tpumetrics.monitoring.sketch import (
    SketchLayout,
    SketchQuantiles,
    empty_sketch,
    sketch_merge,
)
from tpumetrics.monitoring.windowed import (
    DecayedMean,
    WindowedMax,
    WindowedMean,
    WindowedMin,
    WindowedSum,
)

__all__ = [
    "DecayedMean",
    "DriftMonitor",
    "KLDrift",
    "KSDistance",
    "PSI",
    "SketchLayout",
    "SketchQuantiles",
    "WindowedMax",
    "WindowedMean",
    "WindowedMin",
    "WindowedSum",
    "current_stream",
    "empty_sketch",
    "monitoring_stats",
    "release_stream",
    "sketch_merge",
    "stream_scope",
]
