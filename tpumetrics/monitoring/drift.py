"""Drift monitors: live sketch vs a frozen reference distribution.

A drift monitor is an ordinary sketch-backed metric (state = the mergeable
``(slots, N)`` sketch of :mod:`tpumetrics.monitoring.sketch`, optionally
windowed) whose ``compute()`` returns a **divergence score** between the
live distribution and a reference distribution frozen at construction:

========================= =============================================
:class:`PSI`              population stability index
                          ``sum((p - q) * ln(p / q))`` over sketch
                          buckets (eps-smoothed); the industry-standard
                          "has the feature shifted" score (rule of
                          thumb: < 0.1 stable, > 0.25 shifted)
:class:`KLDrift`          ``KL(live || reference)`` over sketch buckets
                          (eps-smoothed)
:class:`KSDistance`       Kolmogorov–Smirnov statistic: max CDF gap
                          between the live (windowed) histogram and the
                          reference — scale-free, in ``[0, 1]``
========================= =============================================

The reference is pushed through the *same* sketch binning once, eagerly, at
construction, and stored as plain (non-state) bucket masses — so live and
reference are always compared on identical bins, and the monitor's
registered state stays a pure mergeable sketch (snapshots, elastic resize,
and cross-rank merge need nothing new).  ``reference_digest`` (a content
hash) rides the config fingerprint, so restoring a snapshot into a monitor
with a *different* reference fails loudly.

**Alerting** is a host-side ``compute()``-time effect (never reachable from
``update()`` — tpulint TPL104 enforces that separation): every concrete
score refreshes the ``tpumetrics_drift_score{stream,monitor}`` gauge, and an
upward threshold crossing emits ONE ``drift_alert`` ledger event + bumps
``tpumetrics_drift_alerts_total{stream,monitor}``.  The alert then latches:
it re-arms only after the score falls below ``threshold - hysteresis``, so a
score jittering around the threshold cannot page once per compute.  The
ambient stream label comes from :func:`stream_scope` (the runtime wraps its
compute paths in it; standalone OO use gets the ``""`` stream), and latches
are kept **per stream** so one shared-step metric instance serving many
tenants alerts independently per tenant.
"""

from __future__ import annotations

import hashlib
import threading
from contextlib import contextmanager
from typing import Any, Dict, Generator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from tpumetrics.metric import Metric
from tpumetrics.monitoring.sketch import _SketchBacked
from tpumetrics.telemetry import instruments as _instruments
from tpumetrics.telemetry import ledger as _telemetry
from tpumetrics.utils.exceptions import TPUMetricsUserError

Array = jax.Array

__all__ = [
    "DriftMonitor",
    "KLDrift",
    "KSDistance",
    "PSI",
    "current_stream",
    "monitoring_stats",
    "release_stream",
    "stream_scope",
]

_DRIFT_GAUGE = _instruments.gauge(
    _instruments.DRIFT_SCORE, help="latest drift-monitor score", labels=("stream", "monitor")
)
_DRIFT_ALERTS = _instruments.counter(
    _instruments.DRIFT_ALERTS,
    help="drift threshold crossings (hysteresis-latched)",
    labels=("stream", "monitor"),
)

_SCOPE = threading.local()


@contextmanager
def stream_scope(stream: str) -> Generator[None, None, None]:
    """Ambient stream/tenant label for drift bookkeeping on this thread —
    the runtime wraps its compute paths in it so one shared metric instance
    keeps per-tenant scores, latches, and gauge series apart."""
    prev = getattr(_SCOPE, "stream", "")
    _SCOPE.stream = str(stream)
    try:
        yield
    finally:
        _SCOPE.stream = prev


def current_stream() -> str:
    return getattr(_SCOPE, "stream", "")


class DriftMonitor(_SketchBacked):
    """Base class: live sketch vs frozen reference + threshold alerting.

    Args:
        reference: reference sample values (array-like) — binned once at
            construction through this monitor's own sketch layout.
        threshold: score at or above which a ``drift_alert`` fires.
        hysteresis: re-arm margin — after an alert, the latch clears only
            once the score drops below ``threshold - hysteresis``.
        score_bins: PSI/KL are scored over this many **equal-reference-mass
            groups** of sketch buckets (the classic "reference deciles"
            practice, assignment frozen at construction): scoring directly
            over thousands of fine sketch buckets would drown a real shift
            in per-bucket sampling noise.  KS ignores it (a max-CDF-gap is
            noise-robust at full resolution).
        eps: probability floor for the PSI/KL ratio terms (ignored by KS).
        name: monitor label for telemetry (default: the class name).
        window / slots / levels / capacity / unit: sketch geometry
            (:class:`~tpumetrics.monitoring.sketch._SketchBacked`).
    """

    higher_is_better = False

    def __init__(
        self,
        reference: Any,
        threshold: float = 0.25,
        hysteresis: float = 0.0,
        score_bins: int = 10,
        eps: float = 1e-6,
        name: Optional[str] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.threshold = float(threshold)
        self.hysteresis = float(hysteresis)
        self.eps = float(eps)
        self.score_bins = int(score_bins)
        if self.hysteresis < 0:
            raise TPUMetricsUserError(f"hysteresis must be >= 0, got {hysteresis}")
        if self.score_bins < 2:
            raise TPUMetricsUserError(f"score_bins must be >= 2, got {score_bins}")
        self.monitor_name = str(name) if name is not None else type(self).__name__
        ref = np.asarray(jax.device_get(jnp.asarray(reference, self._dtype)))
        ref = ref[np.isfinite(ref)]
        if ref.size == 0:
            raise TPUMetricsUserError(
                f"{type(self).__name__} needs a non-empty finite reference sample."
            )
        layout = self._sketch_layout
        row = np.asarray(
            jax.device_get(
                layout.update_row(layout.empty(1)[0], jnp.asarray(ref), jnp.ones(ref.shape))
            )
        )
        counts = np.asarray(jax.device_get(layout.ordered_counts(jnp.asarray(row))))
        self._ref_pmf = (counts / max(float(row[layout.total_index]), 1.0)).astype(np.float32)
        # sketch bucket -> score-bin assignment at equal reference mass
        # (midpoint-CDF rule; zero-mass tail buckets join the edge bins, so
        # out-of-reference-range live data still shows up as edge-bin mass)
        cdf = np.cumsum(self._ref_pmf, dtype=np.float64)
        mid = cdf - 0.5 * self._ref_pmf
        self._score_assign = np.clip(
            (mid * self.score_bins).astype(np.int32), 0, self.score_bins - 1
        )
        self._ref_binned = np.bincount(
            self._score_assign, weights=self._ref_pmf, minlength=self.score_bins
        ).astype(np.float32)
        # content hash of the binned reference: restoring a snapshot into a
        # monitor frozen against a DIFFERENT reference must fail loudly, and
        # a plain-scalar public attr rides _config_fingerprint for free
        self.reference_digest = hashlib.sha1(counts.tobytes()).hexdigest()
        # per-stream host bookkeeping: {stream: {score, active, alerts}},
        # guarded by a lock — the evaluator's compute_every refresh runs
        # compute() on the worker thread while user threads compute() too,
        # and an unguarded check-then-act on the latch would double-page one
        # crossing (the exactly-once contract)
        self._stream_state: Dict[str, Dict[str, Any]] = {}
        self._alert_lock = threading.Lock()

    def _binned(self, pmf: Array) -> Array:
        """Aggregate a full-resolution pmf into the frozen equal-reference-
        mass score bins (pure; static assignment)."""
        return jax.ops.segment_sum(
            pmf, jnp.asarray(self._score_assign), num_segments=self.score_bins
        )

    # locks don't deepcopy/pickle: clone()/collection construction rebuild a
    # fresh one (latch state itself is plain data and copies fine)
    def __getstate__(self) -> Dict[str, Any]:
        state = super().__getstate__()
        state.pop("_alert_lock", None)
        return state

    def __setstate__(self, state: Dict[str, Any]) -> None:
        super().__setstate__(state)
        self._alert_lock = threading.Lock()

    # ----------------------------------------------------------------- score

    def _score(self, live_pmf: Array, ref_pmf: Array) -> Array:
        raise NotImplementedError

    def drift_score(self) -> Array:
        """The pure score (no alerting side effects): live sketch pmf vs the
        frozen reference pmf; ``0`` before any live data."""
        layout = self._sketch_layout
        row = self.merged_row()
        live = layout.pmf(row)
        score = self._score(live, jnp.asarray(self._ref_pmf))
        return jnp.where(layout.total(row) > 0, score, 0.0)

    def compute(self) -> Array:
        score = self.drift_score()
        self._maybe_alert(score)
        return score

    # -------------------------------------------------------------- alerting

    def _runtime(self, stream: str) -> Dict[str, Any]:
        entry = self._stream_state.get(stream)
        if entry is None:
            entry = {"score": None, "active": False, "alerts": 0}
            self._stream_state[stream] = entry
        return entry

    def _maybe_alert(self, score: Any) -> None:
        """Host-side: gauge refresh + hysteresis-latched threshold alert.
        Inert inside a trace (a traced score has no concrete value to
        compare — the runtime's compute paths are eager over concrete
        states, which is where alerting belongs).  The whole read-modify-
        write runs under the alert lock so a worker-thread compute_every
        refresh racing a user-thread compute() cannot double-fire one
        crossing."""
        if isinstance(score, jax.core.Tracer):
            return
        value = float(score)
        stream = current_stream()
        with self._alert_lock:
            entry = self._runtime(stream)
            entry["score"] = value
            if _instruments.enabled():
                _DRIFT_GAUGE.set(value, stream, self.monitor_name)
            if value >= self.threshold and not entry["active"]:
                entry["active"] = True
                entry["alerts"] += 1
                if _instruments.enabled():
                    _DRIFT_ALERTS.inc(1, stream, self.monitor_name)
                _telemetry.record_event(
                    self._active_backend(),
                    "drift_alert",
                    monitor=self.monitor_name,
                    metric=type(self).__name__,
                    stream=stream,
                    score=value,
                    threshold=self.threshold,
                )
            elif entry["active"] and value < self.threshold - self.hysteresis:
                entry["active"] = False

    def monitoring_entry(self, stream: Optional[str] = None) -> Dict[str, Any]:
        """This monitor's telemetry view for one stream (``stats()``
        ``"monitoring"`` section)."""
        with self._alert_lock:
            entry = dict(self._runtime(current_stream() if stream is None else stream))
        return {
            "monitor": type(self).__name__,
            "score": entry["score"],
            "threshold": self.threshold,
            "hysteresis": self.hysteresis,
            "alert_active": entry["active"],
            "alerts": entry["alerts"],
            "window": self.window,
        }


class PSI(DriftMonitor):
    """Population stability index between the live sketch and the reference.

    Example:
        >>> import numpy as np
        >>> from tpumetrics.monitoring import PSI
        >>> rng = np.random.default_rng(0)
        >>> ref = rng.normal(0.0, 1.0, 4000)
        >>> m = PSI(reference=ref, threshold=0.25)
        >>> m.update(rng.normal(0.0, 1.0, 4000))  # same distribution
        >>> bool(m.compute() < 0.1)
        True
    """

    def _score(self, live_pmf: Array, ref_pmf: Array) -> Array:
        p = jnp.clip(self._binned(live_pmf), self.eps, 1.0)
        q = jnp.clip(jnp.asarray(self._ref_binned), self.eps, 1.0)
        return jnp.sum((p - q) * jnp.log(p / q))


class KLDrift(DriftMonitor):
    """``KL(live || reference)`` over the shared sketch bins.

    Example:
        >>> import numpy as np
        >>> from tpumetrics.monitoring import KLDrift
        >>> ref = np.arange(1.0, 1001.0)
        >>> m = KLDrift(reference=ref, threshold=0.25)
        >>> m.update(ref + 2000.0)  # the live stream moved entirely
        >>> bool(m.compute() > 0.25)
        True
    """

    def _score(self, live_pmf: Array, ref_pmf: Array) -> Array:
        p = jnp.clip(self._binned(live_pmf), self.eps, 1.0)
        q = jnp.clip(jnp.asarray(self._ref_binned), self.eps, 1.0)
        return jnp.sum(p * jnp.log(p / q))


class KSDistance(DriftMonitor):
    """Kolmogorov–Smirnov distance between the live (windowed) histogram's
    CDF and the reference CDF — scale-free, bounded in ``[0, 1]``, the usual
    choice for "did the whole shape move" monitoring.

    Example:
        >>> import numpy as np
        >>> from tpumetrics.monitoring import KSDistance
        >>> ref = np.arange(1.0, 1001.0)
        >>> m = KSDistance(reference=ref, threshold=0.5)
        >>> m.update(ref)  # live matches the reference
        >>> bool(m.compute() < 0.05)
        True
    """

    def _score(self, live_pmf: Array, ref_pmf: Array) -> Array:
        return jnp.max(jnp.abs(jnp.cumsum(live_pmf) - jnp.cumsum(ref_pmf)))


# ----------------------------------------------------------- runtime surface


def _iter_monitors(metric: Any):
    from tpumetrics.collections import MetricCollection

    if isinstance(metric, MetricCollection):
        for key, member in metric._modules.items():
            if isinstance(member, DriftMonitor):
                yield key, member
    elif isinstance(metric, DriftMonitor):
        yield metric.monitor_name, metric


def monitoring_stats(metric: Any, stream: str) -> Dict[str, Dict[str, Any]]:
    """The ``stats()["monitoring"]`` section for one stream: every
    :class:`DriftMonitor` in ``metric`` (a bare monitor or a collection
    member), keyed by its collection key / monitor name.  Empty dict when
    the metric carries no monitors."""
    return {key: mon.monitoring_entry(stream) for key, mon in _iter_monitors(metric)}


def release_stream(metric: Any, stream: str) -> None:
    """Drop one stream's drift bookkeeping and its gauge/counter label
    series — the monitoring side of the runtime's close() contract (auto-
    minted stream labels must not leak dead series in construct-per-job
    processes)."""
    for _key, mon in _iter_monitors(metric):
        with mon._alert_lock:
            mon._stream_state.pop(stream, None)
        _DRIFT_GAUGE.remove(stream, mon.monitor_name)
        _DRIFT_ALERTS.remove(stream, mon.monitor_name)
