"""Hand-written Pallas TPU kernels for the framework's hot ops.

Everything here has a pure-XLA fallback at its call site — kernels are an
optimization, never a requirement, and each wrapper exposes ``interpret=True``
so the exact kernel code is testable on CPU.
"""

from tpumetrics.ops.binned_confusion import binned_confusion_fused

__all__ = ["binned_confusion_fused"]
