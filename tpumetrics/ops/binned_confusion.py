"""Fused multi-threshold confusion accumulation (Pallas TPU kernel).

The binned PR-curve/ROC/AUROC update needs, for every threshold ``t`` and
class ``c``::

    tp[t, c]      = Σ_n (preds[n, c] >= thr[t]) · y[n, c]
    predpos[t, c] = Σ_n (preds[n, c] >= thr[t]) · v[n, c]

This kernel fuses the compare into the accumulation: ``preds`` is streamed
through VMEM once (tiles over N), each tile is compared against a tile of
thresholds and reduced on the VPU, and the ``(C, T)`` accumulators never
leave VMEM between N-tiles. HBM traffic is ``3·N·C`` reads + ``2·C·T``
writes regardless of T.

**Why it is not the default path**: measured on a TPU v5e, XLA compiles the
einsum formulation in ``_binned_confusion_contract`` to the same fusion —
the ``(N, C, T)`` comparison operand never hits HBM (T=200 → 4.6 ms,
T=1000 → 5.0 ms at N=8192, C=128; this kernel: 7.1/8.4 ms, grid-step
overhead bound). Hand-scheduling what the compiler already fuses buys
nothing, so the XLA path stays the default and this kernel is kept as a
pinned-semantics explicit alternative (and a ready fallback for hardware
or compiler versions where that fusion regresses), exercised by the test
suite in interpreter mode.

Exactness: all operands are 0/1-weighted f32 and every partial sum is an
integer below 2^24, so the result is exact — callers keep the same
``EXACT_F32_COUNT`` gate as the XLA path.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Array = jax.Array


def _kernel(thr_ref, preds_ref, y_ref, v_ref, tp_ref, pp_ref):
    """One (T-tile, N-tile) grid step: compare an N-tile against a T-tile of
    thresholds and accumulate into the revisited (C, T-tile) output blocks."""

    @pl.when(pl.program_id(1) == 0)
    def _init():
        tp_ref[...] = jnp.zeros_like(tp_ref)
        pp_ref[...] = jnp.zeros_like(pp_ref)

    preds = preds_ref[...]  # (TN, C)
    y = y_ref[...]  # (TN, C) target-bit · valid
    v = v_ref[...]  # (TN, C) valid
    thr = thr_ref[0]  # (TT,) — carried as (1, TT) for 2-D TPU tiling
    # (TN, C, TT) comparison lives only in VMEM/registers — never in HBM
    pos = (preds[:, :, None] >= thr[None, None, :]).astype(jnp.float32)
    tp_ref[...] += jnp.sum(pos * y[:, :, None], axis=0)  # (C, TT)
    pp_ref[...] += jnp.sum(pos * v[:, :, None], axis=0)


def binned_confusion_fused(
    preds: Array,
    y: Array,
    v: Array,
    thresholds: Array,
    interpret: bool = False,
) -> Tuple[Array, Array]:
    """Return ``(tp, predpos)``, each ``(T, C)`` f32, for the sums above.

    ``preds``/``y``/``v`` are ``(N, C)`` f32; ``thresholds`` is ``(T,)`` f32.
    ``interpret=True`` runs the kernel in the Pallas interpreter (CPU-safe,
    used by the test suite to pin the kernel's exact semantics).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.ops import binned_confusion_fused
        >>> preds = jnp.asarray([[0.2], [0.7], [0.9]])
        >>> y = jnp.asarray([[0.0], [1.0], [1.0]])
        >>> v = jnp.ones((3, 1))
        >>> thr = jnp.asarray([0.5])
        >>> tp, predpos = binned_confusion_fused(preds, y, v, thr, interpret=True)
        >>> float(tp[0, 0]), float(predpos[0, 0])
        (2.0, 2.0)
    """
    n, c = preds.shape
    t = thresholds.shape[0]

    # the (TN, C, TT) compare plus its two broadcast products must fit in
    # ~16 MB VMEM alongside the (C, TT) accumulators; budget ~0.5M elements.
    # Wide class counts shrink the T-tile first, then the N-tile; beyond the
    # budget even at the minimum (8, C, 8) tile the kernel cannot run
    budget = 1 << 19
    if c * 64 > budget:
        raise ValueError(
            f"binned_confusion_fused: num_classes={c} is too wide for the VMEM tile budget; "
            "use the XLA path (_binned_confusion_contract)"
        )
    # pad C to the 128-lane multiple: C is a block-shape lane dimension below,
    # and real-TPU tiling requires lane-aligned blocks (interpret mode would
    # accept any C and hide the misalignment — ADVICE r2)
    c_pad = max(128, -(-c // 128) * 128)
    tt = max(8, min(128, -(-t // 8) * 8, budget // (c_pad * 8) // 8 * 8))
    tn = max(8, min(1024, budget // max(c_pad * tt, 1) // 8 * 8))
    n_pad = -(-n // tn) * tn
    t_pad = -(-t // tt) * tt

    if c_pad != c:
        pad = ((0, 0), (0, c_pad - c))
        preds = jnp.pad(preds, pad)
        y = jnp.pad(y, pad)  # padded classes have v = y = 0 -> all-zero counts
        v = jnp.pad(v, pad)
    if n_pad != n:
        pad = ((0, n_pad - n), (0, 0))
        preds = jnp.pad(preds, pad)
        y = jnp.pad(y, pad)  # padded rows have v = y = 0 -> contribute nothing
        v = jnp.pad(v, pad)
    if t_pad != t:
        thresholds = jnp.pad(thresholds, (0, t_pad - t), constant_values=jnp.inf)
    thresholds = thresholds[None, :]  # 1-D operands get awkward TPU layouts

    grid = (t_pad // tt, n_pad // tn)
    tp, pp = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, tt), lambda i, j: (0, i)),
            pl.BlockSpec((tn, c_pad), lambda i, j: (j, 0)),
            pl.BlockSpec((tn, c_pad), lambda i, j: (j, 0)),
            pl.BlockSpec((tn, c_pad), lambda i, j: (j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((c_pad, tt), lambda i, j: (0, i)),
            pl.BlockSpec((c_pad, tt), lambda i, j: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((c_pad, t_pad), jnp.float32),
            jax.ShapeDtypeStruct((c_pad, t_pad), jnp.float32),
        ],
        interpret=interpret,
    )(thresholds, preds, y, v)
    return tp.T[:t, :c], pp.T[:t, :c]
