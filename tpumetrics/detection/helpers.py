"""Detection input validation helpers (counterpart of reference
``detection/helpers.py``)."""

from __future__ import annotations

from typing import Dict, Sequence, Union

import jax
import jax.numpy as jnp

Array = jax.Array


def _fix_empty_tensors(boxes: Array) -> Array:
    """Empty tensors get a (0, 4) shape so downstream ops are well-defined
    (reference helpers.py:88-93)."""
    boxes = jnp.asarray(boxes)
    if boxes.size == 0 and boxes.ndim == 1:
        return boxes.reshape(0, 4)
    return boxes


def _input_validator(
    preds: Sequence[Dict[str, Array]],
    targets: Sequence[Dict[str, Array]],
    iou_type: Union[str, tuple] = "bbox",
    ignore_score: bool = False,
) -> None:
    """Validate the list-of-dict detection input format (reference helpers.py:22-85)."""
    if isinstance(iou_type, str):
        iou_type = (iou_type,)
    item_val_name = {"bbox": "boxes", "segm": "masks"}
    if any(t not in ("bbox", "segm") for t in iou_type):
        raise Exception(f"IOU type {iou_type} is not supported")

    if not isinstance(preds, Sequence):
        raise ValueError(f"Expected argument `preds` to be of type Sequence, but got {preds}")
    if not isinstance(targets, Sequence):
        raise ValueError(f"Expected argument `target` to be of type Sequence, but got {targets}")
    if len(preds) != len(targets):
        raise ValueError(
            f"Expected argument `preds` and `target` to have the same length, but got {len(preds)} and {len(targets)}"
        )

    for t in iou_type:
        name = item_val_name[t]
        if any(name not in p for p in preds):
            raise ValueError(f"Expected all dicts in `preds` to contain the `{name}` key")
        if any(name not in tgt for tgt in targets):
            raise ValueError(f"Expected all dicts in `target` to contain the `{name}` key")
    if not ignore_score and any("scores" not in p for p in preds):
        raise ValueError("Expected all dicts in `preds` to contain the `scores` key")
    if any("labels" not in p for p in preds):
        raise ValueError("Expected all dicts in `preds` to contain the `labels` key")
    if any("labels" not in tgt for tgt in targets):
        raise ValueError("Expected all dicts in `target` to contain the `labels` key")

    for i, item in enumerate(targets):
        name = item_val_name[iou_type[0]]
        if item[name].shape[0] != item["labels"].shape[0]:
            raise ValueError(
                f"Input '{name}' and labels of sample {i} in targets have a"
                f" different length (expected {item[name].shape[0]} labels, got {item['labels'].shape[0]})"
            )
    if ignore_score:
        return
    for i, item in enumerate(preds):
        name = item_val_name[iou_type[0]]
        if not (item[name].shape[0] == item["labels"].shape[0] == item["scores"].shape[0]):
            raise ValueError(
                f"Input '{name}', labels and scores of sample {i} in predictions have a"
                f" different length (expected {item[name].shape[0]} labels and scores,"
                f" got {item['labels'].shape[0]} labels and {item['scores'].shape[0]})"
            )
