"""Jitted bucketed COCO matcher: the mAP hot path as ONE compiled program.

:mod:`tpumetrics.detection._coco_eval` already collapsed the per-(image,
class) greedy matching into a batched numpy pass over padded ``(cells, D,
G)`` cell buckets.  This module pushes the same ragged→bucketed-dense trick
one layer further down: the greedy matcher *and* the PR-curve accumulation
run as **one jitted XLA program** over a dense ``(K, I, D, G)`` cell grid —
pow-2 padded on every axis, so the compiled-program universe is bounded by
the bucket edges (the :mod:`tpumetrics.runtime.bucketing` shape discipline)
and the persistent compilation cache amortizes compiles across processes.

Bit-identical parity with the numpy reference path
(:func:`~tpumetrics.detection._coco_eval.coco_evaluate_unfused`) is a hard
contract, engineered rather than hoped for:

- all IoU/area arithmetic is **float64** (under a scoped
  ``jax.experimental.enable_x64``) with the exact elementwise formulas of
  the numpy path — elementwise IEEE double ops are deterministic, and the
  parity tests pin them bitwise;
- TP/FP cumulative sums act on 0/1 indicators, so any XLA scan
  re-association still produces exact integers;
- every division keeps a *runtime* divisor (XLA strength-reduces division
  by a compile-time constant into multiply-by-reciprocal, which is NOT
  bit-equal — ``npig`` is computed in-program from the inputs);
- sorts are stable, so forcing pad slots to ``-inf`` score provably
  preserves the relative order of real detections (a stable sort of a
  superset, restricted to a subset, equals the stable sort of the subset),
  and pad columns are TP=FP=0 no-ops that cannot move any sampled
  precision/recall value;
- the last-wins argmax is the same reversed-argmax trick as the numpy
  matcher.

The program runs on the default accelerator when a startup probe proves it
computes real float64 (many accelerator stacks lack f64 or silently demote
it, which would break the parity contract), and otherwise on the **host
CPU XLA client**: ``compute()`` is the one place the paper contract allows
a host sync, the inputs just arrived from the single state fetch, and the
CPU build keeps the math exact with zero extra round trips to a
remote-attached chip.  "Device-resident" mAP means the *state* lives on the
accelerator until ``compute()``; the protocol itself is compiled, not
interpreted, wherever it runs.

Scope: ``bbox`` matching without ``extended_summary``; RLE ``segm`` (host
mask decode) and the extended IoU payload stay on the numpy path, as does
any corpus whose padded cell grid exceeds :data:`MATCH_BUDGET` (a single
huge image would force the padding blow-up onto every cell).
:func:`coco_evaluate_jit` returns ``None`` for those, and callers fall back
to :func:`~tpumetrics.detection._coco_eval.coco_evaluate`.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from tpumetrics.detection._coco_eval import _AREA_RANGES, _summarize
from tpumetrics.runtime.bucketing import pow2_at_least as _pow2_at_least

#: padded work budget: cells * areas * thresholds * D_pad * G_pad elements
#: touched per matching pass.  Above it the dense grid would not fit the
#: fused program comfortably; the numpy bucketed path (which can split
#: buckets) takes over.
MATCH_BUDGET = 1 << 26

#: flip to False (or set TPUMETRICS_JIT_MATCHER=0) to force the numpy
#: matcher everywhere — the bench uses this to time the interpreted path
#: and tests use it to cross-check all three implementations.
_ENABLED = True

_PROGRAMS: Dict[Tuple, Callable] = {}

#: the matcher's identity in the shared device-profile registry
#: (:mod:`tpumetrics.telemetry.device`): every distinct compiled matcher
#: program registers its abstract call signature there, and the bench's MFU
#: accounting reads the newest profile under this label — ONE code path for
#: program cost, no detection-private ``last_cost_analysis`` variant
MATCHER_PROFILE_LABEL = "detection/coco_matcher"


def jit_matcher_enabled() -> bool:
    """Whether the jitted matcher is active (module flag + env override)."""
    return _ENABLED and os.environ.get("TPUMETRICS_JIT_MATCHER", "1") != "0"


def _cpu_device() -> Any:
    import jax

    try:
        return jax.local_devices(backend="cpu")[0]
    except Exception:  # no CPU client (exotic build): let callers fall back
        return None


_MATCHER_DEVICE: List[Any] = []  # memoized [device-or-None]


def _matcher_device() -> Any:
    """Where the matcher program runs: the default backend when it PROVABLY
    computes float64 (verified by a probe whose result a float32 fallback
    cannot produce — some accelerator stacks silently demote x64), else the
    host CPU XLA client.  Bit-exact parity is the contract; the accelerator
    is only an optimization when it keeps the contract."""
    if _MATCHER_DEVICE:
        return _MATCHER_DEVICE[0]
    import jax
    from jax.experimental import enable_x64

    device = _cpu_device()
    try:
        default = jax.devices()[0]
        if default.platform != "cpu":
            with enable_x64():
                eps = float(np.float64(2.0) ** -40)
                x = jax.device_put(np.float64(1.0 + eps), default)
                if float(jax.jit(lambda v: v - 1.0)(x)) == eps:
                    device = default
    except Exception:
        pass  # unprobeable backend: stay on the host CPU client
    _MATCHER_DEVICE.append(device)
    return device


def _build_program(
    kp: int,
    ip: int,
    dp: int,
    gp: int,
    c2s: int,
    d_trip: int,
    iou_thrs: Tuple[float, ...],
    rec_thrs: Tuple[float, ...],
    max_dets: Tuple[int, ...],
    area_ranges: Tuple[Tuple[float, float], ...],
) -> Callable:
    """One jitted match+accumulate program for a static cell-grid shape.

    Inputs (all dense, cell grid ``C = kp * ip`` flattened on the leading
    axis): det boxes f64 ``(C, dp, 4)`` xyxy, det scores f32 ``(C, dp)``,
    det valid bool, gt boxes f64 ``(C, gp, 4)``, gt crowd bool, gt area f64
    (user-provided; ``0`` falls back to geometry area in-program), gt valid
    bool.  Returns ``(precision (kp, A, T, M, R) f64, recall (kp, A, T, M)
    f64, npig (kp, A) i64)`` — assembled into the COCO ``(T, R, K, A, M)``
    layout on host.

    ``c2s`` is the static post-sort column budget: the caller guarantees no
    class holds more than ``c2s`` real (capped) detections, so after the
    stable score sort — pad slots forced to ``-inf`` — every real column
    lives in the first ``c2s`` positions and the tail is all no-op padding,
    which the accumulation may drop without moving any sampled value.  This
    is what keeps the cumsum/envelope work proportional to real detections
    instead of to the pow-2 cell-grid padding.
    """
    import jax
    import jax.numpy as jnp

    num_areas = len(area_ranges)
    num_thrs = len(iou_thrs)
    c2 = ip * dp

    def run(dbox, dscore, dvalid, gbox, gcrowd, garea, gvalid):
        # ---- geometry (f64 elementwise, formula-identical to numpy path)
        da = (dbox[..., 2] - dbox[..., 0]) * (dbox[..., 3] - dbox[..., 1])  # (C, dp)
        geom_ga = (gbox[..., 2] - gbox[..., 0]) * (gbox[..., 3] - gbox[..., 1])
        area_eff = jnp.where(garea > 0, garea, geom_ga)  # (C, gp)

        lt = jnp.maximum(dbox[:, :, None, :2], gbox[:, None, :, :2])
        rb = jnp.minimum(dbox[:, :, None, 2:], gbox[:, None, :, 2:])
        wh = jnp.clip(rb - lt, 0, None)
        inter = wh[..., 0] * wh[..., 1]  # (C, dp, gp)
        union = da[:, :, None] + geom_ga[:, None, :] - inter
        union = jnp.where(gcrowd[:, None, :], da[:, :, None], union)
        ious = inter / jnp.where(union > 0, union, 1.0)
        # pad pairs carry IoU -1 (below any threshold), like the numpy pad
        ious = jnp.where(dvalid[:, :, None] & gvalid[:, None, :], ious, -1.0)

        lo = jnp.asarray([r[0] for r in area_ranges], jnp.float64)
        hi = jnp.asarray([r[1] for r in area_ranges], jnp.float64)
        thr = jnp.minimum(jnp.asarray(iou_thrs, jnp.float64), 1 - 1e-10)  # (T,)

        # (C, A, G): crowd / out-of-range / pad gts absorb without counting
        gt_ignore = (
            gcrowd[:, None, :]
            | (area_eff[:, None, :] < lo[None, :, None])
            | (area_eff[:, None, :] > hi[None, :, None])
            | ~gvalid[:, None, :]
        )
        real = ~gt_ignore
        n_cells = ious.shape[0]

        det_matches0 = jnp.zeros((n_cells, num_areas, num_thrs, dp), bool)
        det_ignore0 = jnp.zeros((n_cells, num_areas, num_thrs, dp), bool)

        if gp <= 32:
            # ---- bitmask matching: the candidate/availability sets live as
            # ONE uint32 bitmask over the gt axis, so the loop's working set
            # shrinks ~G-fold.  Picking the greedy winner scans the gts in
            # descending (IoU, index) order — a flip of a stable ascending
            # argsort — which is EXACTLY the reference's last-wins argmax:
            # max IoU first, ties broken toward the larger index.
            pow2 = jnp.asarray((np.uint32(1) << np.arange(gp)).astype(np.uint32))

            def packbits(mask):  # (..., G) bool -> (...) uint32
                return jnp.sum(jnp.where(mask, pow2, jnp.uint32(0)), axis=-1, dtype=jnp.uint32)

            cand_thr = packbits(ious[:, :, None, :] >= thr[None, None, :, None])  # (C, dp, T)
            perm = jnp.flip(jnp.argsort(ious, axis=2, stable=True), axis=2)
            perm_bits = jnp.left_shift(jnp.uint32(1), perm.astype(jnp.uint32))  # (C, dp, G)
            real_b = packbits(real)  # (C, A)
            ign_b = packbits(gt_ignore)
            crowd_b = packbits(gcrowd & gvalid)  # (C,)
            avail0 = jnp.broadcast_to(
                packbits(gvalid)[:, None, None], (n_cells, num_areas, num_thrs)
            )

            def body(d_i, carry):
                avail, det_matches, det_ignore = carry
                ct = jax.lax.dynamic_index_in_dim(cand_thr, d_i, axis=1, keepdims=False)
                bitj = jax.lax.dynamic_index_in_dim(perm_bits, d_i, axis=1, keepdims=False)
                cand = avail & ct[:, None, :]  # (C, A, T)
                cand_real = cand & real_b[:, :, None]
                # non-ignored gts take precedence (reference sorted-ignored-last)
                pick = jnp.where(cand_real != 0, cand_real, cand & ign_b[:, :, None])
                has = pick != 0
                best = jnp.zeros((n_cells, num_areas, num_thrs), jnp.uint32)
                found = jnp.zeros((n_cells, num_areas, num_thrs), bool)
                for j in range(gp):  # static scan in descending (IoU, g) order
                    bj = bitj[:, j][:, None, None]
                    hit = (pick & bj) != 0
                    best = jnp.where(hit & ~found, bj, best)
                    found = found | hit
                picked_ignored = (best & ign_b[:, :, None]) != 0
                picked_crowd = (best & crowd_b[:, None, None]) != 0
                det_matches = jax.lax.dynamic_update_index_in_dim(
                    det_matches, has, d_i, axis=3
                )
                det_ignore = jax.lax.dynamic_update_index_in_dim(
                    det_ignore, has & picked_ignored, d_i, axis=3
                )
                # crowd gts absorb without being claimed
                avail = avail & ~jnp.where(has & ~picked_crowd, best, jnp.uint32(0))
                return avail, det_matches, det_ignore

        else:
            g_idx = jnp.arange(gp)
            avail0 = jnp.broadcast_to(
                gvalid[:, None, None, :], (n_cells, num_areas, num_thrs, gp)
            )

            def body(d_i, carry):
                avail, det_matches, det_ignore = carry
                iou_row = jax.lax.dynamic_index_in_dim(ious, d_i, axis=1, keepdims=False)
                cand = avail & (iou_row[:, None, None, :] >= thr[None, None, :, None])
                cand_real = cand & real[:, :, None, :]
                use_real = cand_real.any(axis=3)  # non-ignored gts take precedence
                pick_from = jnp.where(
                    use_real[..., None], cand_real, cand & gt_ignore[:, :, None, :]
                )
                has = pick_from.any(axis=3)  # (C, A, T)
                vals = jnp.where(pick_from, iou_row[:, None, None, :], -1.0)
                best_g = gp - 1 - jnp.argmax(vals[..., ::-1], axis=3)  # last-wins
                onehot = g_idx[None, None, None, :] == best_g[..., None]  # (C, A, T, G)
                picked_ignored = jnp.any(onehot & gt_ignore[:, :, None, :], axis=3)
                picked_crowd = jnp.any(onehot & gcrowd[:, None, None, :], axis=3)
                det_matches = jax.lax.dynamic_update_index_in_dim(
                    det_matches, has, d_i, axis=3
                )
                det_ignore = jax.lax.dynamic_update_index_in_dim(
                    det_ignore, has & picked_ignored, d_i, axis=3
                )
                claimed = has & ~picked_crowd  # crowd gts absorb without claiming
                avail = avail & ~(onehot & claimed[..., None])
                return avail, det_matches, det_ignore

        # trip count: detection slots past every cell's true (capped) count
        # hold IoU -1 everywhere — those iterations cannot match anything,
        # so the loop stops at d_trip (<= dp) exactly
        _avail, det_matches, det_ignore = jax.lax.fori_loop(
            0, d_trip, body, (avail0, det_matches0, det_ignore0)
        )

        # unmatched detections outside the area range are ignored
        det_out = (da[:, None, :] < lo[None, :, None]) | (da[:, None, :] > hi[None, :, None])
        det_ignore = det_ignore | (
            (~det_matches) & det_out[:, :, None, :] & dvalid[:, None, None, :]
        )
        num_gt = (~gt_ignore).sum(axis=2)  # (C, A)
        npig = num_gt.reshape(kp, ip, num_areas).sum(axis=1)  # (kp, A)

        # ---- accumulate: per class, ONE stable score sort over all columns.
        # Pad slots get score -inf: a stable sort of the superset restricted
        # to the real columns equals the numpy path's sort of the compacted
        # columns, and pad columns are TP=FP=0 no-ops everywhere below.
        scores_flat = jnp.where(dvalid, dscore, -jnp.inf).reshape(kp, c2)
        order = jnp.argsort(-scores_flat, axis=1, stable=True)[:, :c2s]  # (kp, c2s)
        rank_flat = jnp.broadcast_to(jnp.arange(dp)[None, :], (ip, dp)).reshape(c2)
        rank_sorted = jnp.take_along_axis(
            jnp.broadcast_to(rank_flat[None, :], (kp, c2)), order, axis=1
        )  # (kp, c2s)
        valid_sorted = jnp.take_along_axis(dvalid.reshape(kp, c2), order, axis=1)

        def sort_cols(x):  # (C, A, T, dp) -> (kp, A, T, c2s) in score order
            x = x.reshape(kp, ip, num_areas, num_thrs, dp)
            x = jnp.transpose(x, (0, 2, 3, 1, 4)).reshape(kp, num_areas, num_thrs, c2)
            return jnp.take_along_axis(x, order[:, None, None, :], axis=3)

        m_sorted = sort_cols(det_matches)
        i_sorted = sort_cols(det_ignore)
        live = valid_sorted[:, None, None, :]  # (kp, 1, 1, c2s)
        caps = jnp.stack(
            [(rank_sorted < m)[:, None, None, :] & live for m in max_dets], axis=3
        )  # (kp, 1, 1, M, c2s) broadcastable
        tp = (m_sorted & ~i_sorted)[:, :, :, None, :] & caps
        fp = (~m_sorted & ~i_sorted)[:, :, :, None, :] & caps
        # int32 scan then cast: TP/FP counts are 0/1 sums far below 2^31, so
        # the narrower scan is exact and halves the memory traffic of the
        # hottest tensors
        tp_sum = jnp.cumsum(tp.astype(jnp.int32), axis=4).astype(jnp.float64)
        fp_sum = jnp.cumsum(fp.astype(jnp.int32), axis=4).astype(jnp.float64)
        # npig is a traced value — the divisor must never be a compile-time
        # constant, or XLA strength-reduces to multiply-by-reciprocal and
        # the quotient is no longer bit-equal to the numpy division
        npig_safe = jnp.maximum(npig, 1).astype(jnp.float64)[:, :, None, None, None]
        rc = tp_sum / npig_safe
        pr = tp_sum / jnp.maximum(fp_sum + tp_sum, jnp.finfo(jnp.float64).eps)
        recall = rc[..., -1]  # (kp, A, T, M)
        env = jnp.flip(jax.lax.cummax(jnp.flip(pr, axis=4), axis=4), axis=4)

        rec_arr = jnp.asarray(rec_thrs, jnp.float64)
        rc2 = rc.reshape(-1, c2s)
        inds = jax.vmap(lambda r: jnp.searchsorted(r, rec_arr, side="left"))(rc2)
        env2 = env.reshape(-1, c2s)
        sampled = jnp.take_along_axis(env2, jnp.clip(inds, 0, c2s - 1), axis=1)
        q = jnp.where(inds < c2s, sampled, 0.0)
        precision = q.reshape(kp, num_areas, num_thrs, len(max_dets), len(rec_thrs))
        return precision, recall, npig

    return jax.jit(run)


def _dense_cells(
    boxes: np.ndarray,
    img: np.ndarray,
    cls_slot: np.ndarray,
    kp: int,
    ip: int,
    slot_pad: int,
    max_rows: Optional[int],
    extra: Sequence[np.ndarray] = (),
    order: Optional[np.ndarray] = None,
) -> Tuple[np.ndarray, np.ndarray, List[np.ndarray], int]:
    """Scatter flat rows into a dense ``(kp * ip, slot)`` cell grid.

    ``order`` pre-orders rows (score-descending for detections; ``None``
    keeps the stored order, which is the ground-truth convention).  Returns
    ``(dense_boxes, valid, dense_extras, max_cell_rows)`` where ``slot`` is
    ``slot_pad`` columns wide; rows whose within-cell rank reaches
    ``max_rows`` (the detection cap) are dropped exactly like the numpy
    path's ``order[:max_det]``.
    """
    n = boxes.shape[0]
    cell = cls_slot.astype(np.int64) * ip + img.astype(np.int64)
    if order is None:
        rows = np.argsort(cell, kind="mergesort")  # stable: keeps stored order
    else:
        rows = order[np.argsort(cell[order], kind="mergesort")]
    counts = np.bincount(cell, minlength=kp * ip)
    starts = np.cumsum(counts) - counts
    rank = np.arange(n, dtype=np.int64) - starts[cell[rows]]
    keep = rank < (slot_pad if max_rows is None else min(slot_pad, max_rows))
    slot = cell[rows][keep] * slot_pad + rank[keep]

    dense_boxes = np.zeros((kp * ip, slot_pad, 4), np.float64)
    dense_boxes.reshape(-1, 4)[slot] = boxes[rows][keep]
    valid = np.zeros((kp * ip, slot_pad), bool)
    valid.reshape(-1)[slot] = True
    outs = []
    for arr in extra:
        dense = np.zeros((kp * ip, slot_pad), arr.dtype)
        dense.reshape(-1)[slot] = arr[rows][keep]
        outs.append(dense)
    max_cell = int(counts.max()) if n else 0
    return dense_boxes, valid, outs, max_cell


def coco_evaluate_jit(
    detections: Sequence[Tuple[np.ndarray, np.ndarray, np.ndarray]],
    groundtruths: Sequence[Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]],
    iou_thresholds: Sequence[float],
    rec_thresholds: Sequence[float],
    max_detection_thresholds: Sequence[int],
    class_ids: Sequence[int],
    average: str = "macro",
    iou_type: str = "bbox",
    extended: bool = False,
) -> Optional[Dict[str, np.ndarray]]:
    """Full COCO evaluation through the jitted dense-cell program.

    Same contract as :func:`~tpumetrics.detection._coco_eval.coco_evaluate`
    (``detections`` per image ``(xyxy f64 geometry, scores, labels)``,
    ``groundtruths`` ``(geometry, labels, iscrowd, area)``); ``class_ids``
    must be sorted.  Returns ``None`` when the jitted path does not apply
    (disabled, ``segm``, ``extended``, empty corpus, or over
    :data:`MATCH_BUDGET`) — the caller falls back to the numpy matcher.
    """
    if iou_type != "bbox" or extended:
        return None
    num_imgs = len(detections)
    if num_imgs == 0:
        return None

    # ---- flatten the per-image lists into packed rows + segment ids
    d_img = np.repeat(np.arange(num_imgs), [d[1].shape[0] for d in detections])
    g_img = np.repeat(np.arange(num_imgs), [g[1].shape[0] for g in groundtruths])
    d_box = (
        np.concatenate([np.asarray(d[0], np.float64).reshape(-1, 4) for d in detections])
        if d_img.size else np.zeros((0, 4))
    )
    d_score = (
        np.concatenate([np.asarray(d[1], np.float32).reshape(-1) for d in detections])
        if d_img.size else np.zeros(0, np.float32)
    )
    d_label = (
        np.concatenate([np.asarray(d[2], np.int64).reshape(-1) for d in detections])
        if d_img.size else np.zeros(0, np.int64)
    )
    g_box = (
        np.concatenate([np.asarray(g[0], np.float64).reshape(-1, 4) for g in groundtruths])
        if g_img.size else np.zeros((0, 4))
    )
    g_label = (
        np.concatenate([np.asarray(g[1], np.int64).reshape(-1) for g in groundtruths])
        if g_img.size else np.zeros(0, np.int64)
    )
    g_crowd = (
        np.concatenate([np.asarray(g[2], np.int64).reshape(-1) for g in groundtruths])
        if g_img.size else np.zeros(0, np.int64)
    )
    g_area = (
        np.concatenate([np.asarray(g[3], np.float64).reshape(-1) for g in groundtruths])
        if g_img.size else np.zeros(0)
    )
    return coco_evaluate_rows(
        (d_box, d_score, d_label, d_img),
        (g_box, g_label, g_crowd, g_area, g_img),
        num_imgs, iou_thresholds, rec_thresholds, max_detection_thresholds,
        class_ids, average=average,
    )


def coco_evaluate_rows(
    det: Tuple[np.ndarray, ...],
    gt: Tuple[np.ndarray, ...],
    num_imgs: int,
    iou_thresholds: Sequence[float],
    rec_thresholds: Sequence[float],
    max_detection_thresholds: Sequence[int],
    class_ids: Sequence[int],
    average: str = "macro",
) -> Optional[Dict[str, np.ndarray]]:
    """Jitted evaluation straight off packed flat rows + segment ids — the
    device-resident state layout, with no per-image detour.

    ``det`` = ``(boxes_xyxy f64 (N, 4), scores f32, labels i64, img i64)``;
    ``gt`` adds crowd and area columns before the ids.  Same decline
    contract as :func:`coco_evaluate_jit` (returns ``None``).
    """
    if not jit_matcher_enabled() or num_imgs == 0 or not class_ids:
        return None
    device = _matcher_device()
    if device is None:
        return None
    return coco_evaluate_packed(
        det, gt, num_imgs,
        tuple(float(t) for t in iou_thresholds),
        tuple(float(t) for t in rec_thresholds),
        tuple(sorted(int(m) for m in max_detection_thresholds)),
        np.asarray(sorted(class_ids), np.int64),
        average,
        list(_AREA_RANGES),
        tuple(_AREA_RANGES[a] for a in _AREA_RANGES),
        device,
    )


def coco_evaluate_packed(
    det: Tuple[np.ndarray, ...],
    gt: Tuple[np.ndarray, ...],
    num_imgs: int,
    iou_thrs: Tuple[float, ...],
    rec_thrs: Tuple[float, ...],
    max_dets: Tuple[int, ...],
    class_arr: np.ndarray,
    average: str,
    area_names: List[str],
    area_ranges: Tuple[Tuple[float, float], ...],
    device: Any,
) -> Optional[Dict[str, np.ndarray]]:
    """Evaluate packed flat rows (the device-resident state layout) through
    the jitted program; ``None`` over budget (caller falls back)."""
    import jax
    from jax.experimental import enable_x64

    d_box, d_score, d_label, d_img = det
    g_box, g_label, g_crowd, g_area, g_img = gt
    eval_class_ids: Sequence[int] = [0] if average == "micro" else class_arr.tolist()
    k = len(eval_class_ids)

    # class slot per row (micro pools everything into slot 0)
    if average == "micro":
        d_slot = np.zeros(d_label.shape[0], np.int64)
        g_slot = np.zeros(g_label.shape[0], np.int64)
    else:
        d_slot = np.searchsorted(class_arr, d_label)
        g_slot = np.searchsorted(class_arr, g_label)

    if d_score.size and not np.isfinite(d_score).all():
        return None  # -inf is the pad sentinel and NaN breaks stable sorts

    kp = _pow2_at_least(k)
    ip = _pow2_at_least(num_imgs)
    # score-descending, stable in stored order — the numpy path's per-image
    # ``argsort(-scores, kind="stable")`` composed with its stable class
    # selection; within-cell relative order is identical by the stable-sort
    # subset property
    d_order = np.argsort(-d_score, kind="mergesort")

    # detection slots: cap at the top max-det threshold like order[:max_det]
    cell_d = d_slot * ip + d_img
    counts_d = np.bincount(cell_d, minlength=kp * ip) if d_img.size else np.zeros(kp * ip, np.int64)
    capped = int(min(counts_d.max() if d_img.size else 0, max_dets[-1]))
    dp = _pow2_at_least(max(capped, 1))
    counts_g = (
        np.bincount(g_slot * ip + g_img, minlength=kp * ip) if g_img.size else np.zeros(kp * ip, np.int64)
    )
    gp = _pow2_at_least(max(int(counts_g.max() if g_img.size else 0), 1))
    if kp * ip * len(area_ranges) * len(iou_thrs) * dp * gp > MATCH_BUDGET:
        return None
    # post-sort column budget: the worst class holds at most this many real
    # (per-cell max-det-capped) detection columns, so the accumulation can
    # statically drop the -inf pad tail beyond it (see _build_program)
    capped_counts = np.minimum(counts_d, max_dets[-1]).reshape(kp, ip)
    per_class_cols = int(capped_counts.sum(axis=1).max()) if counts_d.size else 0
    c2s = _pow2_at_least(max(per_class_cols, 1))
    c2s = min(c2s, ip * dp)

    dense_dbox, d_valid, (dense_score,), _ = _dense_cells(
        d_box, d_img, d_slot, kp, ip, dp, max_dets[-1], extra=[d_score], order=d_order
    )
    dense_gbox, g_valid, (dense_crowd, dense_garea), _ = _dense_cells(
        g_box, g_img, g_slot, kp, ip, gp, None,
        extra=[g_crowd.astype(bool), np.asarray(g_area, np.float64)],
    )

    # loop-trip bucketing: exact would recompile per distinct max cell count,
    # so round up to the next multiple of 4 (<= 4 variants per dp edge)
    d_trip = min(dp, 4 * ((max(capped, 1) + 3) // 4))
    key = (kp, ip, dp, gp, c2s, d_trip, iou_thrs, rec_thrs, max_dets, area_ranges)
    program = _PROGRAMS.get(key)
    with enable_x64():
        if program is None:
            program = _build_program(
                kp, ip, dp, gp, c2s, d_trip, iou_thrs, rec_thrs, max_dets, area_ranges
            )
            _PROGRAMS[key] = program
        args = jax.device_put(
            (dense_dbox, dense_score.astype(np.float32), d_valid,
             dense_gbox, dense_crowd, dense_garea, g_valid),
            device,
        )
        precision_d, recall_d, npig_d = jax.device_get(program(*args))
    # register the program in the SHARED device-profile registry (only the
    # abstract input specs are retained — holding the concrete args would
    # pin the dense device grids, potentially MATCH_BUDGET-scale, in memory
    # for the rest of the process); the cost/memory analysis resolves
    # lazily on the reader's thread (bench MFU, stats()["device"])
    from tpumetrics.telemetry import device as _device

    _device.register_program(MATCHER_PROFILE_LABEL, program, args, x64=True)

    # ---- host assembly into the COCO (T, R, K, A, M) / (T, K, A, M) layout
    num_thrs, num_rec, num_areas, n_m = len(iou_thrs), len(rec_thrs), len(area_names), len(max_dets)
    precision = -np.ones((num_thrs, num_rec, k, num_areas, n_m))
    recall = -np.ones((num_thrs, k, num_areas, n_m))
    live = npig_d[:k] > 0  # (k, A): cells with no countable gts stay -1
    for k_idx in range(k):
        for a_idx in range(num_areas):
            if not live[k_idx, a_idx]:
                continue
            # precision_d[k, a] is (T, M, R) -> (T, R, M)
            precision[:, :, k_idx, a_idx, :] = np.transpose(
                precision_d[k_idx, a_idx], (0, 2, 1)
            )
            recall[:, k_idx, a_idx, :] = recall_d[k_idx, a_idx]
    return _summarize(
        precision, recall, np.asarray(iou_thrs), class_arr.tolist(), eval_class_ids,
        area_names, list(max_dets), {}, False,
    )
