"""PanopticQuality / ModifiedPanopticQuality (counterpart of reference
``detection/panoptic_qualities.py``)."""

from __future__ import annotations

from typing import Any, Collection

import jax
import jax.numpy as jnp

from tpumetrics.functional.detection._panoptic_quality_common import (
    _get_category_id_to_continuous_id,
    _get_void_color,
    _panoptic_quality_compute,
    _panoptic_quality_update,
    _parse_categories,
    _prepocess_inputs,
    _validate_inputs,
)
from tpumetrics.metric import Metric

Array = jax.Array


class PanopticQuality(Metric):
    """Panoptic Quality accumulated over batches: four per-category sum
    states (iou_sum, TP, FP, FN) — one psum each on sync.

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.detection import PanopticQuality
        >>> preds = jnp.asarray([[[[6, 0], [0, 0], [6, 0], [6, 0]],
        ...                       [[0, 0], [0, 0], [6, 0], [0, 1]],
        ...                       [[0, 0], [0, 0], [6, 0], [0, 1]],
        ...                       [[0, 0], [7, 0], [6, 0], [1, 0]],
        ...                       [[0, 0], [7, 0], [7, 0], [7, 0]]]])
        >>> target = jnp.asarray([[[[6, 0], [0, 1], [6, 0], [0, 1]],
        ...                        [[0, 1], [0, 1], [6, 0], [0, 1]],
        ...                        [[0, 1], [0, 1], [6, 0], [1, 0]],
        ...                        [[0, 1], [7, 0], [1, 0], [1, 0]],
        ...                        [[0, 1], [7, 0], [7, 0], [7, 0]]]])
        >>> metric = PanopticQuality(things={0, 1}, stuffs={6, 7})
        >>> metric.update(preds, target)
        >>> round(float(metric.compute()), 4)
        0.5463
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    _modified_variant: bool = False

    def __init__(
        self,
        things: Collection[int],
        stuffs: Collection[int],
        allow_unknown_preds_category: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        things_set, stuffs_set = _parse_categories(things, stuffs)
        self.things = things_set
        self.stuffs = stuffs_set
        self.void_color = _get_void_color(things_set, stuffs_set)
        self.cat_id_to_continuous_id = _get_category_id_to_continuous_id(things_set, stuffs_set)
        self.allow_unknown_preds_category = allow_unknown_preds_category

        num_categories = len(things_set) + len(stuffs_set)
        self.add_state("iou_sum", default=jnp.zeros(num_categories), dist_reduce_fx="sum")
        self.add_state("true_positives", default=jnp.zeros(num_categories), dist_reduce_fx="sum")
        self.add_state("false_positives", default=jnp.zeros(num_categories), dist_reduce_fx="sum")
        self.add_state("false_negatives", default=jnp.zeros(num_categories), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Match segments of one batch (reference detection/panoptic_qualities.py update)."""
        _validate_inputs(preds, target)
        flatten_preds = _prepocess_inputs(
            self.things, self.stuffs, preds, self.void_color, self.allow_unknown_preds_category
        )
        flatten_target = _prepocess_inputs(self.things, self.stuffs, target, self.void_color, True)
        iou_sum, true_positives, false_positives, false_negatives = _panoptic_quality_update(
            flatten_preds,
            flatten_target,
            self.cat_id_to_continuous_id,
            self.void_color,
            modified_metric_stuffs=self.stuffs if self._modified_variant else None,
        )
        self.iou_sum = self.iou_sum + iou_sum
        self.true_positives = self.true_positives + true_positives
        self.false_positives = self.false_positives + false_positives
        self.false_negatives = self.false_negatives + false_negatives

    def compute(self) -> Array:
        return _panoptic_quality_compute(
            self.iou_sum, self.true_positives, self.false_positives, self.false_negatives
        )


class ModifiedPanopticQuality(PanopticQuality):
    """Modified PQ (Porzi et al. 2019): stuff classes score IoU / #segments
    (reference detection/panoptic_qualities.py ModifiedPanopticQuality).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.detection import ModifiedPanopticQuality
        >>> preds = jnp.asarray([[[0, 0], [0, 1], [6, 0], [7, 0], [0, 2], [1, 0]]])
        >>> target = jnp.asarray([[[0, 1], [0, 0], [6, 0], [7, 0], [6, 0], [255, 0]]])
        >>> metric = ModifiedPanopticQuality(things={0, 1}, stuffs={6, 7})
        >>> metric.update(preds, target)
        >>> round(float(metric.compute()), 4)
        0.7667
    """

    _modified_variant: bool = True
