"""MeanAveragePrecision (counterpart of reference ``detection/mean_ap.py:76``).

The reference keeps 9 ragged list states and shells out to pycocotools on
CPU at compute (reference mean_ap.py:50-71, :501). Here:

- states are per-image ragged lists (reduce ``None``), merged across
  replicas with per-image boundaries preserved
  (:func:`tpumetrics.parallel.merge.merge_metric_states`);
- compute runs the from-scratch vectorized numpy COCO protocol in
  :mod:`tpumetrics.detection._coco_eval` — no external backend needed.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from tpumetrics.detection._coco_eval import coco_evaluate
from tpumetrics.detection.helpers import _fix_empty_tensors, _input_validator
from tpumetrics.functional.detection._box_ops import box_convert
from tpumetrics.metric import Metric

Array = jax.Array


class MeanAveragePrecision(Metric):
    """Mean Average Precision / Recall for object detection (COCO protocol).

    Inputs follow the reference's list-of-dicts format: per image,
    ``preds`` = {"boxes" (D, 4), "scores" (D,), "labels" (D,)} and
    ``target`` = {"boxes" (G, 4), "labels" (G,)} with optional ``iscrowd``
    and ``area`` keys.

    Args:
        box_format: ``xyxy``/``xywh``/``cxcywh`` input box format.
        iou_type: only ``bbox`` is supported (``segm`` requires mask inputs).
        iou_thresholds: IoU thresholds; defaults to COCO's 0.50:0.05:0.95.
        rec_thresholds: recall thresholds; defaults to COCO's 0:0.01:1.
        max_detection_thresholds: per-image detection caps (default 1/10/100).
        class_metrics: include per-class map/mar in the output.
        average: ``macro`` (COCO standard) or ``micro`` (classes pooled).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.detection import MeanAveragePrecision
        >>> preds = [dict(boxes=jnp.asarray([[258.0, 41.0, 606.0, 285.0]]),
        ...               scores=jnp.asarray([0.536]), labels=jnp.asarray([0]))]
        >>> target = [dict(boxes=jnp.asarray([[214.0, 41.0, 562.0, 285.0]]),
        ...                labels=jnp.asarray([0]))]
        >>> metric = MeanAveragePrecision()
        >>> metric.update(preds, target)
        >>> result = metric.compute()
        >>> round(float(result["map"]), 4)
        0.6
        >>> round(float(result["map_50"]), 4)
        1.0
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = True
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    detection_boxes: List[Array]
    detection_scores: List[Array]
    detection_labels: List[Array]
    groundtruth_boxes: List[Array]
    groundtruth_labels: List[Array]
    groundtruth_crowds: List[Array]
    groundtruth_area: List[Array]

    def __init__(
        self,
        box_format: str = "xyxy",
        iou_type: str = "bbox",
        iou_thresholds: Optional[List[float]] = None,
        rec_thresholds: Optional[List[float]] = None,
        max_detection_thresholds: Optional[List[int]] = None,
        class_metrics: bool = False,
        average: str = "macro",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)

        allowed_box_formats = ("xyxy", "xywh", "cxcywh")
        if box_format not in allowed_box_formats:
            raise ValueError(f"Expected argument `box_format` to be one of {allowed_box_formats} but got {box_format}")
        self.box_format = box_format
        if iou_type != "bbox":
            raise ValueError(f"Expected argument `iou_type` to be `bbox` but got {iou_type}")
        self.iou_type = iou_type

        if iou_thresholds is not None and not isinstance(iou_thresholds, list):
            raise ValueError(
                f"Expected argument `iou_thresholds` to either be `None` or a list of floats but got {iou_thresholds}"
            )
        self.iou_thresholds = iou_thresholds or np.linspace(0.5, 0.95, 10).tolist()

        if rec_thresholds is not None and not isinstance(rec_thresholds, list):
            raise ValueError(
                f"Expected argument `rec_thresholds` to either be `None` or a list of floats but got {rec_thresholds}"
            )
        self.rec_thresholds = rec_thresholds or np.linspace(0.0, 1.0, 101).tolist()

        if max_detection_thresholds is not None and not isinstance(max_detection_thresholds, list):
            raise ValueError(
                f"Expected argument `max_detection_thresholds` to either be `None` or a list of ints"
                f" but got {max_detection_thresholds}"
            )
        self.max_detection_thresholds = sorted(max_detection_thresholds or [1, 10, 100])

        if not isinstance(class_metrics, bool):
            raise ValueError("Expected argument `class_metrics` to be a boolean")
        self.class_metrics = class_metrics
        if average not in ("macro", "micro"):
            raise ValueError(f"Expected argument `average` to be one of ('macro', 'micro') but got {average}")
        self.average = average

        self.add_state("detection_boxes", default=[], dist_reduce_fx=None)
        self.add_state("detection_scores", default=[], dist_reduce_fx=None)
        self.add_state("detection_labels", default=[], dist_reduce_fx=None)
        self.add_state("groundtruth_boxes", default=[], dist_reduce_fx=None)
        self.add_state("groundtruth_labels", default=[], dist_reduce_fx=None)
        self.add_state("groundtruth_crowds", default=[], dist_reduce_fx=None)
        self.add_state("groundtruth_area", default=[], dist_reduce_fx=None)

    def update(self, preds: Sequence[Dict[str, Array]], target: Sequence[Dict[str, Array]]) -> None:
        """Append one batch of per-image detections and ground truths
        (reference mean_ap.py:366-400)."""
        _input_validator(preds, target, iou_type=self.iou_type)

        for item in preds:
            boxes = self._convert_boxes(item["boxes"])
            self.detection_boxes.append(boxes)
            self.detection_scores.append(jnp.asarray(item["scores"], jnp.float32).ravel())
            self.detection_labels.append(jnp.asarray(item["labels"], jnp.int32).ravel())

        for item in target:
            boxes = self._convert_boxes(item["boxes"])
            n = boxes.shape[0]
            self.groundtruth_boxes.append(boxes)
            self.groundtruth_labels.append(jnp.asarray(item["labels"], jnp.int32).ravel())
            crowds = item.get("iscrowd")
            self.groundtruth_crowds.append(
                jnp.asarray(crowds, jnp.int32).ravel() if crowds is not None else jnp.zeros((n,), jnp.int32)
            )
            area = item.get("area")
            self.groundtruth_area.append(
                jnp.asarray(area, jnp.float32).ravel() if area is not None else jnp.zeros((n,), jnp.float32)
            )

    def _convert_boxes(self, boxes: Array) -> Array:
        boxes = _fix_empty_tensors(jnp.asarray(boxes, jnp.float32))
        if boxes.size > 0:
            boxes = box_convert(boxes, in_fmt=self.box_format, out_fmt="xyxy")
        return boxes

    def compute(self) -> Dict[str, Array]:
        """Run the COCO protocol over the accumulated images.

        All per-image device arrays are fetched with one batched
        ``jax.device_get`` — serial ``np.asarray`` fetches pay the full
        device round-trip latency per array, which dwarfs the evaluation
        itself on remote-attached accelerators."""
        num_imgs = len(self.detection_boxes)
        host = jax.device_get(
            (
                list(self.detection_boxes),
                list(self.detection_scores),
                list(self.detection_labels),
                list(self.groundtruth_boxes),
                list(self.groundtruth_labels),
                list(self.groundtruth_crowds),
                list(self.groundtruth_area),
            )
        )
        det_boxes, det_scores, det_labels, gt_boxes, gt_labels, gt_crowds, gt_area = (
            [np.asarray(x) for x in group] for group in host
        )
        detections = [(det_boxes[i], det_scores[i], det_labels[i]) for i in range(num_imgs)]
        groundtruths = [
            (gt_boxes[i], gt_labels[i], gt_crowds[i], gt_area[i]) for i in range(num_imgs)
        ]
        all_labels = det_labels + gt_labels
        class_ids = (
            sorted(np.unique(np.concatenate(all_labels)).astype(int).tolist()) if all_labels else []
        )
        result = coco_evaluate(
            detections,
            groundtruths,
            self.iou_thresholds,
            self.rec_thresholds,
            self.max_detection_thresholds,
            class_ids,
            average=self.average,
        )

        max_det = self.max_detection_thresholds[-1]
        out: Dict[str, Array] = {}
        for key in (
            "map",
            "map_50",
            "map_75",
            "map_small",
            "map_medium",
            "map_large",
            "mar_small",
            "mar_medium",
            "mar_large",
            *(f"mar_{m}" for m in self.max_detection_thresholds),
        ):
            out[key] = jnp.asarray(result[key])
        if self.class_metrics:
            if self.average == "micro":
                # micro pools classes for the global scores, but per-class
                # values only make sense macro-style (reference mean_ap.py
                # recomputes them with average="macro"), keeping
                # map_per_class aligned with the observed `classes`
                per_class = coco_evaluate(
                    detections,
                    groundtruths,
                    self.iou_thresholds,
                    self.rec_thresholds,
                    self.max_detection_thresholds,
                    class_ids,
                    average="macro",
                )
            else:
                per_class = result
            out["map_per_class"] = jnp.asarray(per_class["map_per_class"])
            out[f"mar_{max_det}_per_class"] = jnp.asarray(per_class["mar_per_class"])
        else:
            out["map_per_class"] = jnp.asarray(-1.0)
            out[f"mar_{max_det}_per_class"] = jnp.asarray(-1.0)
        out["classes"] = jnp.asarray(result["classes"])
        return out
