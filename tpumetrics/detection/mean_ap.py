"""MeanAveragePrecision (counterpart of reference ``detection/mean_ap.py:76``).

The reference keeps 9 ragged list states and shells out to pycocotools on
CPU at compute (reference mean_ap.py:50-71, :501). Here:

- states are per-image ragged lists (reduce ``None``), merged across
  replicas with per-image boundaries preserved
  (:func:`tpumetrics.parallel.merge.merge_metric_states`);
- compute runs the from-scratch vectorized numpy COCO protocol in
  :mod:`tpumetrics.detection._coco_eval` — no external backend needed.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from tpumetrics.detection._coco_eval import coco_evaluate, precompute_geometries
from tpumetrics.detection._coco_eval_jax import coco_evaluate_jit
from tpumetrics.detection.helpers import _input_validator
from tpumetrics.metric import Metric
from tpumetrics.utils.exceptions import TPUMetricsUserError

Array = jax.Array

#: packed-row layouts (see the class docstring's "packed device-resident
#: state"): one f32 row per detection / ground truth, segment id last.
#: f32 carries class ids, crowd flags and image ids exactly below 2^24.
_DET_COLS = 7  # x1, y1, x2, y2, score, label, image id (-1 = pad sentinel)
_GT_COLS = 8  # x1, y1, x2, y2, label, iscrowd, area, image id


@jax.jit
def _pack_flat_f32(*pieces: Array) -> Array:
    """Ravel + cast + concatenate every piece in one compiled program: the
    single device dispatch (and single transfer, via the caller's
    ``np.asarray``) that ``compute`` pays regardless of how many images or
    updates accumulated.  f32 round-trips integer labels/crowds exactly
    (class ids and flags are far below 2^24).  Keyed by the pieces' shape
    signature; the persistent compilation cache amortizes recompiles across
    processes."""
    return jnp.concatenate([jnp.ravel(p).astype(jnp.float32) for p in pieces])


_PACK_CHUNK = 1024  # pieces per jitted pack call — bounds trace/compile size


@jax.jit
def _concat_flat(*flats: Array) -> Array:
    """Join per-chunk pack outputs on device (one cached dispatch)."""
    return jnp.concatenate(flats)


def _fetch_pieces(pieces: List[Array]) -> List[np.ndarray]:
    """Materialize a mixed host/device list of arrays on host with O(1)
    device round trips: device pieces go through :func:`_pack_flat_f32` (in
    chunks of ``_PACK_CHUNK`` so a huge corpus can't blow up one compile) +
    one ``np.asarray`` per chunk; host pieces pass through untouched.

    Cost model on a remote-attached accelerator: a jitted pack call with a
    known signature is ~2 ms; a NEW signature pays one remote compile
    (~0.8 s, amortized by the persistent compilation cache); every eager
    alternative pays per-piece dispatches, which is strictly worse at any
    corpus size."""
    dev_idx = [i for i, x in enumerate(pieces) if isinstance(x, jax.Array)]
    parts: List[np.ndarray] = []
    if dev_idx:
        dev = [pieces[i] for i in dev_idx]
        sizes = np.asarray([int(x.size) for x in dev])
        flats = [
            _pack_flat_f32(*dev[lo : lo + _PACK_CHUNK])
            for lo in range(0, len(dev), _PACK_CHUNK)
        ]
        # chunks stay on device and are concatenated there: the transfer cost
        # is per-ROUND-TRIP, not per-byte, so N chunk fetches (~75 ms each on
        # a remote-attached accelerator) collapse into one
        flat_dev = flats[0] if len(flats) == 1 else _concat_flat(*flats)
        flat = np.asarray(flat_dev)
        parts = np.split(flat, np.cumsum(sizes)[:-1])
    out: List[np.ndarray] = []
    j = 0
    for i, x in enumerate(pieces):
        if isinstance(x, jax.Array):
            out.append(parts[j].reshape(x.shape))
            j += 1
        else:
            out.append(np.asarray(x))
    return out


def _own(x):
    """Defensively copy host inputs stored by reference: a caller reusing one
    scratch numpy buffer across updates must not retroactively rewrite the
    accumulated state (device arrays are immutable — no copy needed)."""
    if isinstance(x, np.ndarray):
        return np.array(x)
    if isinstance(x, jax.Array):
        return x
    return np.asarray(x)


def _torch_f32_linspace(start: float, end: float, steps: int) -> List[float]:
    """The reference's default thresholds, bit-for-bit.

    ``torch.linspace`` in float32 (reference mean_ap.py:396,402) anchors the
    first half at ``start`` and the second half at ``end`` and evaluates
    ``base ± i*step`` with a fused multiply-add (one rounding).  The exact
    doubles matter: a recall of exactly 3/5 samples on the opposite side of
    recThr[60] depending on whether it is float32-0.6 (0.6000000238…) or a
    float64 0.6 — a whole precision column flips with it.  Emulated here with
    exact f64 intermediates (i ≤ 2²⁴, step a f32 value → products and sums
    are exact in f64) and a single final cast.
    """
    if steps == 1:
        return [float(np.float32(start))]
    step = np.float64(np.float32((np.float32(end) - np.float32(start)) / np.float32(steps - 1)))
    i = np.arange(steps, dtype=np.float64)
    lo = np.float64(np.float32(start)) + i * step
    hi = np.float64(np.float32(end)) - (steps - 1 - i) * step
    vals = np.where(np.arange(steps) < steps // 2, lo, hi).astype(np.float32).astype(np.float64)
    return vals.tolist()


def _fix_empty_boxes(boxes) -> np.ndarray:
    """Empty box inputs get a host (0, 4) shape so downstream shape math is
    well-defined (reference helpers.py:88-93) — no device op for the empty
    case, and non-empty arrays pass through untouched."""
    if getattr(boxes, "size", None) == 0 and getattr(boxes, "ndim", 2) != 2:
        return np.zeros((0, 4), np.float32)
    return boxes


_PACKED_MERGE_ERROR = (
    "Packed detection rows from distinct id spaces were merged: per-rank "
    "packed states collide.  Packed (dense) updates support a single logical "
    "stream — one process, or ONE global program on a GSPMD mesh; use the "
    "list-of-dicts layout for eager per-rank DDP."
)


def _check_packed_chunk_order(chunks: Sequence[np.ndarray]) -> None:
    """Across the fetched per-update chunks of ONE logical stream, image ids
    must STRICTLY increase at every chunk boundary (each update's ids start
    past everything before it).  A cat-merge of per-rank states restarts the
    id sequence — caught here even when a rank contributed a single image,
    which plain nondecreasing-over-the-flat-rows cannot distinguish from one
    image's contiguous rows."""
    last = -1
    for chunk in chunks:
        ids = np.rint(np.asarray(chunk).reshape(-1, chunk.shape[-1])[:, -1]).astype(np.int64)
        ids = ids[ids >= 0]
        if not ids.size:
            continue
        if int(ids[0]) <= last:
            raise TPUMetricsUserError(_PACKED_MERGE_ERROR)
        last = int(ids[-1])


def _filter_packed_rows(flat: np.ndarray, n_imgs: int, label_col: int) -> tuple:
    """Validate fetched packed rows and return ``(rows, ids)`` flat.

    Drops the eager path's ``-1`` pad-sentinel rows, validates that ids are
    nondecreasing (rows of one logical stream always are — a violation means
    per-rank packed states with colliding id spaces were concatenated, which
    only the single-program GSPMD path supports) and in range, and that
    class labels sit inside float32's exact-integer range (a larger label
    would have silently aliased in the f32 row column — fail loudly).
    """
    ids = np.rint(flat[:, -1]).astype(np.int64)
    rows = flat[ids >= 0]
    ids = ids[ids >= 0]
    if ids.size and np.any(np.diff(ids) < 0):
        raise TPUMetricsUserError(_PACKED_MERGE_ERROR)
    if ids.size and ids[-1] >= n_imgs:
        raise TPUMetricsUserError(
            f"Packed detection state is inconsistent: row image id {int(ids[-1])} "
            f">= recorded image count {n_imgs}."
        )
    if rows.shape[0] and float(np.abs(rows[:, label_col]).max()) > 2.0**24:
        raise TPUMetricsUserError(
            "Packed detection state holds class labels beyond the 2^24 "
            "exact-integer range of the float32 row columns — distinct classes "
            "may already have aliased.  Use smaller class ids (or the "
            "list-of-dicts layout)."
        )
    return rows, ids


def _split_packed_rows(flat: np.ndarray, n_imgs: int, label_col: int) -> tuple:
    """:func:`_filter_packed_rows`, then split into per-image arrays:
    ``(per_image_rows, per_image_counts)``."""
    rows, ids = _filter_packed_rows(flat, n_imgs, label_col)
    counts = np.bincount(ids, minlength=n_imgs).astype(np.int64)
    if n_imgs == 0:
        return [], counts
    return np.split(rows, np.cumsum(counts)[:-1]), counts


def _rle_encode_batch(masks: np.ndarray) -> tuple:
    """Column-major RLE encode an (N, H, W) boolean stack.

    Returns ``(flat_runs int32, nruns int32 (N,))`` — all masks' runs
    concatenated, plus the per-mask run count to split them back."""
    n = masks.shape[0]
    if n == 0:
        return np.zeros(0, np.int32), np.zeros(0, np.int32)
    flat_all = masks.transpose(0, 2, 1).reshape(n, -1)
    runs_list = []
    nruns = np.empty(n, np.int32)
    for i in range(n):
        f = flat_all[i]
        change = np.flatnonzero(f[1:] != f[:-1]) + 1
        starts = np.concatenate(([0], change, [f.size]))
        runs = np.diff(starts)
        if f.size and f[0]:
            runs = np.concatenate(([0], runs))
        runs_list.append(runs)
        nruns[i] = runs.shape[0]
    return np.concatenate(runs_list).astype(np.int32), nruns


class MeanAveragePrecision(Metric):
    """Mean Average Precision / Recall for object detection (COCO protocol).

    Inputs follow the reference's list-of-dicts format: per image,
    ``preds`` = {"boxes" (D, 4), "scores" (D,), "labels" (D,)} and
    ``target`` = {"boxes" (G, 4), "labels" (G,)} with optional ``iscrowd``
    and ``area`` keys.  With ``iou_type="segm"``, ``masks`` (N, H, W) boolean
    stacks replace ``boxes`` (reference mean_ap.py:430-438); masks are
    RLE-encoded at update and matched by mask IoU at compute.

    **Packed dense layout** (bbox only): each side of a batch may instead be
    ONE dict of ``(B, slots, ...)`` arrays plus a per-image ``count`` —
    built by :func:`tpumetrics.detection.pack_detection_batch` — with an
    optional ``valid`` image mask.  That update is a trace-safe fixed-shape
    append into packed row states (``det_rows``/``gt_rows`` + segment ids),
    runs under ``jit`` / ``FusedCollectionStep`` / the bucketed
    ``StreamingEvaluator`` / a GSPMD mesh with zero device→host transfers,
    and lands on bit-identical results (``docs/performance.md``,
    "Device-resident detection").

    Args:
        box_format: ``xyxy``/``xywh``/``cxcywh`` input box format.
        iou_type: ``bbox`` (box IoU), ``segm`` (instance-mask IoU), or a
            list/tuple of both — inputs then carry ``boxes`` AND ``masks``
            and every output key is prefixed ``bbox_``/``segm_``
            (reference mean_ap.py:390,508).
        iou_thresholds: IoU thresholds; defaults to COCO's 0.50:0.05:0.95.
        rec_thresholds: recall thresholds; defaults to COCO's 0:0.01:1.
        max_detection_thresholds: per-image detection caps (default 1/10/100).
        class_metrics: include per-class map/mar in the output.
        extended_summary: additionally return the per-(image, class) IoU
            matrices and the raw ``precision``/``recall`` tensors over
            (T, R, K, A, M) / (T, K, A, M) (reference mean_ap.py:525-536).
        average: ``macro`` (COCO standard) or ``micro`` (classes pooled).
        backend: accepted for drop-in compatibility (reference
            mean_ap.py:360); both values select the built-in vectorized
            engine, parity-tested against the reference's pycocotools path.
        det_capacity / gt_capacity: row capacities of the packed
            ``det_rows``/``gt_rows`` states on the functional/jit path
            (fixed-shape :class:`~tpumetrics.buffers.MaskedBuffer`\\ s;
            overflow raises at ``compute`` rather than truncating).  The
            eager OO path keeps unbounded lists and ignores these.

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.detection import MeanAveragePrecision
        >>> preds = [dict(boxes=jnp.asarray([[258.0, 41.0, 606.0, 285.0]]),
        ...               scores=jnp.asarray([0.536]), labels=jnp.asarray([0]))]
        >>> target = [dict(boxes=jnp.asarray([[214.0, 41.0, 562.0, 285.0]]),
        ...                labels=jnp.asarray([0]))]
        >>> metric = MeanAveragePrecision()
        >>> metric.update(preds, target)
        >>> result = metric.compute()
        >>> round(float(result["map"]), 4)
        0.6
        >>> round(float(result["map_50"]), 4)
        1.0
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = True
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    detection_boxes: List[Array]
    detection_scores: List[Array]
    detection_labels: List[Array]
    detection_counts: List[Array]
    groundtruth_boxes: List[Array]
    groundtruth_labels: List[Array]
    groundtruth_crowds: List[Array]
    groundtruth_area: List[Array]
    groundtruth_counts: List[Array]
    # segm-only ragged mask state (column-major RLE runs, flattened with
    # per-mask run counts — same counts-array pattern as the box states, so
    # the generic device-array merge syncs masks too; the reference instead
    # needs a custom object-gather for its RLE tuples, ref mean_ap.py:994-1024)
    detection_mask_runs: List[Array]
    detection_mask_nruns: List[Array]
    groundtruth_mask_runs: List[Array]
    groundtruth_mask_nruns: List[Array]
    mask_sizes: List[Array]
    # packed device-resident state (bbox only): flat row buffers + segment
    # ids instead of per-image host lists.  ``det_rows`` is (N, 7) f32 —
    # box xyxy (raw input format), score, label, image id — and ``gt_rows``
    # (N, 8) adds crowd/area columns; ``packed_imgs`` counts the images the
    # packed rows describe.  The segment id rides as the LAST COLUMN of the
    # same buffer (not a sibling array) so merge / elastic fold / reshard /
    # overflow can never de-align rows from their ids.  Registered through
    # the buffer-state machinery: "cat" reduce semantics, a declared
    # capacity (MaskedBuffer on the functional/jit path), P(dp) partition
    # rules from StatePartitionRules.for_metric, and snapshot specs — all
    # for free.  On the eager OO path the states stay Python lists and pad
    # rows carry image id -1 (dropped at compute), so eager dense updates
    # are exactly as host-sync-free as the traced ones.
    det_rows: List[Array]
    gt_rows: List[Array]
    packed_imgs: Array

    def __init__(
        self,
        box_format: str = "xyxy",
        iou_type: str = "bbox",
        iou_thresholds: Optional[List[float]] = None,
        rec_thresholds: Optional[List[float]] = None,
        max_detection_thresholds: Optional[List[int]] = None,
        class_metrics: bool = False,
        extended_summary: bool = False,
        average: str = "macro",
        backend: str = "pycocotools",
        det_capacity: int = 8192,
        gt_capacity: int = 8192,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)

        allowed_box_formats = ("xyxy", "xywh", "cxcywh")
        if box_format not in allowed_box_formats:
            raise ValueError(f"Expected argument `box_format` to be one of {allowed_box_formats} but got {box_format}")
        self.box_format = box_format
        if isinstance(iou_type, str):
            iou_types = (iou_type,)
        else:
            iou_types = tuple(iou_type)
        if (
            not iou_types
            or any(t not in ("bbox", "segm") for t in iou_types)
            or len(set(iou_types)) != len(iou_types)
        ):
            raise ValueError(
                f"Expected argument `iou_type` to be one of ('bbox', 'segm') or a list of distinct"
                f" entries, but got {iou_type}"
            )
        # single-type callers read the plain string (and our internal
        # branches key off membership); the reference normalizes to a tuple
        # the same way (reference helpers.py _validate_iou_type_arg)
        self.iou_type = iou_types[0] if len(iou_types) == 1 else iou_types
        self._iou_types = iou_types

        if iou_thresholds is not None and not isinstance(iou_thresholds, list):
            raise ValueError(
                f"Expected argument `iou_thresholds` to either be `None` or a list of floats but got {iou_thresholds}"
            )
        self.iou_thresholds = iou_thresholds or _torch_f32_linspace(0.5, 0.95, 10)

        if rec_thresholds is not None and not isinstance(rec_thresholds, list):
            raise ValueError(
                f"Expected argument `rec_thresholds` to either be `None` or a list of floats but got {rec_thresholds}"
            )
        self.rec_thresholds = rec_thresholds or _torch_f32_linspace(0.0, 1.0, 101)

        if max_detection_thresholds is not None and not isinstance(max_detection_thresholds, list):
            raise ValueError(
                f"Expected argument `max_detection_thresholds` to either be `None` or a list of ints"
                f" but got {max_detection_thresholds}"
            )
        self.max_detection_thresholds = sorted(max_detection_thresholds or [1, 10, 100])

        if not isinstance(class_metrics, bool):
            raise ValueError("Expected argument `class_metrics` to be a boolean")
        self.class_metrics = class_metrics
        if not isinstance(extended_summary, bool):
            raise ValueError("Expected argument `extended_summary` to be a boolean")
        self.extended_summary = extended_summary
        if average not in ("macro", "micro"):
            raise ValueError(f"Expected argument `average` to be one of ('macro', 'micro') but got {average}")
        self.average = average
        if backend not in ("pycocotools", "faster_coco_eval"):
            raise ValueError(
                f"Expected argument `backend` to be one of ('pycocotools', 'faster_coco_eval') but got {backend}"
            )
        # accepted for drop-in compatibility: both reference backends map to
        # the one built-in vectorized engine here, which is parity-tested
        # against the reference's primary (pycocotools) path
        self.backend = backend

        if not (isinstance(det_capacity, int) and isinstance(gt_capacity, int)) or min(det_capacity, gt_capacity) < 1:
            raise ValueError(
                f"Expected `det_capacity`/`gt_capacity` to be positive ints, got {det_capacity}/{gt_capacity}"
            )

        self.add_state("detection_scores", default=[], dist_reduce_fx=None)
        self.add_state("detection_labels", default=[], dist_reduce_fx=None)
        self.add_state("detection_counts", default=[], dist_reduce_fx=None)
        self.add_state("groundtruth_labels", default=[], dist_reduce_fx=None)
        self.add_state("groundtruth_crowds", default=[], dist_reduce_fx=None)
        self.add_state("groundtruth_area", default=[], dist_reduce_fx=None)
        self.add_state("groundtruth_counts", default=[], dist_reduce_fx=None)
        if "bbox" in iou_types:
            self.add_state("detection_boxes", default=[], dist_reduce_fx=None)
            self.add_state("groundtruth_boxes", default=[], dist_reduce_fx=None)
            # packed device-resident states (class docstring): the declared
            # capacity only binds the functional/jit path, where the state
            # becomes a fixed-capacity MaskedBuffer (overflow raises at
            # compute); the eager path keeps unbounded Python lists
            self.add_state(
                "det_rows", default=[], dist_reduce_fx="cat",
                capacity=det_capacity, feature_shape=(_DET_COLS,), feature_dtype=jnp.float32,
            )
            self.add_state(
                "gt_rows", default=[], dist_reduce_fx="cat",
                capacity=gt_capacity, feature_shape=(_GT_COLS,), feature_dtype=jnp.float32,
            )
            self.add_state("packed_imgs", default=jnp.zeros((), jnp.int32), dist_reduce_fx="sum")
        if "segm" in iou_types:
            self.add_state("detection_mask_runs", default=[], dist_reduce_fx=None)
            self.add_state("detection_mask_nruns", default=[], dist_reduce_fx=None)
            self.add_state("groundtruth_mask_runs", default=[], dist_reduce_fx=None)
            self.add_state("groundtruth_mask_nruns", default=[], dist_reduce_fx=None)
            self.add_state("mask_sizes", default=[], dist_reduce_fx=None)

    def update(
        self,
        preds: Union[Sequence[Dict[str, Array]], Dict[str, Array]],
        target: Union[Sequence[Dict[str, Array]], Dict[str, Array]],
        valid: Optional[Array] = None,
    ) -> None:
        """Append one batch of detections and ground truths.

        Two input layouts:

        - **list-of-dicts** (the reference format, mean_ap.py:366-400): one
          dict per image with ragged arrays.  ZERO device operations happen
          here: per-image arrays are stored as-is (device or host),
          per-image boundaries as host int arrays, and missing
          ``iscrowd``/``area`` as host zero placeholders.
        - **packed dense dicts** (``preds``/``target`` each ONE dict of
          ``(B, slots, ...)`` arrays — :func:`tpumetrics.detection.packing.
          pack_detection_batch` builds them): a trace-safe fixed-shape
          append into the packed row states.  ``valid`` masks padded
          images (the :mod:`tpumetrics.runtime.bucketing` convention), so
          this path runs under ``jit`` / ``FusedCollectionStep`` / the
          bucketed ``StreamingEvaluator`` / a GSPMD mesh with zero
          device→host transfers — the paper's no-host-sync-until-compute
          contract for the detection family.

        All device work is deferred to ``compute``, which packs every
        device-resident piece into ONE jitted concatenation and pays ONE
        transfer — on a remote-attached accelerator each eager dispatch or
        fetch is a full network round trip, so per-update device math (the
        reference does O(images) tensor ops per update) is the dominant
        cost, not the protocol itself."""
        if isinstance(preds, dict) or isinstance(target, dict):
            self._update_packed(preds, target, valid)
            return
        if valid is not None:
            raise TPUMetricsUserError(
                "`valid` only applies to packed (dict) detection batches; the "
                "list-of-dicts layout is always fully valid."
            )
        from tpumetrics.utils.data import _is_tracer

        if any(_is_tracer(v) for p in preds for v in p.values()) or any(
            _is_tracer(v) for t in target for v in t.values()
        ):
            raise TPUMetricsUserError(
                "The list-of-dicts detection layout cannot run under jit / the "
                "bucketed runtime (per-image arrays are ragged).  Pack the batch "
                "into the dense dict layout first — "
                "tpumetrics.detection.pack_detection_batch(preds, target) — and "
                "submit the two dicts."
            )
        _input_validator(preds, target, iou_type=self.iou_type)
        if not preds:
            return

        # ALL validation happens before the first state append (the invariant
        # _append_masks documents): a raising update must leave no
        # half-appended state behind, or every later compute is misaligned
        if "bbox" in self._iou_types:
            dboxes = [_own(_fix_empty_boxes(p["boxes"])) for p in preds]
            dcounts = [int(b.shape[0]) for b in dboxes]
            gboxes = [_own(_fix_empty_boxes(t["boxes"])) for t in target]
            gcounts = [int(b.shape[0]) for b in gboxes]
        else:
            dcounts = [int(p["masks"].shape[0]) for p in preds]
            gcounts = [int(t["masks"].shape[0]) for t in target]
        if "segm" in self._iou_types:
            if "bbox" in self._iou_types:
                for i, (p, t, nd, ng) in enumerate(zip(preds, target, dcounts, gcounts)):
                    if int(p["masks"].shape[0]) != nd:
                        raise ValueError(
                            f"Sample {i}: prediction `boxes` and `masks` must describe the same"
                            f" detections, got {nd} boxes vs {int(p['masks'].shape[0])} masks"
                        )
                    if int(t["masks"].shape[0]) != ng:
                        raise ValueError(
                            f"Sample {i}: target `boxes` and `masks` must describe the same"
                            f" ground truths, got {ng} boxes vs {int(t['masks'].shape[0])} masks"
                        )
            self._append_masks(preds, target)

        if "bbox" in self._iou_types:
            self.detection_boxes.extend(dboxes)
            self.groundtruth_boxes.extend(gboxes)
        self.detection_scores.extend(_own(p["scores"]) for p in preds)
        self.detection_labels.extend(_own(p["labels"]) for p in preds)
        self.detection_counts.append(np.asarray(dcounts, np.int64))
        self.groundtruth_labels.extend(_own(t["labels"]) for t in target)
        self.groundtruth_crowds.extend(
            _own(t["iscrowd"]) if t.get("iscrowd") is not None else np.zeros(n, np.int64)
            for t, n in zip(target, gcounts)
        )
        self.groundtruth_area.extend(
            _own(t["area"]) if t.get("area") is not None else np.zeros(n, np.float32)
            for t, n in zip(target, gcounts)
        )
        self.groundtruth_counts.append(np.asarray(gcounts, np.int64))

    # ------------------------------------------------- packed (device) path

    @staticmethod
    def _check_packed_shapes(side: str, d: Dict[str, Array], keys: tuple) -> tuple:
        """Static (metadata-only, trace-safe) validation of one dense dict;
        returns ``(B, slots)``."""
        for key in keys:
            if key not in d:
                raise ValueError(f"Packed {side} dict is missing the `{key}` key")
        boxes = d["boxes"]
        if getattr(boxes, "ndim", 0) != 3 or boxes.shape[-1] != 4:
            raise ValueError(
                f"Packed {side} `boxes` must have shape (B, slots, 4), got {jnp.shape(boxes)}"
            )
        b, slots = boxes.shape[0], boxes.shape[1]
        for key in d:
            if key == "boxes":
                continue
            shape = tuple(jnp.shape(d[key]))
            want = (b,) if key == "count" else (b, slots)
            if shape != want:
                raise ValueError(
                    f"Packed {side} `{key}` must have shape {want}, got {shape}"
                )
        return b, slots

    def _update_packed(
        self, preds: Dict[str, Array], target: Dict[str, Array], valid: Optional[Array]
    ) -> None:
        """One fixed-shape append of a packed dense batch (class docstring).

        Every operation here is shape-metadata checks plus traced ``jnp``
        math — no data-dependent Python branch and no device→host transfer —
        so the same code path serves the eager OO metric, ``jit`` via
        ``functional_update``, the bucketed masked update, and a GSPMD mesh.
        """
        if not (isinstance(preds, dict) and isinstance(target, dict)):
            raise ValueError(
                "Packed detection updates need BOTH `preds` and `target` as dense dicts"
            )
        if self._iou_types != ("bbox",):
            raise TPUMetricsUserError(
                "Packed detection updates support iou_type='bbox' only; the RLE "
                "segm path needs host mask decode — use the list-of-dicts layout."
            )
        b, d_slots = self._check_packed_shapes("preds", preds, ("boxes", "scores", "labels"))
        bt, g_slots = self._check_packed_shapes("target", target, ("boxes", "labels"))
        if bt != b:  # tpulint: disable=TPL102 -- b/bt are Python ints read off .shape metadata (static at trace time), never traced values
            raise ValueError(f"Packed preds describe {b} images but target {bt}")

        if valid is None:
            valid_b = jnp.ones((b,), bool)
        else:
            valid_b = jnp.asarray(valid).astype(bool).reshape((b,))
        vi = valid_b.astype(jnp.int32)
        base = jnp.asarray(self.packed_imgs, jnp.int32)
        # compacted image ids: the j-th VALID image of this batch gets
        # base + j, so ids stay dense however the bucketer padded the batch
        ids = base + jnp.cumsum(vi) - 1  # (B,) int32

        def rows_for(d: Dict[str, Array], slots: int, is_det: bool):
            count = d.get("count")
            if count is None:
                count = jnp.full((b,), slots, jnp.int32)
            if isinstance(count, (np.ndarray, list, tuple)):
                # host counts (the pack_detection_batch output): a count past
                # the slot budget would mark zero-filled pad slots as real
                # detections — fail loudly while the value is host-readable
                host_max = int(np.max(count)) if np.size(count) else 0
                if host_max > slots:
                    raise ValueError(
                        f"Packed `count` claims {host_max} rows but the dict has "
                        f"only {slots} slots per image"
                    )
            # device/traced counts can't be value-checked without a host sync
            # (this path must stay transfer-free); clamping keeps the row mask
            # inside the slot budget either way
            count = jnp.minimum(jnp.asarray(count), slots)
            rvalid = (jnp.arange(slots)[None, :] < count[:, None]) & valid_b[:, None]
            img_col = jnp.where(rvalid, ids[:, None].astype(jnp.float32), -1.0)
            cols = [
                jnp.reshape(d["boxes"], (b * slots, 4)).astype(jnp.float32),
                jnp.reshape(d["scores"] if is_det else d["labels"], (b * slots, 1)).astype(jnp.float32),
            ]
            if is_det:
                cols.append(jnp.reshape(d["labels"], (b * slots, 1)).astype(jnp.float32))
            else:
                crowds = d.get("iscrowd")
                areas = d.get("area")
                cols.append(
                    jnp.reshape(
                        jnp.zeros((b, slots), jnp.float32) if crowds is None else crowds,
                        (b * slots, 1),
                    ).astype(jnp.float32)
                )
                cols.append(
                    jnp.reshape(
                        jnp.zeros((b, slots), jnp.float32) if areas is None else areas,
                        (b * slots, 1),
                    ).astype(jnp.float32)
                )
            cols.append(jnp.reshape(img_col, (b * slots, 1)))
            return jnp.concatenate(cols, axis=1), jnp.reshape(rvalid, (b * slots,))

        det_rows, det_valid = rows_for(preds, d_slots, is_det=True)
        gt_rows, gt_valid = rows_for(target, g_slots, is_det=False)
        self._append_packed("det_rows", det_rows, det_valid)
        self._append_packed("gt_rows", gt_rows, gt_valid)
        self.packed_imgs = base + jnp.sum(vi)

    def _append_packed(self, name: str, rows: Array, mask: Array) -> None:
        """Append packed rows: masked-compacted into the MaskedBuffer on the
        functional/jit path; appended whole on the eager list path, where the
        ``-1`` image-id sentinel already marks pad rows (a boolean compaction
        would force a device→host sync, which this path must never do)."""
        from tpumetrics.buffers import _BufferList

        val = getattr(self, name)
        if isinstance(val, _BufferList):
            val.append(rows, valid=mask)
        else:
            val.append(rows)

    @staticmethod
    def coco_to_tm(
        coco_preds: str,
        coco_target: str,
        iou_type: str = "bbox",
        backend: str = "pycocotools",
    ):
        """Convert COCO-format json files into this metric's input format
        (reference mean_ap.py:612-719, without needing pycocotools: the files
        are plain json).  Boxes come back in COCO's xywh layout — construct
        the metric with ``box_format="xywh"`` — and segm masks must be
        uncompressed-RLE dicts (compressed-string counts / polygons need the
        real pycocotools toolchain).  ``backend`` matches the reference
        signature (mean_ap.py:628-633, 'pycocotools'|'faster_coco_eval') and
        is accepted-and-ignored like the constructor's: the built-in json
        reader serves both.

        Returns:
            ``(preds, target)`` lists of per-image dicts of jnp arrays.
        """
        import json

        if iou_type not in ("bbox", "segm"):
            raise ValueError(f"Expected argument `iou_type` to be bbox or segm, got {iou_type}")
        if backend not in ("pycocotools", "faster_coco_eval"):
            raise ValueError(
                f"Expected argument `backend` to be `pycocotools` or `faster_coco_eval`, got {backend}"
            )
        with open(coco_target) as fh:
            gt_data = json.load(fh)
        with open(coco_preds) as fh:
            dt_anns = json.load(fh)
        if isinstance(dt_anns, dict):
            dt_anns = dt_anns.get("annotations", [])
        gt_anns = gt_data.get("annotations") if isinstance(gt_data, dict) else gt_data
        if not isinstance(gt_anns, list):
            raise ValueError(
                "coco_target must be a COCO dataset dict with an `annotations` list or a bare"
                " annotation list"
            )
        # one entry per image for BOTH sides, in one shared order: gt images
        # without detections (and vice versa) get empty entries, exactly like
        # the reference's backfill (reference mean_ap.py:700-718) — without
        # it, positional update() pairing silently misaligns images
        image_ids = sorted(
            {img["id"] for img in (gt_data.get("images", []) if isinstance(gt_data, dict) else [])}
            | {a["image_id"] for a in gt_anns}
            | {a["image_id"] for a in dt_anns}
        )

        def decode_mask(ann):
            seg = ann.get("segmentation")
            if not (isinstance(seg, dict) and isinstance(seg.get("counts"), (list, tuple))):
                raise NotImplementedError(
                    "coco_to_tm supports uncompressed-RLE segmentations only (dict with a"
                    " list `counts`); compressed strings and polygons need pycocotools."
                )
            h, w = seg["size"]
            flat = np.repeat(
                np.arange(len(seg["counts"])) % 2, np.asarray(seg["counts"], np.int64)
            ).astype(bool)
            return flat.reshape(w, h).T  # column-major, like COCO

        def group(anns, with_scores):
            by_img: Dict[int, Dict[str, list]] = {
                i: {"labels": [], "scores": [], "iscrowd": [], "area": [], "boxes": [], "masks": []}
                for i in image_ids
            }
            for a in anns:
                entry = by_img[a["image_id"]]
                entry["labels"].append(a["category_id"])
                if with_scores:
                    entry["scores"].append(a["score"])
                else:
                    entry["iscrowd"].append(a.get("iscrowd", 0))
                    entry["area"].append(a.get("area", 0))
                if iou_type == "bbox":
                    entry["boxes"].append(a["bbox"])
                else:
                    entry["masks"].append(decode_mask(a))
            out = []
            for img_id in image_ids:
                e = by_img[img_id]
                d = {"labels": jnp.asarray(np.asarray(e["labels"], np.int64))}
                if iou_type == "bbox":
                    d["boxes"] = jnp.asarray(np.asarray(e["boxes"], np.float32).reshape(-1, 4))
                else:
                    d["masks"] = jnp.asarray(np.stack(e["masks"]) if e["masks"] else np.zeros((0, 0, 0), bool))
                if with_scores:
                    d["scores"] = jnp.asarray(np.asarray(e["scores"], np.float32))
                else:
                    d["iscrowd"] = jnp.asarray(np.asarray(e["iscrowd"], np.int64))
                    d["area"] = jnp.asarray(np.asarray(e["area"], np.float32))
                out.append(d)
            return out

        return group(dt_anns, True), group(gt_anns, False)

    def tm_to_coco(self, name: str = "tm_map_input") -> None:
        """Dump the accumulated state as COCO-format json
        (``{name}_preds.json`` / ``{name}_target.json``; reference
        mean_ap.py:721-792)."""
        import json

        if "bbox" not in self._iou_types:
            raise NotImplementedError(
                "tm_to_coco currently exports bbox states (segm export needs a compressed-RLE"
                " writer to be readable by pycocotools)."
            )
        if len(self.det_rows) or len(self.gt_rows):
            raise NotImplementedError(
                "tm_to_coco exports the per-image list states; this metric holds packed"
                " (dense-update) rows.  Use the list-of-dicts update layout for COCO export."
            )
        dcounts = np.concatenate([np.asarray(c) for c in self.detection_counts]).astype(int) if self.detection_counts else np.zeros(0, int)
        gcounts = np.concatenate([np.asarray(c) for c in self.groundtruth_counts]).astype(int) if self.groundtruth_counts else np.zeros(0, int)

        def xywh(b):
            b = np.asarray(b, np.float64).reshape(-1, 4)
            return np.stack([b[:, 0], b[:, 1], b[:, 2] - b[:, 0], b[:, 3] - b[:, 1]], 1)

        images = [{"id": i} for i in range(len(gcounts))]
        ann_id = 1
        target_anns = []
        for img, (boxes, labels, crowds, areas) in enumerate(
            zip(self.groundtruth_boxes, self.groundtruth_labels, self.groundtruth_crowds, self.groundtruth_area)
        ):
            for b, lab, c, a in zip(xywh(self._convert_boxes_host(np.asarray(boxes))),
                                    np.asarray(labels).reshape(-1),
                                    np.asarray(crowds).reshape(-1),
                                    np.asarray(areas).reshape(-1)):
                target_anns.append({
                    "id": ann_id, "image_id": img, "bbox": [float(v) for v in b],
                    "area": float(a) if a > 0 else float(b[2] * b[3]),
                    "category_id": int(lab), "iscrowd": int(c),
                })
                ann_id += 1
        pred_anns = []
        ann_id = 1
        for img, (boxes, labels, scores) in enumerate(
            zip(self.detection_boxes, self.detection_labels, self.detection_scores)
        ):
            for b, lab, s in zip(xywh(self._convert_boxes_host(np.asarray(boxes))),
                                 np.asarray(labels).reshape(-1), np.asarray(scores).reshape(-1)):
                pred_anns.append({
                    "id": ann_id, "image_id": img, "bbox": [float(v) for v in b],
                    "area": float(b[2] * b[3]), "category_id": int(lab), "score": float(s),
                })
                ann_id += 1
        classes = sorted({a["category_id"] for a in target_anns + pred_anns})
        target_dataset = {"images": images, "annotations": target_anns,
                          "categories": [{"id": c, "name": str(c)} for c in classes]}
        with open(f"{name}_preds.json", "w") as fh:
            json.dump(pred_anns, fh, indent=4)
        with open(f"{name}_target.json", "w") as fh:
            json.dump(target_dataset, fh, indent=4)

    def _convert_boxes_host(self, boxes: np.ndarray) -> np.ndarray:
        """Convert to xyxy on host (box_format conversion is 6 flops/box —
        never worth a device round trip).

        Bit-faithful to the reference's primary path: boxes pass through
        float32 xywh (reference mean_ap.py:803-812 ``box_convert(...,
        out_fmt='xywh')`` on f32 tensors) and the xyxy extents are rebuilt in
        float64 as ``x + w`` — exactly what pycocotools' double-precision IoU
        sees.  Skipping the f32 xywh rounding shifts IoUs by ~1e-8, enough to
        flip matches that land on an IoU threshold."""
        b = np.asarray(boxes, np.float32).reshape(-1, 4)
        if b.size:
            if self.box_format == "xyxy":
                xywh = np.stack([b[:, 0], b[:, 1], b[:, 2] - b[:, 0], b[:, 3] - b[:, 1]], axis=1)
            elif self.box_format == "xywh":
                xywh = b
            else:  # cxcywh
                xywh = np.stack(
                    [b[:, 0] - b[:, 2] / 2, b[:, 1] - b[:, 3] / 2, b[:, 2], b[:, 3]], axis=1
                )
            xywh = xywh.astype(np.float32)
            x, y, w, h = (xywh[:, i].astype(np.float64) for i in range(4))
            return np.stack([x, y, x + w, y + h], axis=1)
        return b.astype(np.float64)

    def _convert_boxes_host_batched(self, boxes_list, counts) -> List[np.ndarray]:
        """Per-image box conversion as ONE concat-convert-split: the
        conversion is elementwise per row, so converting the concatenation
        bit-identically equals converting each image — at O(1) numpy
        dispatches instead of O(images)."""
        flat = self._convert_boxes_host(
            np.concatenate([np.asarray(b, np.float32).reshape(-1, 4) for b in boxes_list])
            if boxes_list
            else np.zeros((0, 4), np.float32)
        )
        return np.split(flat, np.cumsum(np.asarray(counts, np.int64))[:-1])

    def _unpack_mask_geoms(self, dcounts, gcounts):
        """Rebuild per-image ``((h, w), [runs per mask])`` geometries from the
        host-side run state (the inverse of :meth:`_append_masks`)."""
        d_runs_flat = np.concatenate(self.detection_mask_runs) if self.detection_mask_runs else np.zeros(0, np.int32)
        d_nruns = np.concatenate(self.detection_mask_nruns) if self.detection_mask_nruns else np.zeros(0, np.int32)
        g_runs_flat = (
            np.concatenate(self.groundtruth_mask_runs) if self.groundtruth_mask_runs else np.zeros(0, np.int32)
        )
        g_nruns = np.concatenate(self.groundtruth_mask_nruns) if self.groundtruth_mask_nruns else np.zeros(0, np.int32)
        sizes = np.concatenate(self.mask_sizes).reshape(-1, 2)
        d_masks = np.split(d_runs_flat, np.cumsum(d_nruns)[:-1]) if d_nruns.size else []
        g_masks = np.split(g_runs_flat, np.cumsum(g_nruns)[:-1]) if g_nruns.size else []
        det_geoms, gt_geoms = [], []
        d_pos = g_pos = 0
        for i in range(len(dcounts)):
            h, w = int(sizes[i, 0]), int(sizes[i, 1])
            dc, gc = int(dcounts[i]), int(gcounts[i])
            det_geoms.append(((h, w), d_masks[d_pos : d_pos + dc]))
            gt_geoms.append(((h, w), g_masks[g_pos : g_pos + gc]))
            d_pos += dc
            g_pos += gc
        return det_geoms, gt_geoms

    def _append_masks(self, preds, target) -> None:
        """RLE-encode one batch of instance masks and append flat run state.

        Encoding happens on host (the masks' run structure is data-dependent);
        the stored state is four flat int32 arrays + a sizes array per update
        — NOT python objects — so cross-replica merge uses the same
        concatenation path as every other ragged state (the reference keeps
        RLE tuples on CPU and needs ``all_gather_object``, ref
        mean_ap.py:994-1024).  The runs stay host-resident: they were just
        computed on host, compute reads them on host, and a device round trip
        each way would buy nothing."""
        # ONE batched host fetch for every mask stack in the update
        # (device->host round trips dominate on remote chips), then validate
        # everything BEFORE the first state append so a bad input can't leave
        # the metric with half-appended, misaligned state
        pred_masks, target_masks = jax.device_get(
            ([p["masks"] for p in preds], [t["masks"] for t in target])
        )
        pred_masks = [np.asarray(m).astype(bool) for m in pred_masks]
        target_masks = [np.asarray(m).astype(bool) for m in target_masks]
        sizes = []
        for i, (pm, tm) in enumerate(zip(pred_masks, target_masks)):
            for side, m in (("preds", pm), ("target", tm)):
                # non-3D is only acceptable as a fully-empty stack with a zero
                # leading dim — e.g. shape (2, 0) would record 2 detections in
                # the counts but encode 0 masks, corrupting downstream state
                if m.ndim != 3 and (m.ndim == 0 or m.shape[0] != 0):
                    raise ValueError(
                        f"Expected `masks` of sample {i} in {side} to have shape (num_masks, H, W),"
                        f" but got {m.shape}"
                    )
            ph, pw = (pm.shape[-2], pm.shape[-1]) if pm.ndim == 3 and pm.shape[0] else (0, 0)
            th, tw = (tm.shape[-2], tm.shape[-1]) if tm.ndim == 3 and tm.shape[0] else (0, 0)
            if ph and th and (ph, pw) != (th, tw):
                raise ValueError(
                    f"Prediction and target masks of one image have different sizes: {(ph, pw)} vs {(th, tw)}"
                )
            sizes.append((max(ph, th), max(pw, tw)))

        staged = []  # encode everything first; append states only on success
        for stacks in (pred_masks, target_masks):
            flats, nruns = [], []
            for masks in stacks:
                if masks.ndim != 3:
                    masks = masks.reshape((0, 0, 0))
                f, n = _rle_encode_batch(masks)
                flats.append(f)
                nruns.append(n)
            staged.append(
                (
                    np.concatenate(flats) if flats else np.zeros(0, np.int32),
                    np.concatenate(nruns) if nruns else np.zeros(0, np.int32),
                )
            )
        self.mask_sizes.append(np.asarray(sizes, np.int32).reshape(-1, 2))
        self.detection_mask_runs.append(staged[0][0])
        self.detection_mask_nruns.append(staged[0][1])
        self.groundtruth_mask_runs.append(staged[1][0])
        self.groundtruth_mask_nruns.append(staged[1][1])

    def compute(self) -> Dict[str, Array]:
        """Run the COCO protocol over the accumulated images.

        All device-resident pieces of the state (boxes/scores/labels/...,
        appended raw by ``update``) are packed by ONE jitted
        ravel-cast-concatenate and fetched with ONE transfer — on a
        remote-attached accelerator every eager dispatch and every fetch is a
        full network round trip, so the round-trip count, not bytes, is the
        cost.  Host-resident pieces (numpy inputs, placeholder zeros, RLE
        runs) never touch the device.  Per-image boundaries come from the
        host-side counts."""
        types = self._iou_types
        self._check_packed_overflow()
        if self.detection_counts:
            dcounts = np.concatenate([np.asarray(c) for c in self.detection_counts]).astype(np.int64)
            gcounts = np.concatenate([np.asarray(c) for c in self.groundtruth_counts]).astype(np.int64)
        else:
            dcounts = np.zeros(0, np.int64)
            gcounts = np.zeros(0, np.int64)
        num_list = len(dcounts)

        # every device-resident piece — list-path states AND packed row
        # chunks AND the packed image counter — rides the ONE pack + transfer
        packed_det_pieces = list(self.det_rows) if "bbox" in types else []
        packed_gt_pieces = list(self.gt_rows) if "bbox" in types else []
        geom_pieces = (self.detection_boxes + self.groundtruth_boxes) if "bbox" in types else []
        fetched = _fetch_pieces(
            list(self.detection_scores)
            + list(self.detection_labels)
            + list(self.groundtruth_labels)
            + list(self.groundtruth_crowds)
            + list(self.groundtruth_area)
            + list(geom_pieces)
            + packed_det_pieces
            + packed_gt_pieces
            + ([jnp.asarray(self.packed_imgs)] if "bbox" in types else [])
        )
        pos = 0

        def take(n):
            nonlocal pos
            out = fetched[pos : pos + n]
            pos += n
            return out

        det_scores = [s.reshape(-1).astype(np.float32) for s in take(num_list)]
        det_labels = [lab.reshape(-1).astype(np.int64) for lab in take(num_list)]
        gt_labels = [lab.reshape(-1).astype(np.int64) for lab in take(num_list)]
        gt_crowds = [c.reshape(-1).astype(np.int64) for c in take(num_list)]
        gt_area = [a.reshape(-1).astype(np.float32) for a in take(num_list)]
        geoms_by_type: Dict[str, tuple] = {}
        n_packed = 0
        direct_bbox = None  # (result, class_ids) from the packed-only fast path
        if "bbox" in types:
            det_boxes_raw: List[np.ndarray] = [b.reshape(-1, 4) for b in take(num_list)]
            gt_boxes_raw: List[np.ndarray] = [b.reshape(-1, 4) for b in take(num_list)]
            det_chunks = [p.reshape(-1, _DET_COLS) for p in take(len(packed_det_pieces))]
            gt_chunks = [p.reshape(-1, _GT_COLS) for p in take(len(packed_gt_pieces))]
            # one update = one chunk: ids must strictly increase across chunk
            # boundaries, or per-rank id spaces were cat-merged (see helper)
            _check_packed_chunk_order(det_chunks)
            _check_packed_chunk_order(gt_chunks)
            det_flat = (
                np.concatenate(det_chunks) if det_chunks else np.zeros((0, _DET_COLS), np.float32)
            )
            gt_flat = (
                np.concatenate(gt_chunks) if gt_chunks else np.zeros((0, _GT_COLS), np.float32)
            )
            n_packed = int(round(float(take(1)[0].reshape(()))))
            if n_packed > 2**24:
                raise TPUMetricsUserError(
                    f"Packed detection state describes {n_packed} images, past the "
                    "2^24 exact-integer range of the float32 image-id column — ids "
                    "would alias and mAP would be silently wrong.  Compute/reset in "
                    "smaller windows, or use the list-of-dicts layout."
                )
            if n_packed and not num_list and not self.extended_summary and not (
                self.class_metrics and self.average == "micro"
            ):
                # packed-only fast path: the state already IS the flat
                # rows-plus-segment-ids layout the jitted matcher consumes —
                # skip the per-image split/re-concatenate detour entirely
                # (O(images) small-array churn per compute); a declined jit
                # path falls through to the per-image route below
                direct_bbox = self._evaluate_packed_rows(det_flat, gt_flat, n_packed)
            if direct_bbox is None:
                if n_packed or det_flat.size or gt_flat.size:
                    d_per, extra_d = _split_packed_rows(det_flat, n_packed, label_col=5)
                    g_per, extra_g = _split_packed_rows(gt_flat, n_packed, label_col=4)
                    for rows in d_per:
                        det_boxes_raw.append(rows[:, :4])
                        det_scores.append(rows[:, 4].astype(np.float32))
                        det_labels.append(np.rint(rows[:, 5]).astype(np.int64))
                    for rows in g_per:
                        gt_boxes_raw.append(rows[:, :4])
                        gt_labels.append(np.rint(rows[:, 4]).astype(np.int64))
                        gt_crowds.append(np.rint(rows[:, 5]).astype(np.int64))
                        gt_area.append(rows[:, 6].astype(np.float32))
                    dcounts = np.concatenate([dcounts, extra_d])
                    gcounts = np.concatenate([gcounts, extra_g])
                geoms_by_type["bbox"] = (
                    self._convert_boxes_host_batched(det_boxes_raw, dcounts),
                    self._convert_boxes_host_batched(gt_boxes_raw, gcounts),
                )
            else:
                geoms_by_type["bbox"] = ([], [])  # evaluation already done
        if "segm" in types:
            geoms_by_type["segm"] = (
                self._unpack_mask_geoms(dcounts, gcounts) if len(dcounts) else ([], [])
            )
        num_imgs = num_list + n_packed
        if direct_bbox is not None:
            class_ids = direct_bbox[1]
        else:
            all_labels = det_labels + gt_labels
            class_ids = (
                sorted(np.unique(np.concatenate(all_labels)).astype(int).tolist())
                if all_labels else []
            )

        max_det = self.max_detection_thresholds[-1]
        # staged on host, shipped to device by ONE device_put at the end —
        # on a remote-attached accelerator each per-key jnp.asarray would be
        # its own round trip (~16 of them), the batched put is one
        staged: Dict[str, Any] = {}
        np_only: Dict[str, Any] = {}
        for i_type in types:
            # prefix outputs only when evaluating both geometries at once,
            # like the reference (mean_ap.py:508)
            prefix = "" if len(types) == 1 else f"{i_type}_"
            if i_type == "bbox" and direct_bbox is not None:
                # the jitted matcher already consumed the flat rows; no
                # per-image tuples exist (and none are needed: the micro
                # per-class recompute is excluded from the direct path)
                detections, groundtruths = [], []
                result, geom_cache = direct_bbox[0], None
            else:
                det_geoms, gt_geoms = geoms_by_type[i_type]
                detections = [(det_geoms[i], det_scores[i], det_labels[i]) for i in range(num_imgs)]
                groundtruths = [
                    (gt_geoms[i], gt_labels[i], gt_crowds[i], gt_area[i]) for i in range(num_imgs)
                ]
                result, geom_cache = self._evaluate(
                    detections, groundtruths, class_ids, i_type, self.average, None,
                    extended=self.extended_summary,
                )
            if self.extended_summary:
                # reference mean_ap.py:525-536: score-sorted (image, class)
                # IoU matrices + the raw precision/recall tensors over
                # (T, R, K, A, M).  The IoU dict stays numpy: it is
                # host-produced diagnostics, and device_put-ing
                # O(images x classes) tiny matrices would pay one transfer
                # round trip each
                np_only[f"{prefix}ious"] = {k: np.asarray(v, np.float32) for k, v in result["ious"].items()}
                staged[f"{prefix}precision"] = np.asarray(result["precision"])
                staged[f"{prefix}recall"] = np.asarray(result["recall"])
            for key in (
                "map",
                "map_50",
                "map_75",
                "map_small",
                "map_medium",
                "map_large",
                "mar_small",
                "mar_medium",
                "mar_large",
                *(f"mar_{m}" for m in self.max_detection_thresholds),
            ):
                staged[f"{prefix}{key}"] = np.asarray(result[key])
            self._add_per_class(staged, prefix, result, detections, groundtruths, class_ids, i_type, geom_cache, max_det)
        staged["classes"] = np.asarray(class_ids, np.int32) if class_ids else np.zeros(0, np.int32)
        out: Dict[str, Array] = jax.device_put(staged)
        out.update(np_only)
        return out

    def _evaluate(
        self, detections, groundtruths, class_ids, i_type, average, geom_cache, extended=False
    ):
        """Route one COCO evaluation: the jitted dense-cell matcher
        (:func:`~tpumetrics.detection._coco_eval_jax.coco_evaluate_jit`)
        when it applies — bbox, non-extended, in budget — else the batched
        numpy path.  Returns ``(result, geom_cache)``; the cache is only
        materialized when a numpy evaluation actually needs it, so the jit
        hot path never pays the per-image intersection precompute."""
        if not extended and i_type == "bbox":
            result = coco_evaluate_jit(
                detections,
                groundtruths,
                self.iou_thresholds,
                self.rec_thresholds,
                self.max_detection_thresholds,
                class_ids,
                average=average,
                iou_type=i_type,
            )
            if result is not None:
                return result, geom_cache
        if geom_cache is None:
            # pay the geometry cost (mask decode + intersections) once,
            # shared by the optional second macro evaluation
            geom_cache = precompute_geometries(detections, groundtruths, i_type)
        result = coco_evaluate(
            detections,
            groundtruths,
            self.iou_thresholds,
            self.rec_thresholds,
            self.max_detection_thresholds,
            class_ids,
            average=average,
            iou_type=i_type,
            geom_cache=geom_cache,
            extended=extended,
        )
        return result, geom_cache

    def _evaluate_packed_rows(self, det_flat, gt_flat, n_packed):
        """Packed-only fast path: run the jitted matcher straight off the
        flat row layout (validated + sentinel-filtered, boxes converted in
        ONE vectorized pass) — no per-image split/re-concatenate detour.
        Returns ``(result, class_ids)`` or ``None`` when the jitted path
        declines (the caller then builds the per-image form and falls back).
        """
        from tpumetrics.detection._coco_eval_jax import coco_evaluate_rows

        d_rows, d_img = _filter_packed_rows(det_flat, n_packed, label_col=5)
        g_rows, g_img = _filter_packed_rows(gt_flat, n_packed, label_col=4)
        d_labels = np.rint(d_rows[:, 5]).astype(np.int64)
        g_labels = np.rint(g_rows[:, 4]).astype(np.int64)
        cat = np.concatenate([d_labels, g_labels])
        class_ids = sorted(np.unique(cat).astype(int).tolist()) if cat.size else []
        result = coco_evaluate_rows(
            (
                self._convert_boxes_host(d_rows[:, :4]),
                d_rows[:, 4].astype(np.float32),
                d_labels,
                d_img,
            ),
            (
                self._convert_boxes_host(g_rows[:, :4]),
                g_labels,
                np.rint(g_rows[:, 5]).astype(np.int64),
                g_rows[:, 6].astype(np.float64),
                g_img,
            ),
            n_packed,
            self.iou_thresholds,
            self.rec_thresholds,
            self.max_detection_thresholds,
            class_ids,
            average=self.average,
        )
        return None if result is None else (result, class_ids)

    def _sync_state_collect_inner(self, state, backend, reducer, group, out, pending):
        """Refuse a cross-rank eager sync while packed rows exist: a generic
        cat-merge would concatenate independent per-rank image-id spaces,
        which is semantically wrong (and the compacted-buffer form can make
        the collision undetectable after the fact).  Multi-rank packed
        detection belongs to the ONE-global-program GSPMD path; eager DDP
        uses the list-of-dicts layout."""
        try:
            world = int(backend.world_size())
        except Exception:
            world = 1
        if world > 1 and "bbox" in self._iou_types and self._packed_rows_present(state):
            raise TPUMetricsUserError(
                "Packed detection state cannot sync across eager ranks: per-rank "
                "image-id spaces would collide in the cat-merge.  Use the "
                "list-of-dicts update layout for eager DDP, or run the packed "
                "layout as ONE global program on a GSPMD mesh."
            )
        return super()._sync_state_collect_inner(state, backend, reducer, group, out, pending)

    @staticmethod
    def _packed_rows_present(state) -> bool:
        from tpumetrics.buffers import MaskedBuffer, _BufferList
        from tpumetrics.utils.data import _is_tracer

        for name in ("det_rows", "gt_rows"):
            val = state.get(name)
            if isinstance(val, _BufferList):
                val = val.buffer
            if isinstance(val, MaskedBuffer):
                if _is_tracer(val.count):
                    return True  # in-trace: emptiness unknowable — be strict
                if int(val.count) > 0:  # eager sync context: host read is fine
                    return True
            elif isinstance(val, list) and val:
                return True
        return False

    def _check_packed_overflow(self) -> None:
        """A packed MaskedBuffer that dropped rows must fail loudly: mAP over
        silently truncated detections is a wrong number, not an estimate."""
        from tpumetrics.buffers import _BufferList, buffer_overflowed

        if "bbox" not in self._iou_types:
            return
        for name in ("det_rows", "gt_rows"):
            val = getattr(self, name)
            if isinstance(val, _BufferList) and bool(buffer_overflowed(val.buffer)):
                raise TPUMetricsUserError(
                    f"Packed detection state {name!r} overflowed its declared "
                    f"capacity {val.buffer.capacity} ({int(val.buffer.requested)} rows "
                    "requested) — rows were dropped and mAP would be silently "
                    "wrong.  Raise `det_capacity`/`gt_capacity`."
                )

    def _add_per_class(self, out, prefix, result, detections, groundtruths, class_ids, i_type, geom_cache, max_det):
        """Per-class map/mar entries for one iou type (reference mean_ap.py:538-570)."""
        if self.class_metrics:
            if self.average == "micro":
                # micro pools classes for the global scores, but per-class
                # values only make sense macro-style (reference mean_ap.py
                # recomputes them with average="macro"), keeping
                # map_per_class aligned with the observed `classes`
                per_class, _cache = self._evaluate(
                    detections, groundtruths, class_ids, i_type, "macro", geom_cache
                )
            else:
                per_class = result
            out[f"{prefix}map_per_class"] = np.asarray(per_class["map_per_class"])
            out[f"{prefix}mar_{max_det}_per_class"] = np.asarray(per_class["mar_per_class"])
        else:
            out[f"{prefix}map_per_class"] = np.asarray(-1.0, np.float32)
            out[f"{prefix}mar_{max_det}_per_class"] = np.asarray(-1.0, np.float32)
