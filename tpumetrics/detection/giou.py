"""GeneralizedIntersectionOverUnion (counterpart of reference ``detection/giou.py``)."""

from __future__ import annotations

from typing import Callable

from tpumetrics.detection.iou import IntersectionOverUnion
from tpumetrics.functional.detection.giou import _giou_compute, _giou_update


class GeneralizedIntersectionOverUnion(IntersectionOverUnion):
    """GIoU accumulated over batches (reference detection/giou.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.detection import GeneralizedIntersectionOverUnion
        >>> preds = [dict(boxes=jnp.asarray([[296.55, 93.96, 314.97, 152.79]]), labels=jnp.asarray([4]))]
        >>> target = [dict(boxes=jnp.asarray([[300.00, 100.00, 315.00, 150.00]]), labels=jnp.asarray([4]))]
        >>> metric = GeneralizedIntersectionOverUnion()
        >>> metric.update(preds, target)
        >>> round(float(metric.compute()["giou"]), 4)
        0.6895
    """

    _iou_type: str = "giou"
    _invalid_val: float = -1.0

    _iou_update_fn: Callable = staticmethod(_giou_update)
    _iou_compute_fn: Callable = staticmethod(_giou_compute)
