"""DistanceIntersectionOverUnion (counterpart of reference ``detection/diou.py``)."""

from __future__ import annotations

from typing import Callable

from tpumetrics.detection.iou import IntersectionOverUnion
from tpumetrics.functional.detection.diou import _diou_compute, _diou_update


class DistanceIntersectionOverUnion(IntersectionOverUnion):
    """DIoU accumulated over batches (reference detection/diou.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.detection import DistanceIntersectionOverUnion
        >>> preds = [dict(boxes=jnp.asarray([[296.55, 93.96, 314.97, 152.79]]), labels=jnp.asarray([4]))]
        >>> target = [dict(boxes=jnp.asarray([[300.00, 100.00, 315.00, 150.00]]), labels=jnp.asarray([4]))]
        >>> metric = DistanceIntersectionOverUnion()
        >>> metric.update(preds, target)
        >>> round(float(metric.compute()["diou"]), 4)
        0.6883
    """

    _iou_type: str = "diou"
    _invalid_val: float = -1.0

    _iou_update_fn: Callable = staticmethod(_diou_update)
    _iou_compute_fn: Callable = staticmethod(_diou_compute)
