"""COCO-faithful detection evaluation in vectorized numpy.

A from-scratch reimplementation of the COCO mAP protocol (the semantics of
pycocotools' ``COCOeval``, which the reference shells out to on CPU from
``detection/mean_ap.py:501``; the reference's pure-torch blueprint is
``detection/_mean_ap.py``):

- IoU thresholds 0.50:0.05:0.95, recall thresholds 0:0.01:1 (101 points),
  max-detection caps (1, 10, 100), area ranges all/small/medium/large;
- per (class, image): detections sorted by score, greedily matched to the
  not-yet-matched ground truth with the highest IoU above the threshold;
  crowd ground truths may match many detections and use a detection-area
  union (``iscrowd`` semantics); ignored ground truths (crowd or
  out-of-area-range) absorb matches without counting;
- accumulation: detections merged across images per class, re-sorted by
  score, TP/FP cumsums over non-ignored entries, precision made monotone
  from the right, sampled at the recall thresholds.

Everything after the per-image matching is dense numpy (the matching itself
is a data-dependent greedy loop, which is why — like the reference — this
runs on host at ``compute`` time; states stay on device until then).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

_AREA_RANGES = {
    "all": (0.0, 1e10),
    "small": (0.0, 32.0**2),
    "medium": (32.0**2, 96.0**2),
    "large": (96.0**2, 1e10),
}


def rle_decode_flat(runs: np.ndarray, num_pixels: int) -> np.ndarray:
    """Decode column-major RLE runs (alternating 0s/1s, leading 0-run) to a
    flat (num_pixels,) uint8 vector."""
    runs = np.asarray(runs, dtype=np.int64)
    vals = np.zeros(runs.shape[0], dtype=np.uint8)
    vals[1::2] = 1
    flat = np.repeat(vals, runs)
    if flat.shape[0] != num_pixels:
        raise ValueError(f"RLE decodes to {flat.shape[0]} pixels, expected {num_pixels}")
    return flat


def _pairwise_geometry(
    det_geom, gt_geom, iou_type: str
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Precompute class-independent pairwise pieces for one image: the
    intersection matrix (D, G) and the per-item geometry areas.

    For ``bbox`` the geometry is an xyxy (N, 4) array; for ``segm`` it is
    ``((h, w), [runs, ...])`` — column-major RLE runs per mask.  Masks are
    decoded once per image and intersected with ONE (D, HW) x (HW, G)
    matmul, so the per-class loop below only slices — the pycocotools
    equivalent recomputes ``maskUtils.iou`` per (image, category).
    """
    if iou_type == "bbox":
        det, gt = det_geom, gt_geom
        det_area = (det[:, 2] - det[:, 0]) * (det[:, 3] - det[:, 1]) if det.size else np.zeros(det.shape[0])
        gt_area = (gt[:, 2] - gt[:, 0]) * (gt[:, 3] - gt[:, 1]) if gt.size else np.zeros(gt.shape[0])
        if det.shape[0] == 0 or gt.shape[0] == 0:
            inter = np.zeros((det.shape[0], gt.shape[0]))
        else:
            lt = np.maximum(det[:, None, :2], gt[None, :, :2])
            rb = np.minimum(det[:, None, 2:], gt[None, :, 2:])
            wh = np.clip(rb - lt, 0, None)
            inter = (wh[..., 0] * wh[..., 1]).astype(np.float64)
        return inter, np.asarray(det_area, np.float64), np.asarray(gt_area, np.float64)

    (h, w), det_runs = det_geom
    _, gt_runs = gt_geom
    num_px = h * w
    det_area = np.array([float(np.asarray(r, np.int64)[1::2].sum()) for r in det_runs])
    gt_area = np.array([float(np.asarray(r, np.int64)[1::2].sum()) for r in gt_runs])
    if len(det_runs) == 0 or len(gt_runs) == 0:
        return np.zeros((len(det_runs), len(gt_runs))), det_area, gt_area
    # decode to uint8 and matmul in float32, chunked over detections: f32 is
    # exact for pixel counts < 2^24 (any mask below 16.7 Mpx) at half the
    # float64 footprint, and chunking bounds the peak to the gt matrix plus
    # one chunk rather than the full (D, HW) dense float block
    dmat = np.stack([rle_decode_flat(r, num_px) for r in det_runs])
    gmat32 = np.stack([rle_decode_flat(r, num_px) for r in gt_runs]).astype(np.float32).T
    inter = np.empty((dmat.shape[0], gmat32.shape[1]), dtype=np.float64)
    chunk = max(1, min(dmat.shape[0], (1 << 25) // max(num_px, 1)))  # ~128 MB f32 per chunk
    for i in range(0, dmat.shape[0], chunk):
        inter[i : i + chunk] = dmat[i : i + chunk].astype(np.float32) @ gmat32
    return inter, det_area, gt_area


def _match_image_areas(
    ious: np.ndarray,
    det_areas: np.ndarray,
    det_scores: np.ndarray,
    gt_crowd: np.ndarray,
    gt_area: np.ndarray,
    iou_thresholds: np.ndarray,
    area_ranges: Sequence[Tuple[float, float]],
    max_det: int,
) -> Optional[List[dict]]:
    """Match one (image, class) pair at every (area range, IoU threshold)
    simultaneously (pycocotools ``evaluateImg`` semantics; reference
    _mean_ap.py:521-649).

    ``ious``/``det_areas``/``det_scores`` are already score-sorted
    (descending, stable) — computed once per (image, class) by the caller.
    Only the detection loop is sequential (each det claims a gt); the per-det
    candidate search is vectorized over all (area, threshold, gt) triples —
    area ranges only change which gts are ignored, so evaluating all four in
    one pass quarters the Python-loop overhead of the hot host path.  The
    greedy rules are replicated exactly: non-ignored gts take precedence over
    ignored ones (the reference's sorted-ignored-last + break), ties replace
    (last-wins argmax), crowd gts can absorb any number of detections.
    """
    n_gt = gt_crowd.shape[0]
    n_det = min(det_scores.shape[0], max_det)
    if n_gt == 0 and n_det == 0:
        return None

    lo = np.asarray([r[0] for r in area_ranges])  # (A,)
    hi = np.asarray([r[1] for r in area_ranges])
    crowd = gt_crowd.astype(bool)
    gt_ignore = crowd[None, :] | (gt_area[None, :] < lo[:, None]) | (gt_area[None, :] > hi[:, None])  # (A, G)
    num_areas = len(area_ranges)
    num_thrs = len(iou_thresholds)
    thr = np.minimum(np.asarray(iou_thresholds)[None, :, None], 1 - 1e-10)  # (1, T, 1)
    det_matches = np.zeros((num_areas, num_thrs, n_det), dtype=np.int64)  # 1 if matched
    det_ignore = np.zeros((num_areas, num_thrs, n_det), dtype=bool)
    avail = np.ones((num_areas, num_thrs, n_gt), dtype=bool)  # gt not yet claimed
    ious = ious[:n_det]
    real = ~gt_ignore

    for d_idx in range(n_det):
        iou_row = ious[d_idx][None, None, :]  # (1, 1, G)
        cand = avail & (iou_row >= thr)  # (A, T, G)
        cand_real = cand & real[:, None, :]
        use_real = cand_real.any(axis=2)
        pick_from = np.where(use_real[..., None], cand_real, cand & gt_ignore[:, None, :])
        has = pick_from.any(axis=2)
        if not has.any():
            continue
        vals = np.where(pick_from, iou_row, -1.0)
        best_g = n_gt - 1 - np.argmax(vals[..., ::-1], axis=2)  # last-wins argmax
        rows_a, rows_t = np.nonzero(has)
        bg = best_g[rows_a, rows_t]
        det_matches[rows_a, rows_t, d_idx] = 1
        det_ignore[rows_a, rows_t, d_idx] = gt_ignore[rows_a, bg]
        noncrowd = ~crowd[bg]
        avail[rows_a[noncrowd], rows_t[noncrowd], bg[noncrowd]] = False

    # unmatched detections outside the area range are ignored
    da = det_areas[:n_det]
    det_out_of_range = (da[None, :] < lo[:, None]) | (da[None, :] > hi[:, None])  # (A, D)
    det_ignore = det_ignore | ((det_matches == 0) & det_out_of_range[:, None, :])

    scores = det_scores[:n_det]
    return [
        {
            "det_scores": scores,
            "det_matches": det_matches[a],
            "det_ignore": det_ignore[a],
            "num_gt": int((~gt_ignore[a]).sum()),
        }
        for a in range(num_areas)
    ]




def _accumulate_class_area(
    results: List[Optional[dict]], num_thrs: int, rec_thresholds: np.ndarray, max_det: Optional[int] = None
) -> Tuple[np.ndarray, np.ndarray]:
    """Merge per-image matchings of one (class, area, maxdet) cell into
    precision-at-recall-thresholds and best recall (pycocotools
    ``accumulate``; reference _mean_ap.py:696-782).

    ``max_det`` slices each image's (already score-sorted) detections, so the
    greedy matching runs once per (class, area) at the largest cap and is
    reused for the smaller ones — pycocotools does the same."""
    results = [r for r in results if r is not None]
    num_rec = len(rec_thresholds)
    precision = -np.ones((num_thrs, num_rec))
    recall = -np.ones(num_thrs)
    if not results:
        return precision, recall

    m = max_det if max_det is not None else max(r["det_scores"].shape[0] for r in results)
    scores = np.concatenate([r["det_scores"][:m] for r in results])
    matches = np.concatenate([r["det_matches"][:, :m] for r in results], axis=1)
    ignore = np.concatenate([r["det_ignore"][:, :m] for r in results], axis=1)
    npig = sum(r["num_gt"] for r in results)
    if npig == 0:
        return precision, recall

    order = np.argsort(-scores, kind="mergesort")
    matches = matches[:, order]
    ignore = ignore[:, order]

    tps = np.logical_and(matches, ~ignore)
    fps = np.logical_and(~matches.astype(bool), ~ignore)
    tp_sum = np.cumsum(tps, axis=1).astype(np.float64)
    fp_sum = np.cumsum(fps, axis=1).astype(np.float64)

    for t_idx in range(num_thrs):
        tp = tp_sum[t_idx]
        fp = fp_sum[t_idx]
        nd = len(tp)
        rc = tp / npig
        pr = tp / np.maximum(fp + tp, np.finfo(np.float64).eps)
        recall[t_idx] = rc[-1] if nd else 0.0

        # monotone precision envelope from the right (pycocotools loop)
        pr = np.maximum.accumulate(pr[::-1])[::-1]
        inds = np.searchsorted(rc, rec_thresholds, side="left")
        q = np.zeros(num_rec)
        valid = inds < nd
        q[valid] = pr[inds[valid]]
        precision[t_idx] = q
    return precision, recall


def precompute_geometries(
    detections: Sequence[Tuple],
    groundtruths: Sequence[Tuple],
    iou_type: str,
) -> List[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Class-independent pairwise geometry, ONCE per image (intersections +
    areas); the per-class loop in :func:`coco_evaluate` only slices these.
    pycocotools recomputes IoU per (image, category) — for masks that means
    re-decoding RLEs K times; here each mask is decoded once and intersected
    by one matmul."""
    return [
        _pairwise_geometry(detections[img][0], groundtruths[img][0], iou_type)
        for img in range(len(detections))
    ]


def coco_evaluate(
    detections: Sequence[Tuple[np.ndarray, np.ndarray, np.ndarray]],
    groundtruths: Sequence[Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]],
    iou_thresholds: Sequence[float],
    rec_thresholds: Sequence[float],
    max_detection_thresholds: Sequence[int],
    class_ids: Sequence[int],
    average: str = "macro",
    iou_type: str = "bbox",
    geom_cache: Optional[List[Tuple[np.ndarray, np.ndarray, np.ndarray]]] = None,
    extended: bool = False,
) -> Dict[str, np.ndarray]:
    """Full COCO evaluation over per-image detections/groundtruths.

    Args:
        detections: per image (geometry, scores (D,), labels (D,)).
        groundtruths: per image (geometry, labels (G,), iscrowd (G,),
            area (G,) — zero entries fall back to the geometry area).
        iou_type: geometry kind — ``bbox`` (geometry = xyxy (N, 4) array) or
            ``segm`` (geometry = ``((h, w), [RLE runs per mask])``).
        class_ids: the class label space to evaluate.
        average: ``macro`` (per-class then averaged, COCO standard) or
            ``micro`` (all classes pooled into one).
        geom_cache: output of a prior :func:`precompute_geometries` call on
            the same inputs — lets a caller that evaluates twice (e.g. micro
            scores + macro per-class values) pay the mask-decode/intersection
            cost once.
    """
    iou_thrs = np.asarray(iou_thresholds, dtype=np.float64)
    rec_thrs = np.asarray(rec_thresholds, dtype=np.float64)
    max_dets = sorted(max_detection_thresholds)
    num_imgs = len(detections)

    # micro pools all classes into one evaluation bucket, but the reported
    # `classes` stay the observed ids
    eval_class_ids: Sequence[int] = [0] if average == "micro" else class_ids

    area_names = list(_AREA_RANGES)
    # precision[T, R, K, A, M], recall[T, K, A, M]
    precision = -np.ones((len(iou_thrs), len(rec_thrs), len(eval_class_ids), len(area_names), len(max_dets)))
    recall = -np.ones((len(iou_thrs), len(eval_class_ids), len(area_names), len(max_dets)))

    per_image_geom = (
        geom_cache if geom_cache is not None else precompute_geometries(detections, groundtruths, iou_type)
    )

    iou_map: Dict[Tuple[int, int], np.ndarray] = {}
    for k_idx, class_id in enumerate(eval_class_ids):
        # per (image, class): sort detections by score and compute IoUs ONCE,
        # shared across all four area ranges (pycocotools computes computeIoU
        # once per (img, cat) the same way)
        per_image_cls = []
        for img in range(num_imgs):
            _, det_scores, det_labels = detections[img]
            _, gt_labels, gt_crowd, gt_area = groundtruths[img]
            inter_full, det_area_full, gt_area_geom_full = per_image_geom[img]
            if average == "micro":
                det_sel = np.ones(det_labels.shape[0], dtype=bool)
                gt_sel = np.ones(gt_labels.shape[0], dtype=bool)
            else:
                det_sel = det_labels == class_id
                gt_sel = gt_labels == class_id
            area = gt_area[gt_sel]
            geom_area = gt_area_geom_full[gt_sel]
            area = np.where(area > 0, area, geom_area)
            ds, gc = det_scores[det_sel], gt_crowd[gt_sel]
            det_order = np.argsort(-ds, kind="stable")[: max_dets[-1]]
            ds = ds[det_order]
            da = det_area_full[det_sel][det_order]
            inter = inter_full[det_sel][:, gt_sel][det_order]
            union = da[:, None] + geom_area[None, :] - inter
            union = np.where(gc[None, :].astype(bool), da[:, None], union)
            ious = inter / np.where(union > 0, union, 1.0)
            per_image_cls.append((ious, da, ds, gc, area))
            if extended:
                iou_map[(img, int(class_id))] = ious

        # match once per image across ALL area ranges at the largest cap;
        # smaller caps reuse by slicing
        all_ranges = [_AREA_RANGES[a] for a in area_names]
        per_image_areas = [
            _match_image_areas(ious, da, ds, gc, ga, iou_thrs, all_ranges, max_dets[-1])
            for (ious, da, ds, gc, ga) in per_image_cls
        ]
        for a_idx in range(len(area_names)):
            results = [r if r is None else r[a_idx] for r in per_image_areas]
            for m_idx, max_det in enumerate(max_dets):
                prec, rec = _accumulate_class_area(results, len(iou_thrs), rec_thrs, max_det)
                precision[:, :, k_idx, a_idx, m_idx] = prec
                recall[:, k_idx, a_idx, m_idx] = rec

    def _map(thr_sel=slice(None), area="all", max_det_idx=-1, class_idx=None):
        a_idx = area_names.index(area)
        p = precision[thr_sel, :, :, a_idx, max_det_idx]
        if class_idx is not None:
            p = p[..., class_idx]
        p = p[p > -1]
        return np.float32(p.mean()) if p.size else np.float32(-1.0)

    def _mar(area="all", max_det_idx=-1, class_idx=None):
        a_idx = area_names.index(area)
        r = recall[:, :, a_idx, max_det_idx]
        if class_idx is not None:
            r = r[..., class_idx]
        r = r[r > -1]
        return np.float32(r.mean()) if r.size else np.float32(-1.0)

    thr50 = [i for i, t in enumerate(iou_thrs) if abs(t - 0.5) < 1e-9]
    thr75 = [i for i, t in enumerate(iou_thrs) if abs(t - 0.75) < 1e-9]

    out: Dict[str, np.ndarray] = {
        "map": _map(),
        "map_50": _map(thr_sel=thr50) if thr50 else np.float32(-1.0),
        "map_75": _map(thr_sel=thr75) if thr75 else np.float32(-1.0),
        "map_small": _map(area="small"),
        "map_medium": _map(area="medium"),
        "map_large": _map(area="large"),
        "mar_small": _mar(area="small"),
        "mar_medium": _mar(area="medium"),
        "mar_large": _mar(area="large"),
        "classes": np.asarray(class_ids, dtype=np.int32),
    }
    for m_idx, max_det in enumerate(max_dets):
        out[f"mar_{max_det}"] = _mar(max_det_idx=m_idx)
    out["map_per_class"] = np.asarray([_map(class_idx=k) for k in range(len(eval_class_ids))], np.float32)
    out["mar_per_class"] = np.asarray(
        [_mar(class_idx=k, max_det_idx=len(max_dets) - 1) for k in range(len(eval_class_ids))], np.float32
    )
    if extended:
        # the reference's extended_summary payload (reference mean_ap.py:525-536):
        # score-sorted per-(image, class) IoU matrices plus the raw
        # precision/recall tensors over (T, R, K, A, M) / (T, K, A, M)
        out["ious"] = iou_map
        out["precision"] = precision
        out["recall"] = recall
    return out
